"""Ulysses (all-to-all) sequence parallelism: the alternative to ring.

New TPU capability beyond the reference (single-device attention only,
reference models/gpt.py:56-69). Where ring attention keeps queries local
and rotates K/V shards around the ``sequence`` axis (ops/ring_attention.py,
one ppermute per step), Ulysses (DeepSpeed-Ulysses; see PAPERS.md)
re-shards ONCE per attention: an all-to-all swaps the sharded dimension
from sequence to heads (q/k/v stacked into one collective), every device
runs exact attention over the FULL sequence for its ``H/s`` head slice,
and a second all-to-all swaps back.

Trade-off vs ring: 2 all-to-alls per attention (one for stacked q/k/v,
one for the output) instead of ``s`` ppermutes of K/V — fewer, larger
collectives (better for small ``s`` on fast ICI) — but it needs
``local_heads % s == 0`` (heads AFTER tensor sharding), so it caps at
H-way sequence sharding while ring scales to any ``s``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .blockwise_attention import blockwise_attention
from .ring_attention import (
    _dim_shards,
    attention_shard_map,
    min_widen_factor,
    route_or_blockwise,
    widen_kv_for_shards,
)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    key_mask: jax.Array | None = None,
    *,
    axis_name: str = "sequence",
    causal: bool = True,
) -> jax.Array:
    """Local-shard Ulysses attention; must run inside shard_map.

    q/k/v: (B, T_local, H, D) shards, contiguous along the global sequence
    in axis order; ``key_mask`` is the FULL-sequence (B, T) padding mask
    (replicated over the sequence axis by the shard_map spec — the
    post-exchange attention sees the whole sequence, and replicating
    beats an all-gather per layer). Returns the (B, T_local, H, D)
    output shard.
    """
    s = jax.lax.psum(1, axis_name)
    heads = q.shape[2]
    if heads % s != 0:
        raise ValueError(
            f"ulysses needs local heads ({heads}) divisible by the "
            f"sequence axis size ({s})"
        )
    if k.shape[2] != heads:
        # Grouped-query narrow K/V: keep it narrow through the exchange
        # when its head count splits across the axis (less wire traffic —
        # the post-exchange blockwise groups queries natively); otherwise
        # widen by the smallest exact factor that divides (w=group always
        # satisfies both conditions after the heads % s check above).
        w = min_widen_factor(heads // k.shape[2], k.shape[2], s)
        if w is not None and w > 1:
            k = jnp.repeat(k, w, axis=2)
            v = jnp.repeat(v, w, axis=2)

    if k.shape[2] == heads:
        # Collective 1: device i holds sequence shard i, all local heads;
        # after the exchange it holds head-slice i for the FULL sequence,
        # shards concatenated in axis order so positions line up globally.
        # q/k/v ride one stacked all-to-all (axes shift by 1 for the
        # stack dim).
        qkv = jnp.stack((q, k, v))  # (3, B, T_local, H, D)
        qkv = jax.lax.all_to_all(
            qkv, axis_name, split_axis=3, concat_axis=2, tiled=True
        )
        qh, kh, vh = qkv[0], qkv[1], qkv[2]  # each (B, T, H/s, D)
    else:
        # Narrow K/V: q and the stacked k/v exchange separately — two
        # collectives moving H + 2*Hkv head-widths instead of one moving
        # 3*H. Fewer bytes for any group factor > 1, at the cost of one
        # extra collective's latency; taken unconditionally (unmeasured
        # on ICI — see RESULTS.md pending list).
        qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
        kv = jnp.stack((k, v))  # (2, B, T_local, Hkv, D)
        kv = jax.lax.all_to_all(
            kv, axis_name, split_axis=3, concat_axis=2, tiled=True
        )
        kh, vh = kv[0], kv[1]  # each (B, T, Hkv/s, D)

    # query_mask = key_mask: q and k cover the same full sequence after
    # the all-to-all, so segment semantics (packed cross-document
    # masking) apply directly.
    out = blockwise_attention(
        qh, kh, vh, causal=causal, key_mask=key_mask, query_mask=key_mask
    )
    # Collective 2: back to sequence-sharded, all heads local.
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: jax.sharding.Mesh,
    *,
    causal: bool = True,
    key_mask: jax.Array | None = None,
) -> jax.Array:
    """shard_map wrapper: global (B, T, H, D) arrays over the named mesh
    (same activation layout as ring — ring_attention.attention_shard_map).
    """
    k, v = widen_kv_for_shards(q, k, v, mesh)
    fn = attention_shard_map(
        mesh,
        functools.partial(ulysses_attention, axis_name="sequence", causal=causal),
        with_mask=key_mask is not None,
        mask_replicated=True,
    )
    if key_mask is not None:
        return fn(q, k, v, key_mask)
    return fn(q, k, v)


def _local_heads_divide(mesh: jax.sharding.Mesh, q: jax.Array) -> bool:
    """Ulysses' extra constraint: heads remaining after tensor sharding
    must split across the sequence axis."""
    local_heads = q.shape[2] // _dim_shards(mesh, 2)
    return local_heads % mesh.shape["sequence"] == 0


def ulysses_or_blockwise(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    key_mask: jax.Array | None = None,
) -> jax.Array:
    """Ulysses when an ambient mesh shards the sequence and local heads
    divide by the sequence degree; blockwise otherwise (shared policy:
    ring_attention.route_or_blockwise). ``key_mask`` is the reference's
    (B, T) padding mask, applied inside attention on both paths."""
    return route_or_blockwise(
        q,
        k,
        v,
        causal=causal,
        scheme="ulysses",
        sharded_fn=ulysses_attention_sharded,
        extra_predicate=_local_heads_divide,
        key_mask=key_mask,
    )


__all__ = [
    "ulysses_attention",
    "ulysses_attention_sharded",
    "ulysses_or_blockwise",
]
