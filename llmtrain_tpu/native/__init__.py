"""Native (C) runtime components, built on demand with graceful fallback.

``fastbpe`` accelerates the BPE tokenizer's cold-word merge loop
(data/bpe.py) — the dominant cost when tokenizing high-entropy corpora
(source code) where the Python per-word memo rarely hits. The shared
object is compiled once per source hash with the host C compiler into
``~/.cache/llmtrain_tpu/native/`` and loaded via ctypes; any failure
(no compiler, sandboxed filesystem) silently falls back to the pure
Python implementation, so nothing here is load-bearing for correctness.

Set ``LLMTRAIN_NO_NATIVE=1`` to force the Python paths (the equivalence
tests use it to compare both).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from pathlib import Path

_SRC = Path(__file__).with_name("fastbpe.c")
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _cache_dir() -> Path:
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return Path(root) / "llmtrain_tpu" / "native"


def _compiler() -> str | None:
    for cc in (os.environ.get("CC"), "cc", "gcc", "clang"):
        if cc and shutil.which(cc):
            return cc
    return None


def _build() -> Path | None:
    # Everything inside the try: the module contract is that ANY failure
    # (missing source in a stripped install, read-only cache dir, broken
    # compiler) means "no native encoder", never an exception.
    tmp: Path | None = None
    try:
        src = _SRC.read_bytes()
        tag = hashlib.sha256(src).hexdigest()[:16]
        out = _cache_dir() / f"fastbpe-{tag}.so"
        if out.exists():
            return out
        cc = _compiler()
        if cc is None:
            return None
        out.parent.mkdir(parents=True, exist_ok=True)
        # Per-process tmp: concurrent builders (pytest-xdist, simultaneous
        # jobs on a fresh host) must not interleave writes into one file
        # and promote a corrupt .so into the content-addressed cache.
        tmp = out.with_suffix(f".so.tmp.{os.getpid()}")
        subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        tmp.replace(out)
        return out
    except Exception:
        if tmp is not None:
            tmp.unlink(missing_ok=True)
        return None


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("LLMTRAIN_NO_NATIVE") == "1":
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
        lib.fastbpe_new.restype = ctypes.c_void_p
        lib.fastbpe_new.argtypes = [ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        lib.fastbpe_free.argtypes = [ctypes.c_void_p]
        lib.fastbpe_encode_word.restype = ctypes.c_int32
        lib.fastbpe_encode_word.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
    except OSError:
        return None
    _lib = lib
    return _lib


class FastBpeEncoder:
    """ctypes wrapper over one vocabulary's native merge table."""

    def __init__(self, lib: ctypes.CDLL, merges: list[tuple[int, int]]) -> None:
        flat = (ctypes.c_int32 * (2 * len(merges)))()
        for i, (a, b) in enumerate(merges):
            flat[2 * i] = a
            flat[2 * i + 1] = b
        self._lib = lib
        self._ctx = lib.fastbpe_new(flat, len(merges))
        if not self._ctx:
            raise MemoryError("fastbpe_new failed")

    def encode_word(self, word: str) -> list[int]:
        raw = word.encode("utf-8")
        n = len(raw)
        if n == 0:
            return []
        buf_in = (ctypes.c_uint8 * n).from_buffer_copy(raw)
        buf_out = (ctypes.c_int32 * n)()
        count = self._lib.fastbpe_encode_word(self._ctx, buf_in, n, buf_out)
        return list(buf_out[:count])

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, "_lib", None)
        ctx = getattr(self, "_ctx", None)
        if lib is not None and ctx:
            lib.fastbpe_free(ctx)


def fastbpe_encoder(merges: list[tuple[int, int]]) -> FastBpeEncoder | None:
    """A native encoder for this merge list, or None (fallback to Python)."""
    lib = _load()
    if lib is None:
        return None
    try:
        return FastBpeEncoder(lib, merges)
    except MemoryError:
        return None


__all__ = ["fastbpe_encoder", "FastBpeEncoder"]
