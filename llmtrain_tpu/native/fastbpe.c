/* Native BPE word encoder — the hot inner loop of data/bpe.py.
 *
 * The Python tokenizer keeps a per-word memo, so this accelerates COLD
 * words: high-entropy corpora (source code, many unique identifiers)
 * spend their tokenize time in the greedy lowest-rank merge loop. The
 * algorithm here is bit-identical to BPETokenizer._encode_word: repeat
 * { find the adjacent pair with the lowest merge rank; fuse it } until
 * no adjacent pair has a rank.
 *
 * Built on demand by llmtrain_tpu/native/__init__.py (cc -O2 -shared),
 * loaded via ctypes; everything degrades to the pure-Python path when no
 * compiler is available.
 *
 * Pair lookup: open-addressed hash table keyed on (a << 32) | b with
 * linear probing; sized to >= 2x the merge count rounded up to a power
 * of two, so probes are short and the table fits caches for real
 * vocabularies (tens of thousands of merges).
 */

#include <limits.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    uint64_t *keys;   /* (a << 32) | b, EMPTY when unused */
    int32_t *ranks;
    uint64_t mask;    /* table_size - 1 */
    int32_t n_merges;
} FastBpe;

static const uint64_t EMPTY = ~(uint64_t)0;

static uint64_t hash_key(uint64_t k) {
    /* splitmix64 finalizer — well-distributed for sequential ids. */
    k ^= k >> 30; k *= 0xbf58476d1ce4e5b9ULL;
    k ^= k >> 27; k *= 0x94d049bb133111ebULL;
    k ^= k >> 31;
    return k;
}

FastBpe *fastbpe_new(const int32_t *merges, int32_t n_merges) {
    uint64_t size = 16;
    while (size < (uint64_t)n_merges * 2) size <<= 1;
    FastBpe *ctx = (FastBpe *)malloc(sizeof(FastBpe));
    if (!ctx) return NULL;
    ctx->keys = (uint64_t *)malloc(size * sizeof(uint64_t));
    ctx->ranks = (int32_t *)malloc(size * sizeof(int32_t));
    if (!ctx->keys || !ctx->ranks) {
        free(ctx->keys); free(ctx->ranks); free(ctx);
        return NULL;
    }
    for (uint64_t i = 0; i < size; i++) ctx->keys[i] = EMPTY;
    ctx->mask = size - 1;
    ctx->n_merges = n_merges;
    for (int32_t r = 0; r < n_merges; r++) {
        uint64_t key = ((uint64_t)(uint32_t)merges[2 * r] << 32)
                     | (uint32_t)merges[2 * r + 1];
        uint64_t i = hash_key(key) & ctx->mask;
        while (ctx->keys[i] != EMPTY) i = (i + 1) & ctx->mask;
        ctx->keys[i] = key;
        ctx->ranks[i] = r;
    }
    return ctx;
}

void fastbpe_free(FastBpe *ctx) {
    if (!ctx) return;
    free(ctx->keys);
    free(ctx->ranks);
    free(ctx);
}

static int32_t lookup(const FastBpe *ctx, int32_t a, int32_t b) {
    uint64_t key = ((uint64_t)(uint32_t)a << 32) | (uint32_t)b;
    uint64_t i = hash_key(key) & ctx->mask;
    while (ctx->keys[i] != EMPTY) {
        if (ctx->keys[i] == key) return ctx->ranks[i];
        i = (i + 1) & ctx->mask;
    }
    return -1;
}

/* Encode one pre-tokenized word (UTF-8 bytes). out must hold n ints.
 * Returns the token count (<= n); n == 0 returns 0. */
int32_t fastbpe_encode_word(
    const FastBpe *ctx, const uint8_t *bytes, int32_t n, int32_t *out
) {
    int32_t len = n;
    for (int32_t i = 0; i < n; i++) out[i] = bytes[i];
    while (len >= 2) {
        int32_t best_rank = INT32_MAX, best_i = -1;
        for (int32_t i = 0; i + 1 < len; i++) {
            int32_t r = lookup(ctx, out[i], out[i + 1]);
            if (r >= 0 && r < best_rank) { best_rank = r; best_i = i; }
        }
        if (best_i < 0) break;
        /* Fuse EVERY occurrence of the winning pair left to right,
         * skipping overlaps — mirrors bpe.py's _merge. */
        int32_t a = out[best_i], b = out[best_i + 1];
        int32_t merged = 256 + best_rank;
        int32_t w = 0;
        for (int32_t i = 0; i < len; ) {
            if (i + 1 < len && out[i] == a && out[i + 1] == b) {
                out[w++] = merged;
                i += 2;
            } else {
                out[w++] = out[i++];
            }
        }
        len = w;
    }
    return len;
}
