"""YAML → validated ``RunConfig`` loading.

Parity target: reference ``src/llmtrain/config/loader.py`` — safe_load, a
structured ``ConfigLoadError(message, details, errors)``, rejection of
non-mapping top level, relative paths resolved against cwd.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import yaml
from pydantic import ValidationError

from .schemas import RunConfig


class ConfigLoadError(Exception):
    """Raised when a config file cannot be read, parsed, or validated.

    Carries structured fields so the CLI can render machine-readable JSON
    errors (reference loader.py:14-21, cli.py:63-76).
    """

    def __init__(
        self,
        message: str,
        *,
        details: str | None = None,
        errors: list[dict[str, Any]] | None = None,
    ) -> None:
        super().__init__(message)
        self.message = message
        self.details = details
        self.errors = errors or []


def resolve_config_path(path: str | Path) -> Path:
    """Resolve ``path`` against the current working directory (loader.py:31)."""
    p = Path(path)
    if not p.is_absolute():
        p = Path.cwd() / p
    return p.resolve()


def load_yaml_config(path: str | Path) -> dict[str, Any]:
    """Read and parse a YAML mapping from ``path``."""
    resolved = resolve_config_path(path)
    if not resolved.is_file():
        raise ConfigLoadError(
            f"Config file not found: {resolved}",
            details="Provide an existing YAML file via --config.",
        )
    try:
        raw_text = resolved.read_text(encoding="utf-8")
    except OSError as exc:
        raise ConfigLoadError(f"Config file could not be read: {resolved}", details=str(exc))
    try:
        parsed = yaml.safe_load(raw_text)
    except yaml.YAMLError as exc:
        raise ConfigLoadError(f"Config file is not valid YAML: {resolved}", details=str(exc))
    if parsed is None:
        parsed = {}
    if not isinstance(parsed, dict):
        raise ConfigLoadError(
            f"Config root must be a mapping, got {type(parsed).__name__}: {resolved}",
            details="Top-level YAML must be a key/value mapping of config sections.",
        )
    return parsed


def load_and_validate_config(path: str | Path) -> tuple[RunConfig, dict[str, Any], dict[str, Any]]:
    """Load YAML and validate into ``RunConfig``.

    Returns ``(config, raw_dict, resolved_dict)`` where ``resolved_dict`` is
    the fully-materialized config including defaults (loader.py:48-65).
    """
    raw = load_yaml_config(path)
    try:
        cfg = RunConfig.model_validate(raw)
    except ValidationError as exc:
        errors = [
            {
                "loc": ".".join(str(part) for part in err.get("loc", ())),
                "msg": err.get("msg", ""),
                "type": err.get("type", ""),
            }
            for err in exc.errors()
        ]
        raise ConfigLoadError(
            f"Config validation failed with {exc.error_count()} error(s).",
            details=str(path),
            errors=errors,
        )
    return cfg, raw, cfg.model_dump()
