"""Per-layer activation-policy tier specs (``model.extra.activation_tiers``).

One compact string assigns every transformer block an activation regime —
how much of the forward pass is kept in HBM for the backward pass:

========== =============================================================
tier       saved residuals per block
========== =============================================================
``none``     everything (no remat — the pre-tier ``remat: false`` default)
``selective``  matmul outputs only (``dots_saveable`` — Megatron-style
             selective recomputation: cheap elementwise ops replay)
``full``       nothing (whole-block recompute — the pre-tier
             ``remat: true`` behavior)
``offload``    block inputs staged to host (``pinned_host``) between the
             forward and backward pass; the block interior recomputes
             like ``full``. Backends without a pinned-host memory space
             fall back to ``full`` at runtime with a once-per-process
             warning (models/activation_policy.py) — requesting offload
             is never a config error.
========== =============================================================

Grammar (whitespace-free)::

    spec   := entry ("," entry)*
    entry  := tier ":" range
    range  := "*" | INT | INT "-" INT        # inclusive, 0-based

``*`` covers every layer and must be the only entry.  Layers a spec does
not name default to ``none``.  Overlaps, out-of-range indices, unknown
tier names, and malformed entries all raise :class:`ValueError` — the
config schema (config/schemas.py) and the model adapters call
:func:`parse_activation_tiers` at validation time so a bad spec fails
before any jax work.

Deliberately dependency-free (string/dict math only): imported by the
config schema, the mesh planner (autotune/plan.py), and the models.
"""

from __future__ import annotations

# Canonical tier order: monotonically *decreasing* device-resident
# activation bytes (the HBM-model monotonicity the tests pin).
TIERS = ("none", "selective", "full", "offload")


def parse_activation_tiers(spec: str, n_layers: int) -> tuple[str, ...]:
    """Parse ``spec`` into one tier per layer (length ``n_layers``).

    Raises :class:`ValueError` naming the offending entry for unknown
    tiers, malformed ranges, out-of-range layer indices, overlapping
    assignments, or a ``*`` combined with other entries.
    """
    if n_layers < 1:
        raise ValueError(f"n_layers must be >= 1 (got {n_layers})")
    if not isinstance(spec, str) or not spec.strip():
        raise ValueError(
            "activation_tiers spec must be a non-empty string like "
            "'offload:0-3,full:4-11' or 'full:*'"
        )
    entries = [e.strip() for e in spec.strip().split(",")]
    out: list[str | None] = [None] * n_layers
    for entry in entries:
        if not entry:
            raise ValueError(
                f"activation_tiers spec {spec!r} has an empty entry "
                "(stray comma?)"
            )
        tier, sep, rng = entry.partition(":")
        if not sep or not rng:
            raise ValueError(
                f"activation_tiers entry {entry!r} is malformed; expected "
                "'tier:range' like 'offload:0-3' or 'full:*'"
            )
        if tier not in TIERS:
            raise ValueError(
                f"activation_tiers entry {entry!r} names unknown tier "
                f"{tier!r}; expected one of {list(TIERS)}"
            )
        if rng == "*":
            if len(entries) != 1:
                raise ValueError(
                    f"activation_tiers entry {entry!r} uses '*' alongside "
                    "other entries; '*' must be the only entry"
                )
            return (tier,) * n_layers
        lo_s, dash, hi_s = rng.partition("-")
        try:
            lo = int(lo_s)
            hi = int(hi_s) if dash else lo
        except ValueError:
            raise ValueError(
                f"activation_tiers entry {entry!r} has a malformed layer "
                "range; expected an int or 'lo-hi'"
            ) from None
        if lo > hi:
            raise ValueError(
                f"activation_tiers entry {entry!r} has an inverted range "
                f"({lo} > {hi})"
            )
        if lo < 0 or hi >= n_layers:
            raise ValueError(
                f"activation_tiers entry {entry!r} is out of range for a "
                f"{n_layers}-layer model (valid layers: 0-{n_layers - 1})"
            )
        for layer in range(lo, hi + 1):
            if out[layer] is not None:
                raise ValueError(
                    f"activation_tiers entry {entry!r} overlaps layer "
                    f"{layer}, already assigned tier {out[layer]!r}"
                )
            out[layer] = tier
    return tuple(t if t is not None else "none" for t in out)


def canonical_tier_spec(tiers: tuple[str, ...] | list[str]) -> str:
    """The compact canonical spelling of a per-layer tier tuple — stable
    across equivalent input spellings, so plan keys and tune reports
    compare by value (``('full','full') -> 'full:*'``,
    ``('offload','full','full') -> 'offload:0,full:1-2'``)."""
    if not tiers:
        raise ValueError("tiers must be non-empty")
    for t in tiers:
        if t not in TIERS:
            raise ValueError(f"unknown tier {t!r}; expected one of {list(TIERS)}")
    if len(set(tiers)) == 1:
        return f"{tiers[0]}:*"
    runs: list[tuple[str, int, int]] = []
    for i, t in enumerate(tiers):
        if runs and runs[-1][0] == t and runs[-1][2] == i - 1:
            runs[-1] = (t, runs[-1][1], i)
        else:
            runs.append((t, i, i))
    return ",".join(
        f"{t}:{lo}" if lo == hi else f"{t}:{lo}-{hi}" for t, lo, hi in runs
    )


__all__ = ["TIERS", "canonical_tier_spec", "parse_activation_tiers"]
