"""Config system: strict Pydantic schemas + YAML loader."""

from .loader import (
    ConfigLoadError,
    load_and_validate_config,
    load_yaml_config,
    resolve_config_path,
)
from .schemas import (
    ChaosConfig,
    DataConfig,
    DistributedConfig,
    FaultInjectionConfig,
    LoggingConfig,
    MeshConfig,
    MLflowConfig,
    ModelConfig,
    OutputConfig,
    ResilienceConfig,
    RunConfig,
    RunSectionConfig,
    ServingConfig,
    TrainerConfig,
    TuneConfig,
    WatchdogConfig,
)

__all__ = [
    "ChaosConfig",
    "ConfigLoadError",
    "DataConfig",
    "DistributedConfig",
    "FaultInjectionConfig",
    "LoggingConfig",
    "MeshConfig",
    "MLflowConfig",
    "ModelConfig",
    "OutputConfig",
    "ResilienceConfig",
    "RunConfig",
    "RunSectionConfig",
    "ServingConfig",
    "TrainerConfig",
    "TuneConfig",
    "WatchdogConfig",
    "load_and_validate_config",
    "load_yaml_config",
    "resolve_config_path",
]
