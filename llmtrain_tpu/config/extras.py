"""Typo detection for the ``extra`` escape hatches.

The reference keeps ``extra: dict`` sections deliberately free-form
(reference schemas.py:37,65,87) — but this framework hangs real knobs off
them (loss_impl, z_loss, n_experts, globs, keep_last_k, ...), so a typo
like ``los_impl`` silently no-ops. ``unknown_extra_keys`` compares each
section's keys against what the resolved adapter / data module / trainer
declares via ``known_extra_keys``; the CLI logs WARNINGS (never errors:
user plugins may take keys we cannot know about).
"""

from __future__ import annotations

from ..config.schemas import RunConfig

# Knobs read from trainer.extra (training/trainer.py, training/checkpoint.py,
# training/optimizer.py).
TRAINER_EXTRA_KEYS = frozenset(
    {
        "keep_last_k",
        "profile_start_step",
        "profile_num_steps",
        "profile_all_hosts",
        "optimizer",
        "ema_decay",
        "step_delay_sec",
    }
)


def unknown_extra_keys(cfg: RunConfig) -> dict[str, list[str]]:
    """Best-effort ``{section: sorted unknown keys}`` for warning output.

    Resolves the model adapter and data module from the registries; a
    plugin that does not declare ``known_extra_keys`` (or an unknown
    name) contributes nothing — this must never break validation.
    """
    out: dict[str, list[str]] = {}

    def check(section: str, keys, known) -> None:
        if known is None:
            return
        unknown = sorted(set(keys) - set(known))
        if unknown:
            out[section] = unknown

    try:
        from ..models.lora import build_adapter
        from ..registry import initialize_registries

        initialize_registries()
        # The instance, not the class: the LoRA wrapper augments the
        # wrapped family's known keys with its own (models/lora.py).
        adapter = build_adapter(cfg)
        check(
            "model.extra",
            cfg.model.extra,
            getattr(adapter, "known_extra_keys", None),
        )
    except Exception:  # unknown plugin name etc. — other checks will report
        pass
    try:
        from ..registry import get_data_module

        data_cls = get_data_module(cfg.data.name)
        check(
            "data.extra", cfg.data.extra, getattr(data_cls, "known_extra_keys", None)
        )
    except Exception:
        pass
    check("trainer.extra", cfg.trainer.extra, TRAINER_EXTRA_KEYS)
    return out


__all__ = ["unknown_extra_keys", "TRAINER_EXTRA_KEYS"]
