"""Strict, frozen Pydantic configuration tree.

Parity target: reference ``src/llmtrain/config/schemas.py`` (8 frozen sections,
``extra="forbid"``, ``validate_default=True``, cross-field validators, plugin
``extra`` escape hatches, ``schema_version``). Intentional TPU divergences:

* ``run.device`` is ``cpu|tpu`` (reference restricts to ``cpu|mps``,
  schemas.py:13 — MPS is meaningless on TPU hardware).
* The ``ddp:`` section (reference schemas.py:102-120, torch/gloo runtime hints)
  is replaced by ``distributed:`` — JAX multi-process rendezvous fields plus a
  named device-mesh spec (data/fsdp/tensor/sequence/pipeline/expert axes).
  Env-beats-config resolution semantics are preserved (see
  ``llmtrain_tpu/distributed``).
* ``model.dtype`` / ``model.param_dtype`` add first-class bfloat16 compute
  (the reference has no mixed precision at all, SURVEY §2.4).
"""

from typing import Any, Literal

from pydantic import BaseModel, ConfigDict, Field, model_validator

try:  # typing.Self is 3.11+; typing_extensions covers the 3.10 floor
    from typing import Self
except ImportError:  # pragma: no cover - exercised on 3.10 runtimes
    from typing_extensions import Self

_STRICT = ConfigDict(extra="forbid", frozen=True, validate_default=True)


class RunSectionConfig(BaseModel):
    """Run-level identity, seeding and device selection."""

    name: str
    seed: int = 1337
    device: Literal["cpu", "tpu"] = "cpu"
    deterministic: bool = True
    notes: str | None = None
    # Persistent JAX compilation-cache directory. None = the library
    # default (~/.cache/llmtrain_tpu/jax); the LLMTRAIN_COMPILATION_CACHE
    # env var overrides either (and "off" disables caching entirely) —
    # see llmtrain_tpu.distributed.resolve_compilation_cache_dir. On k8s,
    # point this (or the env var) at a mounted cache volume so
    # podFailurePolicy retries skip the minutes-long recompile.
    compilation_cache_dir: str | None = None

    model_config = _STRICT


class ModelConfig(BaseModel):
    """Architecture hyper-parameters handed to the model adapter.

    Field names and constraints mirror reference schemas.py:24-51 so configs
    translate 1:1; ``dtype``/``param_dtype`` are TPU additions.
    """

    name: str
    init: Literal["random"] = "random"
    block_size: int = Field(256, ge=8)
    d_model: int = Field(384, ge=8)
    n_layers: int = Field(6, ge=1)
    n_heads: int = Field(6, ge=1)
    d_ff: int = Field(1536, ge=8)
    dropout: float = Field(0.1, ge=0.0, lt=1.0)
    tie_embeddings: bool = True
    vocab_size: int | None = None
    dtype: Literal["float32", "bfloat16"] = "float32"
    param_dtype: Literal["float32", "bfloat16"] = "float32"
    remat: bool = False
    attention: Literal["dense", "flash", "ring", "ulysses"] = "dense"
    extra: dict[str, Any] = Field(default_factory=dict)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_model_dimensions(self) -> Self:
        if self.d_model % self.n_heads != 0:
            raise ValueError("d_model must be divisible by n_heads")
        if self.d_ff < self.d_model:
            raise ValueError("d_ff must be greater than or equal to d_model")
        # Strict-validate the per-layer activation-tier spec at config
        # time (unknown tiers, malformed/overlapping/out-of-range ranges,
        # conflict with the deprecated `remat` flag). A backend without a
        # pinned_host memory space is deliberately NOT a config error —
        # offload degrades to full remat at runtime with a warning
        # (models/activation_policy.py).
        spec = self.extra.get("activation_tiers")
        if spec is not None:
            from .activation_tiers import parse_activation_tiers

            if self.remat:
                raise ValueError(
                    "model.remat: true conflicts with model.extra."
                    "activation_tiers; drop model.remat (tiers subsume it)"
                )
            try:
                parse_activation_tiers(str(spec), self.n_layers)
            except ValueError as exc:
                raise ValueError(f"model.extra.activation_tiers: {exc}") from exc
        return self


class DataConfig(BaseModel):
    """Dataset selection, splits, and HuggingFace overrides.

    Mirrors reference schemas.py:54-71 (``num_workers`` kept for config
    compatibility; the JAX input pipeline is synchronous prefetch, not torch
    worker processes).
    """

    name: str
    cache_dir: str = ".cache/datasets"
    num_workers: int = Field(2, ge=0)
    train_split: str = "train"
    val_split: str = "validation"
    dataset_name: str | None = None
    dataset_config: str | None = None
    text_column: str | None = None
    extra: dict[str, Any] = Field(default_factory=dict)

    model_config = _STRICT


class ZeroConfig(BaseModel):
    """ZeRO-style cross-replica optimizer-state sharding
    (parallel/sharding.py:opt_state_shardings, docs/perf.md "Sharded
    optimizer state").

    With ``enabled`` the AdamW/adafactor state leaves are partitioned
    along the combined data-parallel axes (``data``/``fsdp``/``expert``)
    instead of being replicated on every replica — the weight-update
    sharding of Xu et al. (arXiv:2004.13336). Per-replica optimizer
    memory drops ~N_dp×; the loss trajectory is bitwise-identical to the
    replicated path at the default ``stage`` 1.

    ``stage`` picks how gradients synchronize:

    * ``1`` — gradients keep the parameter layout (XLA's all-reduce, as
      today); only the update compute + state storage shard. Bitwise-
      identical trajectories zero on/off (tests/test_zero.py pins it).
    * ``2`` — gradients are constrained to the sharded layout too, so
      GSPMD emits reduce-scatter and the full gradient tree never
      materializes replicated after accumulation. The global-norm clip
      then reduces shard partials first, which reassociates the float
      sum: trajectories track the replicated path to ~1e-6, not bitwise.

    ``host_offload`` pins the (sharded) optimizer state to host memory
    between steps: on backends with a ``pinned_host`` memory space (TPU)
    via memory-kind shardings, elsewhere via an explicit host round-trip
    around the step — HBM for the state drops to ~0 at the cost of a
    per-step H2D/D2H of the state shard.
    """

    enabled: bool = False
    stage: Literal[1, 2] = 1
    host_offload: bool = False

    model_config = _STRICT

    @model_validator(mode="after")
    def check_offload(self) -> Self:
        if self.host_offload and not self.enabled:
            raise ValueError(
                "trainer.zero.host_offload requires trainer.zero.enabled: "
                "true (the offload pins the ZeRO-sharded state tree)"
            )
        return self


class TrainerConfig(BaseModel):
    """Training-loop pacing, optimizer and logging cadence.

    Mirrors reference schemas.py:74-99 incl. the warmup<=max_steps validator.
    """

    max_steps: int = Field(1000, ge=1)
    micro_batch_size: int = Field(8, ge=1)
    grad_accum_steps: int = Field(4, ge=1)
    lr: float = Field(3e-4, gt=0.0)
    weight_decay: float = Field(0.1, ge=0.0)
    warmup_steps: int = Field(100, ge=0)
    max_grad_norm: float = Field(1.0, gt=0.0)
    log_every_steps: int = Field(10, ge=1)
    eval_every_steps: int = Field(100, ge=1)
    save_every_steps: int = Field(500, ge=1)
    # Batches the async input pipeline assembles ahead of the step loop
    # (data/prefetch.py): host-side gathers + H2D overlap the previous
    # step's device compute. 0 = synchronous assembly (the pre-prefetch
    # path, kept as the escape hatch). Loss trajectories are bitwise
    # identical either way — the prefetcher only changes WHEN batches are
    # built, never what is built (tests/test_prefetch.py).
    prefetch_depth: int = Field(2, ge=0)
    # ZeRO-style optimizer-state sharding over the data-parallel axes
    # (see ZeroConfig above; off by default — replicated state, the
    # pre-zero layout, stays the bit-exact parity baseline).
    zero: ZeroConfig = Field(default_factory=ZeroConfig)
    extra: dict[str, Any] = Field(default_factory=dict)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_steps(self) -> Self:
        if self.warmup_steps > self.max_steps:
            raise ValueError("warmup_steps cannot exceed max_steps")
        return self


class MeshConfig(BaseModel):
    """Named device-mesh axis sizes.

    ``-1`` on exactly one axis means "fill with all remaining devices" (like a
    reshape wildcard). Axis order is the physical iteration order — ``data``
    outermost so data-parallel replicas land on distinct hosts and
    tensor/sequence shards ride ICI.
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    pipeline: int = 1
    expert: int = 1

    model_config = _STRICT

    @model_validator(mode="after")
    def check_axes(self) -> Self:
        sizes = self.axis_sizes()
        wildcards = sum(1 for v in sizes.values() if v == -1)
        if wildcards > 1:
            raise ValueError("at most one mesh axis may be -1 (wildcard)")
        for axis, v in sizes.items():
            if v == 0 or v < -1:
                raise ValueError(f"mesh axis {axis!r} must be a positive int or -1")
        # `pipeline` is only consumed by models that stack their layer dim
        # on the "layers" logical axis (gpt_pipeline); whether the selected
        # model supports it is validated by the Trainer against the
        # adapter's `supports_pipeline` flag — config can't see the model.
        # (`expert` is wired: MoE expert weights shard over it and it carries
        # batch shards for dense compute — parallel/sharding.py.)
        return self

    def axis_sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "tensor": self.tensor,
            "sequence": self.sequence,
            "pipeline": self.pipeline,
            "expert": self.expert,
        }


class DistributedConfig(BaseModel):
    """JAX multi-process runtime hints and the device mesh.

    Replaces the reference's ``DDPConfig`` (schemas.py:102-120). The
    rendezvous fields map torch's env contract onto
    ``jax.distributed.initialize``: RANK→process_id, WORLD_SIZE→num_processes,
    MASTER_ADDR/PORT→coordinator. Env vars beat config values, matching
    reference distributed/__init__.py:100-118.
    """

    enabled: bool = False
    backend: Literal["jax"] = "jax"
    timeout_sec: int = Field(1800, ge=1)
    num_processes: int | None = None
    process_id: int | None = None
    coordinator_addr: str | None = None
    coordinator_port: int | None = None
    mesh: MeshConfig = Field(default_factory=MeshConfig)

    model_config = _STRICT


class FaultInjectionConfig(BaseModel):
    """Deterministic fault injection for exercising the recovery paths.

    Every field defaults to "inject nothing" — production configs never set
    these; tests and chaos drills do. Step-indexed faults use 1-based
    optimizer-step numbering, matching the trainer's loop and log lines.
    """

    # Poison loss AND grads with NaN inside the jitted train step for
    # ``nan_loss_steps`` consecutive optimizer steps starting at this one.
    nan_loss_at_step: int | None = Field(None, ge=1)
    nan_loss_steps: int = Field(1, ge=1)
    # Scale the host-observed loss of exactly this step (one-shot, so the
    # replayed step after a rollback is not re-poisoned).
    spike_loss_at_step: int | None = Field(None, ge=1)
    spike_loss_scale: float = Field(100.0, gt=1.0)
    # Deliver SIGTERM to this process right after dispatching this step.
    sigterm_at_step: int | None = Field(None, ge=1)
    # Preemption-named twin of sigterm_at_step: a real SIGTERM delivered
    # to self at EXACTLY this step, driving the clean-preemption save +
    # exit-0 path — the same seeded, in-config treatment kill_at_step
    # gives SIGKILL. The fleet storm schedule (fleet/chaos.py) uses this
    # for step-exact graceful evictions; mutually exclusive with
    # sigterm_at_step (they share the one-shot delivery slot).
    preempt_at_step: int | None = Field(None, ge=1)
    # Hard-kill (SIGKILL — no handler, no cleanup, no checkpoint) this
    # process right after dispatching this step. The crash-shaped failure
    # the atomic commit protocol + chaos harness (resilience/chaos.py)
    # exist for: nothing on the way down gets a chance to tidy up.
    kill_at_step: int | None = Field(None, ge=1)
    # Aim the SIGKILL INSIDE the async checkpoint write instead: the first
    # save at/after kill_at_step (or the first save at all when
    # kill_at_step is unset) dies between its staged files and the
    # manifest publish — the exact window that makes a multi-file
    # checkpoint torn without atomic commits.
    kill_during_checkpoint: bool = False
    # After the checkpoint save at/after this step, damage the newest
    # checkpoint file on disk (one-shot).
    corrupt_checkpoint_at_step: int | None = Field(None, ge=1)
    corrupt_mode: Literal["truncate", "garbage"] = "truncate"
    # Make the first N attempts of these operations raise, to exercise the
    # exponential-backoff retry() wiring.
    dataset_load_failures: int = Field(0, ge=0)
    distributed_init_failures: int = Field(0, ge=0)
    # Block the host step loop FOR REAL right after dispatching this step
    # (one-shot) — the hang-shaped failure the watchdog exists to kill.
    # Without a duration the block is indefinite (the watchdog, or the k8s
    # liveness probe, is what ends it); with one, the loop resumes after —
    # a controllable straggler/GC-pause stand-in.
    hang_at_step: int | None = Field(None, ge=1)
    hang_duration_sec: float | None = Field(None, gt=0.0)
    # Fire the hang inside the background prefetcher's assembly thread
    # instead of the host step loop: the consumer then starves on the
    # queue — the stall signature of a wedged data pipeline, which the
    # watchdog must detect exactly like a host-loop hang. Requires
    # trainer.prefetch_depth >= 1 (with the synchronous fallback there is
    # no prefetcher to hang, so the injection never fires).
    hang_in_prefetcher: bool = False

    model_config = _STRICT

    @model_validator(mode="after")
    def check_preempt_alias(self) -> Self:
        if self.preempt_at_step is not None and self.sigterm_at_step is not None:
            raise ValueError(
                "faults.preempt_at_step and faults.sigterm_at_step are the "
                "same one-shot SIGTERM injection — set exactly one"
            )
        return self


class WatchdogConfig(BaseModel):
    """Hang watchdog + heartbeat + straggler telemetry
    (llmtrain_tpu/resilience/watchdog.py).

    The watchdog hard-exits a stalled run with the retryable
    EXIT_HANG_DETECTED (76) after dumping all-thread stacks and JAX
    diagnostics to ``{run_dir}/hang_report_*.txt`` — a stuck collective
    never raises, so detection has to come from outside the step loop.
    """

    enabled: bool = False
    # No optimizer step dispatched for this long => the run is hung. Budget
    # for the slowest legitimate gap: first-step compile, periodic eval,
    # and checkpoint host-gather all count as "no progress".
    stall_timeout_sec: float = Field(300.0, gt=0.0)
    # Watchdog poll cadence; default None = stall_timeout_sec / 10.
    poll_interval_sec: float | None = Field(None, gt=0.0)
    # Heartbeat file the beacon touches for the k8s livenessProbe exec.
    # None = {run_dir}/heartbeat. Point it at container-local storage
    # (e.g. /tmp/llmtrain-heartbeat) on k8s: the probe must observe THIS
    # pod, not whichever pod last touched a shared volume.
    heartbeat_path: str | None = None
    heartbeat_interval_sec: float = Field(1.0, ge=0.0)
    # Per-host step-time skew telemetry on multi-process runs (allgathered
    # at log boundaries, so it adds no extra device syncs).
    straggler_telemetry: bool = True
    straggler_skew_factor: float = Field(2.0, gt=1.0)
    straggler_patience: int = Field(3, ge=1)

    model_config = _STRICT


class ChaosConfig(BaseModel):
    """Chaos/storm drill gates (resilience/chaos.py, fleet/chaos.py).

    ``min_goodput_frac`` is the configurable goodput floor asserted by the
    single-run chaos drill and per tenant by the fleet storm: the
    productive_train share of total wall-clock (telemetry/goodput.py)
    must not fall below it after all kill/resume cycles. 0.0 (default)
    checks only that the ledger exists and balances.
    """

    min_goodput_frac: float = Field(0.0, ge=0.0, le=1.0)

    model_config = _STRICT


class ResilienceConfig(BaseModel):
    """Fault-tolerance knobs (llmtrain_tpu/resilience/).

    New subsystem over the reference, which has no recovery machinery at
    all (SURVEY §5; PAPER.md §2.4 lists elastic recovery as absent): a
    non-finite guard inside the jitted train step, a loss-spike detector
    with checkpoint auto-rollback, and retry policy for flaky
    initialization. Checkpoint sha-256 integrity sidecars are always on —
    they need no configuration.
    """

    # Mask the optimizer update (optax apply_if_finite style) whenever loss
    # or any gradient is non-finite; the step still advances so the data
    # stream moves past the poisonous batch.
    nonfinite_guard: bool = False
    # Abort the run once this many CONSECUTIVE updates were skipped —
    # persistent NaN means divergence, not a bad batch.
    max_consecutive_nonfinite: int = Field(25, ge=1)
    # Rolling-EWMA loss-spike detector; on a spike, restore the newest
    # verified checkpoint and advance the sampler past the bad window.
    spike_detection: bool = False
    spike_factor: float = Field(4.0, gt=1.0)
    spike_ewma_beta: float = Field(0.9, gt=0.0, lt=1.0)
    spike_min_history: int = Field(20, ge=1)
    max_rollbacks: int = Field(2, ge=0)
    # Exponential-backoff retry for distributed init and dataset loading.
    retry_attempts: int = Field(3, ge=1)
    retry_base_delay: float = Field(0.05, ge=0.0)
    # Hang watchdog + heartbeat + straggler telemetry.
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    faults: FaultInjectionConfig = Field(default_factory=FaultInjectionConfig)
    # Chaos-drill gates (goodput floor) — resilience/chaos.py.
    chaos: ChaosConfig = Field(default_factory=ChaosConfig)

    model_config = _STRICT


class TracingConfig(BaseModel):
    """Distributed request tracing (telemetry/tracing.py,
    docs/observability.md "Distributed request tracing").

    Tail-based sampling keeps the hot path near-free: every request
    buffers its spans in memory, but only slow / errored / failed-over /
    forced (``X-Trace: force``) traces flush full-detail ``cat="trace"``
    trees into the timeline for ``llmtrain trace`` to reassemble.
    """

    enabled: bool = True
    # Keep the slowest fraction of requests (top percentile of a sliding
    # latency reservoir): 0.05 = roughly the p95+ tail.
    slow_keep_frac: float = Field(0.05, gt=0.0, le=1.0)
    # Sliding latency reservoir sizing the slow threshold estimate.
    reservoir: int = Field(512, ge=16)
    # Always keep the first N traces per process so a fresh fleet has
    # something to show before the reservoir warms up.
    warmup_keep: int = Field(16, ge=0)
    # Per-request span buffer cap; overflow is counted, not grown.
    max_spans_per_trace: int = Field(256, ge=8)

    model_config = _STRICT


class TelemetryConfig(BaseModel):
    """Unified telemetry subsystem (llmtrain_tpu/telemetry/,
    docs/observability.md): step-event timeline with Perfetto export,
    device/host memory accounting, the metrics registry every component
    publishes through, a Prometheus text endpoint, and the end-of-run
    report.json/report.md.

    Defaults are production-shaped and near-free on the hot path (span
    recording is a dict append; memory sampling runs at log-interval
    cadence only). ``prometheus`` is the one opt-in: it binds a port.
    """

    enabled: bool = True
    # Structured span/instant timeline: {run_dir}/telemetry/timeline.jsonl
    # per flush + Perfetto-loadable trace.json at end of run.
    timeline: bool = True
    # Retained-event cap; overflow drops the oldest already-persisted
    # events (counted in the report, never silent).
    max_events: int = Field(200_000, ge=1000)
    # Wrap steps/spans in jax.profiler Step/TraceAnnotations so an xprof
    # window lines up 1:1 with the framework timeline.
    xprof_annotations: bool = True
    # mem/hbm_used, mem/hbm_peak, mem/host_rss ... sampled per log interval,
    # with a headroom warning when used/limit crosses the threshold.
    memory: bool = True
    hbm_headroom_warn_frac: float = Field(0.92, gt=0.0, le=1.0)
    # Stdlib HTTP /metrics endpoint (main process only; k8s Jobs carry the
    # matching prometheus.io/scrape annotations).
    prometheus: bool = False
    prometheus_host: str = "0.0.0.0"
    prometheus_port: int = Field(9200, ge=0, le=65535)  # 0 = ephemeral
    # node-exporter textfile-collector snapshot, rewritten atomically at
    # every flush: {run_dir}/telemetry/metrics.prom.
    prometheus_textfile: bool = True
    # End-of-run report.json/report.md in the run dir.
    report: bool = True
    # Cost-attribution block (telemetry/profiling.py): XLA cost_analysis
    # totals from the jitted train step, roofline class, MFU
    # reconciliation — a `perf_attribution` block in report.json plus
    # perf/* gauges. Costs one extra trace+lower of the step function at
    # end of fit (no XLA compile, nothing executes).
    perf_attribution: bool = True
    # Roofline peak overrides merged over the built-in DEVICE_PEAKS row
    # for the detected device kind. Keys: peak_flops, hbm_bytes_per_sec,
    # ici_bytes_per_sec (values in FLOP/s and bytes/s).
    device_peaks: dict[str, float] = Field(default_factory=dict)
    # Distributed request tracing with tail-based sampling (serving
    # fleet + promote lifecycle; `llmtrain trace` reads the output).
    tracing: TracingConfig = Field(default_factory=TracingConfig)

    model_config = _STRICT


class OverloadConfig(BaseModel):
    """SLO-aware overload control (serving/overload.py, docs/serving.md
    "Overload and SLOs").

    Bounded deadline-aware admission, priority classes with per-class
    token buckets, load shedding, and brownout with hysteresis. When
    enabled the continuous-batching scheduler rejects fast (HTTP 429 +
    Retry-After) instead of queueing requests to die, and degrades
    predictably under sustained pressure.
    """

    enabled: bool = False
    # Hard cap on the admission queue; submits past it reject with
    # reason=queue_full.
    queue_cap: int = Field(64, ge=1)
    # Deadline applied to requests that carry none (0 = no deadline:
    # such requests are never rejected for deadline reasons).
    default_deadline_ms: float = Field(0.0, ge=0.0)
    # EWMA smoothing for the per-queue-slot wait estimator, plus the
    # prior used before any observation lands.
    ewma_beta: float = Field(0.8, gt=0.0, lt=1.0)
    prior_wait_ms: float = Field(50.0, gt=0.0)
    # Priority classes and their weighted-round-robin dequeue weights.
    # Higher weight = more dequeues per cycle; every class with queued
    # work is visited each cycle, so batch never starves interactive
    # and vice versa.
    classes: dict[str, int] = Field(
        default_factory=lambda: {"interactive": 4, "batch": 1}
    )
    # Class assigned to requests with an unknown/absent priority.
    default_class: str = "interactive"
    # Optional per-class token-bucket admission rate (requests/sec) and
    # burst size. Classes absent from the map are not rate limited.
    class_rate_rps: dict[str, float] = Field(default_factory=dict)
    class_burst: dict[str, float] = Field(default_factory=dict)
    # Per-client token buckets at the HTTP boundary, keyed by the
    # X-Client-Id header (0 = disabled).
    client_rate_rps: float = Field(0.0, ge=0.0)
    client_burst: float = Field(8.0, ge=1.0)
    max_tracked_clients: int = Field(1024, ge=1)
    # Brownout hysteresis: enter after enter_ticks consecutive scheduler
    # steps with predicted queue wait >= high_ms; exit after exit_ticks
    # consecutive steps < low_ms. While active, max_new_tokens is
    # clamped and speculative decoding is disabled to protect TTFT.
    brownout_high_ms: float = Field(500.0, gt=0.0)
    brownout_low_ms: float = Field(100.0, gt=0.0)
    brownout_enter_ticks: int = Field(3, ge=1)
    brownout_exit_ticks: int = Field(3, ge=1)
    brownout_max_new_tokens: int = Field(16, ge=1)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_overload(self) -> Self:
        if not self.classes:
            raise ValueError("serving.overload.classes must be non-empty")
        if any(w < 1 for w in self.classes.values()):
            raise ValueError(
                "serving.overload.classes weights must be >= 1"
            )
        if self.default_class not in self.classes:
            raise ValueError(
                f"serving.overload.default_class {self.default_class!r} "
                f"not in classes {sorted(self.classes)}"
            )
        for field in ("class_rate_rps", "class_burst"):
            unknown = set(getattr(self, field)) - set(self.classes)
            if unknown:
                raise ValueError(
                    f"serving.overload.{field} keys {sorted(unknown)} "
                    f"not in classes {sorted(self.classes)}"
                )
        if any(v <= 0 for v in self.class_rate_rps.values()):
            raise ValueError(
                "serving.overload.class_rate_rps values must be > 0"
            )
        if any(v < 1 for v in self.class_burst.values()):
            raise ValueError(
                "serving.overload.class_burst values must be >= 1"
            )
        if self.brownout_low_ms >= self.brownout_high_ms:
            raise ValueError(
                "serving.overload.brownout_low_ms must be < "
                "brownout_high_ms (hysteresis needs a gap)"
            )
        return self


class RouterConfig(BaseModel):
    """Replica-router knobs (serving/router.py, ``llmtrain serve
    --router``, docs/serving.md "Fleet tier").

    The router places each request on one of N replicas by score:
    ``affinity_weight * matched_prefix_blocks - load`` — prefix-cache-
    aware placement so requests sharing a system prompt land where their
    KV blocks already live. Replicas failing ``fail_threshold``
    consecutive requests are evicted and probed again after
    ``revive_sec``.
    """

    # In-process replicas `--router` spins up when no --backends given.
    replicas: int = Field(2, ge=1)
    # Score weight of one matched prefix block vs one unit of load.
    affinity_weight: float = Field(4.0, ge=0.0)
    # LRU cap on the prefix-hash -> replica affinity index.
    max_affinity_entries: int = Field(4096, ge=1)
    # Consecutive failures before a replica is evicted from rotation.
    fail_threshold: int = Field(3, ge=1)
    # Seconds before an evicted replica gets a revival probe.
    revive_sec: float = Field(10.0, gt=0.0)
    # Timeout for health/stats probes (GET /healthz, /stats) — separate
    # from the per-request timeout so a wedged replica can't stall the
    # router's health sweep.
    probe_timeout_sec: float = Field(10.0, gt=0.0)
    # Failover retry budget: at most this many retries per window across
    # the fleet, so an overloaded fleet is never DDoS'd by its own
    # router. 0 = unlimited.
    retry_budget: int = Field(16, ge=0)
    retry_window_sec: float = Field(10.0, gt=0.0)

    model_config = _STRICT


class ServingConfig(BaseModel):
    """Inference-serving knobs (llmtrain_tpu/serving/, docs/serving.md).

    ``mode`` selects the backend of ``llmtrain serve``/``serve-bench``:
    ``simple`` keeps the original one-decode-at-a-time locked path;
    ``continuous`` runs the paged-KV continuous-batching scheduler —
    N in-flight sequences of different lengths share one jitted decode
    program, with shape buckets bounding the XLA compile count.
    """

    mode: Literal["simple", "continuous"] = "simple"
    # In-flight sequences the batched decode step can hold.
    max_batch_slots: int = Field(8, ge=1)
    # Paged KV cache: positions per block, and the pool size in blocks
    # (0 = derived: 1 null block + max_batch_slots worst-case sequences).
    block_tokens: int = Field(16, ge=1)
    num_blocks: int = Field(0, ge=0)
    # Shape buckets bounding compiles: prompts pad to the smallest
    # prompt_bucket >= their length, the decode batch to the smallest
    # batch_bucket >= the in-flight count. Empty = powers of two up to
    # block_size / max_batch_slots. The engine asserts the compiled
    # program count stays within len(prompt)+len(batch) buckets.
    prompt_buckets: list[int] = Field(default_factory=list)
    batch_buckets: list[int] = Field(default_factory=list)
    # Scheduler policy: 'paged' = continuous batching (throughput);
    # 'speculative' = draft-and-verify decode per request (latency; needs
    # serve --draft-config/--draft-from, occupancy stays 1).
    policy: Literal["paged", "speculative"] = "paged"
    speculative_gamma: int = Field(4, ge=1)
    # Shared-prefix KV reuse: content-addressed read-only prefix blocks
    # with refcounts and copy-on-write at the first divergent token
    # (serving/paged_kv.py).
    prefix_cache: bool = False
    # Chunked prefill: > 0 splits long prompts into chunks of at most
    # this many tokens, interleaved one per scheduler step with decode —
    # long prompts stop blocking in-flight decodes, and the compile
    # budget grows only by the chunk's bucket. 0 = whole-prompt prefill.
    # Incompatible with the speculative policy.
    prefill_chunk: int = Field(0, ge=0)
    # Replica-router tier (`llmtrain serve --router`).
    router: RouterConfig = Field(default_factory=RouterConfig)
    # SLO-aware overload control (admission, priorities, shedding,
    # brownout) for the continuous scheduler.
    overload: OverloadConfig = Field(default_factory=OverloadConfig)
    # Request validation caps (shared by both modes).
    max_new_tokens_cap: int = Field(256, ge=1)
    default_max_new_tokens: int = Field(48, ge=1)
    # Handler threads give up on a queued request after this long.
    request_timeout_sec: float = Field(120.0, gt=0.0)
    # /healthz turns 503 when the scheduler loop's step beacon is older
    # than this (or the thread is dead) — the k8s livenessProbe contract.
    liveness_stale_sec: float = Field(30.0, gt=0.0)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_buckets(self) -> Self:
        for name, buckets in (
            ("prompt_buckets", self.prompt_buckets),
            ("batch_buckets", self.batch_buckets),
        ):
            if any(b < 1 for b in buckets):
                raise ValueError(f"serving.{name} entries must be >= 1")
            if buckets != sorted(buckets):
                raise ValueError(f"serving.{name} must be ascending")
        if self.batch_buckets and self.batch_buckets[-1] != self.max_batch_slots:
            raise ValueError(
                "the largest serving.batch_bucket must equal "
                f"serving.max_batch_slots ({self.max_batch_slots})"
            )
        if self.num_blocks and self.num_blocks < 2:
            raise ValueError("serving.num_blocks must be 0 (derived) or >= 2")
        if self.prefill_chunk and self.policy == "speculative":
            raise ValueError(
                "serving.prefill_chunk requires the paged policy — the "
                "speculative draft loop prefills whole prompts"
            )
        if (
            self.prefill_chunk
            and self.prompt_buckets
            and self.prefill_chunk > self.prompt_buckets[-1]
        ):
            raise ValueError(
                f"serving.prefill_chunk ({self.prefill_chunk}) exceeds the "
                f"largest prompt bucket ({self.prompt_buckets[-1]}) — chunks "
                "must pad into an existing bucket"
            )
        return self


class FleetTenantConfig(BaseModel):
    """One tenant of the multi-tenant fleet supervisor (llmtrain_tpu/fleet/,
    ``llmtrain fleet``, docs/robustness.md "Fleet: many tenants, shared
    capacity").

    A tenant is a full training job derived from the enclosing config:
    ``overrides`` deep-merges into the resolved base (different lr, LoRA
    block, data mix, ...), the supervisor re-roots its output under the
    fleet work dir and launches it as a real ``train --auto-resume``
    subprocess with a stable run id (= the tenant name), so evictions
    resume from the newest commit and ``resilience/resume_count`` keeps
    accumulating across respawns.

    ``min_devices``/``max_devices`` bound the tenant's data-parallel world
    size on the shared pool (``max_devices`` is the quota). The scheduler
    only ever assigns world sizes that divide the tenant's global
    micro-batch (``trainer.micro_batch_size`` after overrides) so every
    resize is an ELASTIC topology change — ``micro_batch_size × dp`` stays
    constant and the trajectory is preserved (resilience/elastic.py).
    """

    name: str
    # Higher priority wins capacity first; ties break by name so the
    # scheduling policy is a deterministic pure function.
    priority: int = 0
    min_devices: int = Field(1, ge=1)
    max_devices: int = Field(1, ge=1)
    overrides: dict[str, Any] = Field(default_factory=dict)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_bounds(self) -> Self:
        if self.max_devices < self.min_devices:
            raise ValueError(
                f"tenant {self.name!r}: max_devices ({self.max_devices}) "
                f"must be >= min_devices ({self.min_devices})"
            )
        if not self.name or "/" in self.name or self.name.startswith("."):
            raise ValueError(
                "tenant names become run ids and directory names; "
                f"{self.name!r} is not a safe path component"
            )
        return self


class FleetConfig(BaseModel):
    """Multi-tenant fleet supervisor over a bounded emulated device pool
    (llmtrain_tpu/fleet/supervisor.py).

    ``pool_devices`` bounds total capacity; the deterministic scheduling
    policy (fleet/policy.py) grants every runnable tenant its
    ``min_devices`` in priority order, suspends (never crashes) what no
    longer fits when the pool shrinks, and grows tenants toward their
    quota with whatever is left. Preemption is graceful-first:
    SIGTERM (clean preemption save) → ``preempt_grace_sec`` deadline →
    SIGKILL, with seeded full-jitter backoff (``retry_rng``) pacing each
    tenant's respawns.
    """

    pool_devices: int = Field(2, ge=1)
    tenants: list[FleetTenantConfig] = Field(default_factory=list)
    # Escalation ladder: how long a SIGTERM'd tenant gets to finish its
    # clean preemption save before the supervisor hard-kills it.
    preempt_grace_sec: float = Field(20.0, gt=0.0)
    # Full-jitter respawn backoff (resilience/faults.py retry semantics):
    # eviction k of a tenant sleeps uniform(0, min(max, base·2^(k-1))).
    respawn_backoff_base_sec: float = Field(0.05, ge=0.0)
    respawn_backoff_max_sec: float = Field(2.0, gt=0.0)
    # Supervisor reconcile cadence.
    tick_sec: float = Field(0.1, gt=0.0)
    # A tenant exceeding this many respawns is failed instead of
    # crash-looping the pool forever.
    max_respawns_per_tenant: int = Field(20, ge=1)
    # Per-segment wall-clock budget; a tenant subprocess exceeding it is
    # killed and the drill invariant machinery reports the wedge.
    segment_timeout_sec: float = Field(600.0, gt=0.0)
    # A running tenant whose watchdog heartbeat file is staler than this
    # is counted unhealthy in the fleet view (llmtrain_fleet_* gauges).
    heartbeat_stale_sec: float = Field(30.0, gt=0.0)

    model_config = _STRICT

    @model_validator(mode="after")
    def check_tenants(self) -> Self:
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"fleet tenant names must be unique, got {names}")
        for t in self.tenants:
            if t.min_devices > self.pool_devices:
                raise ValueError(
                    f"tenant {t.name!r} needs min_devices={t.min_devices} "
                    f"but the pool only has {self.pool_devices} devices — "
                    "it could never be scheduled"
                )
        return self


class MLflowConfig(BaseModel):
    """MLflow tracking options (reference schemas.py:123-136).

    Divergence: ``backend`` selects the tracking implementation —
    ``auto`` (default) uses the MLflow client when the extra is
    importable and falls back to the dependency-free native SQLite store
    (tracking/sqlite.py) otherwise; ``mlflow``/``native`` force one, and
    ``tensorboard`` writes native TensorBoard event files
    (tracking/tensorboard.py, ``tracking_uri`` is the logdir). The
    reference always requires the mlflow package when enabled.
    """

    enabled: bool = True
    tracking_uri: str = "file:./mlruns"
    experiment: str = "llm-train-k8s"
    run_name: str | None = None
    log_models: bool = False
    backend: Literal["auto", "mlflow", "native", "tensorboard"] = "auto"

    model_config = _STRICT


class LoggingConfig(BaseModel):
    """Structured-logging settings (reference schemas.py:139-151, unchanged)."""

    level: Literal["DEBUG", "INFO", "WARNING", "ERROR"] = "INFO"
    json_output: bool = True
    log_to_file: bool = True
    file_name: str = "train.log"

    model_config = _STRICT


class OutputConfig(BaseModel):
    """Run-dir paths and persistence toggles (reference schemas.py:154-166)."""

    root_dir: str = "runs"
    run_id: str | None = None
    save_config_copy: bool = True
    save_meta_json: bool = True

    model_config = _STRICT


class TuneConfig(BaseModel):
    """Mesh-plan auto-tuner knobs (llmtrain_tpu/autotune/, ``llmtrain tune``,
    docs/perf.md "Mesh planning and auto-tuning").

    The tuner enumerates mesh shape × microbatch × remat × zero stage,
    prunes analytically (roofline + predicted HBM, autotune/search.py),
    then probe-fits the survivors as short subprocess runs scored by the
    measured ``perf_attribution`` MFU. Every knob here bounds device
    time, not correctness — the emitted config re-validates through this
    very schema before it is written.
    """

    # Optimizer steps per probe fit (enough for compile + a few measured
    # steps; the first step's compile time is excluded by the metrics).
    probe_steps: int = Field(4, ge=1)
    # Wall-clock cap per probe subprocess; timeouts score as failures.
    probe_timeout_sec: float = Field(120.0, gt=0.0)
    # Total measuring budget: once spent, remaining survivors are skipped
    # (recorded in the tune report, never silently).
    budget_sec: float = Field(600.0, gt=0.0)
    # Survivor cap after analytic pruning; the baseline probe is exempt.
    max_probes: int = Field(4, ge=1)
    # Explicit microbatch grid; empty = {mb/2, mb, 2·mb} around the
    # config's trainer.micro_batch_size.
    microbatch_candidates: list[int] = Field(default_factory=list)
    # Which dimensions to search; a disabled dimension stays pinned at
    # the config's value.
    search_mesh: bool = True
    search_remat: bool = True
    search_zero: bool = True
    # Only propose plans the elastic-resume topology matrix would accept
    # from the current config's topology (resilience/elastic.py) — for
    # re-tuning a run that must resume from its existing checkpoints.
    preserve_topology: bool = False
    # Per-device HBM feasibility limit override (bytes). None = the
    # DEVICE_HBM_BYTES row for the detected device kind.
    hbm_limit_bytes: float | None = Field(None, gt=0.0)
    # Candidate-order shuffle seed; None = run.seed.
    seed: int | None = None

    model_config = _STRICT

    @model_validator(mode="after")
    def check_candidates(self) -> Self:
        if any(m < 1 for m in self.microbatch_candidates):
            raise ValueError("tune.microbatch_candidates entries must be >= 1")
        return self


class PromoteConfig(BaseModel):
    """Promotion-lifecycle knobs (llmtrain_tpu/lifecycle/, ``llmtrain
    promote``, docs/robustness.md "Canary, promote, rollback").

    The controller watches a training run's manifest stream
    (``latest_valid_checkpoint`` polling — durable artifacts only, the
    goodput stance), canaries every new commit on one designated replica,
    scores it over a soak window, then promotes fleet-wide or rolls the
    canary back. All gates are regression DELTAS against the previously
    promoted baseline, so the loop needs no absolute SLO numbers.
    """

    # Manifest-stream poll cadence on the watched run dir.
    poll_sec: float = Field(2.0, gt=0.0)
    # No new commit AND no training heartbeat for this long → the run is
    # presumed finished/dead and promote exits (taxonomy code).
    idle_timeout_sec: float = Field(600.0, gt=0.0)
    # Replica index that receives canary swaps (the rest keep serving
    # the promoted params).
    canary_replica: int = Field(0, ge=0)
    # Live-traffic fraction the router steers to the canary during the
    # soak (A/B split at the placement layer). 0 = synthetic soak probes
    # only, live traffic never touches the canary.
    traffic_split: float = Field(0.0, ge=0.0, le=1.0)
    # Synthetic soak probes the controller sends to the canary replica to
    # populate TTFT / per-token reservoirs before judging.
    soak_requests: int = Field(16, ge=1)
    soak_timeout_sec: float = Field(120.0, gt=0.0)
    soak_seed: int = 0
    # Gate 1 — eval regression: candidate held-out loss may exceed the
    # promoted baseline's by at most this much.
    max_eval_loss_delta: float = Field(0.05, ge=0.0)
    # Gate 2 — SLO regression: canary p95 TTFT / p99 per-token latency
    # may exceed the baseline percentile by at most this factor (2.0 =
    # twice as slow). None disables the bound.
    ttft_p95_slowdown: float | None = Field(2.0, gt=1.0)
    per_token_p99_slowdown: float | None = Field(2.0, gt=1.0)
    # Any soak-window failed/timed-out canary request fails the gate.
    allow_failed_requests: int = Field(0, ge=0)
    # Stop after this many promotions (0 = run until the stream ends).
    max_promotions: int = Field(0, ge=0)

    model_config = _STRICT


class RunConfig(BaseModel):
    """Top-level schema tying every section into one executable run.

    Mirrors reference schemas.py:169-186 with ``ddp`` → ``distributed``.
    """

    schema_version: int = Field(1, ge=1)
    run: RunSectionConfig
    model: ModelConfig
    data: DataConfig
    trainer: TrainerConfig
    distributed: DistributedConfig = Field(default_factory=DistributedConfig)
    resilience: ResilienceConfig = Field(default_factory=ResilienceConfig)
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    fleet: FleetConfig = Field(default_factory=FleetConfig)
    mlflow: MLflowConfig = Field(default_factory=MLflowConfig)
    logging: LoggingConfig = Field(default_factory=LoggingConfig)
    output: OutputConfig = Field(default_factory=OutputConfig)
    tune: TuneConfig = Field(default_factory=TuneConfig)
    promote: PromoteConfig = Field(default_factory=PromoteConfig)

    model_config = _STRICT
