"""Command-line interface.

Parity target: reference ``src/llmtrain/cli.py`` — argparse CLI with
``train``/``validate``/``print-config`` subcommands (:145-157), required
``--config``, train-only ``--run-id``/``--dry-run``/``--json``/``-v``/
``--resume`` (:147-151), exit codes 0/1 (training failure, :304)/2 (config
error, :167), JSON errors to stderr (:63-76), and the train orchestration:
distributed setup → run dir → logging → registries → tracker → Trainer/dry
run → summary → artifact logging → teardown in ``finally`` (:201-328).
Under ``--json``, logs go to stderr so stdout carries only the summary JSON
(:281-288).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Any

from . import __version__
from .config import ConfigLoadError, load_and_validate_config
from .distributed import (
    DistState,
    configure_compilation_cache,
    configure_platform,
    setup_distributed,
    teardown_distributed,
)
from .registry import (
    RegistryError,
    get_data_module,
    get_model_adapter,
    initialize_registries,
)
from .resilience.exit_codes import (
    EXIT_CONFIG_ERROR,
    EXIT_OK,
    EXIT_RETRYABLE_INFRA,
    EXIT_TRAIN_FAILURE,
    exit_code_for_exception,
)
from .tracking import NullTracker, Tracker, build_tracker
from .utils import (
    configure_logging,
    create_run_directory,
    format_run_summary,
    generate_meta,
    generate_run_id,
    get_logger,
    write_meta_json,
    write_resolved_config,
)

# Exit codes come from the taxonomy module (resilience/exit_codes.py):
# 0 clean, 1 fatal training, 2 fatal config, 75/76 retryable infra/hang.
# The names are re-exported here so `cli.EXIT_*` keeps working.


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="llmtrain",
        description="TPU-native config-driven LLM training",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command", required=True)

    train = sub.add_parser("train", help="run a training job")
    train.add_argument("--config", required=True, help="path to the YAML run config")
    train.add_argument("--run-id", default=None, help="override the generated run id")
    train.add_argument("--dry-run", action="store_true", help="forward-only sanity check")
    train.add_argument("--json", action="store_true", help="emit the run summary as JSON")
    train.add_argument("-v", "--verbose", action="store_true", help="DEBUG logging")
    resume_group = train.add_mutually_exclusive_group()
    resume_group.add_argument(
        "--resume",
        default=None,
        help="checkpoint file, checkpoint dir, or run id to resume from",
    )
    resume_group.add_argument(
        "--auto-resume",
        action="store_true",
        help=(
            "reuse the run dir for --run-id if it exists and resume from its "
            "latest checkpoint (fresh start otherwise); for preemptible pods"
        ),
    )

    gen = sub.add_parser(
        "generate", help="sample completions from a trained checkpoint"
    )
    gen.add_argument("--config", required=True, help="path to the YAML run config")
    gen.add_argument(
        "--from",
        dest="from_spec",
        required=True,
        help="checkpoint file, checkpoint dir, or run id to load params from",
    )
    prompt_group = gen.add_mutually_exclusive_group(required=True)
    prompt_group.add_argument("--prompt", default=None, help="prompt text (needs a tokenizer)")
    prompt_group.add_argument(
        "--prompt-ids",
        default=None,
        help="comma-separated token ids, bypassing the tokenizer",
    )
    prompt_group.add_argument(
        "--prompts-file",
        default=None,
        help="file with one prompt per line (blank lines skipped); prompts "
        "are batched per token length for the compiled decode loop",
    )
    gen.add_argument("--max-new-tokens", type=int, default=48)
    gen.add_argument(
        "--temperature", type=float, default=0.8, help="0 decodes greedily"
    )
    gen.add_argument("--top-k", type=int, default=40, help="0 disables top-k filtering")
    gen.add_argument(
        "--top-p",
        type=float,
        default=None,
        help="nucleus sampling: keep the smallest token set with this "
        "probability mass, 0 < p < 1 (0 or 1 disables, like --top-k 0)",
    )
    gen.add_argument(
        "--eos-token-id",
        type=int,
        default=None,
        help="stop early on this token (default: the tokenizer's EOS, if any)",
    )
    gen.add_argument("--seed", type=int, default=1234)
    gen.add_argument(
        "--decode-param-dtype",
        choices=("compute", "param"),
        default="compute",
        help="'compute' (default) casts floating checkpoint params to the "
        "model compute dtype before decoding — a bf16-compute model then "
        "streams half the weight bytes per token (decode is weight-bandwidth "
        "bound; tools/diag_decode.py attribution); 'param' keeps the "
        "checkpoint's master precision",
    )
    gen.add_argument(
        "--draft-config",
        default=None,
        help="YAML config of a DRAFT model for speculative decoding "
        "(requires --draft-from; same tokenizer/vocab as the target)",
    )
    gen.add_argument(
        "--draft-from",
        default=None,
        help="checkpoint file, dir, or run id for the draft model's params",
    )
    gen.add_argument(
        "--gamma",
        type=int,
        default=4,
        help="speculative lookahead: draft tokens proposed per target forward",
    )
    gen.add_argument(
        "--logprobs",
        action="store_true",
        help="include the model's log-probability of every emitted token "
        "in the JSON output (not supported with --draft-config)",
    )
    gen.add_argument(
        "--ema",
        action="store_true",
        help="decode with the EMA shadow weights tracked by "
        "trainer.extra.ema_decay (errors if the checkpoint has none)",
    )
    gen.add_argument(
        "--quantize",
        choices=("none", "int8"),
        default="none",
        help="weight-only quantization applied after checkpoint load "
        "(ops/quant.py): int8 halves the weight bytes each decoded token "
        "streams vs bf16 (decode is weight-bandwidth bound); applies to "
        "the draft model too under speculative decoding",
    )
    gen.add_argument("--json", action="store_true", help="emit the result as JSON")

    serve = sub.add_parser(
        "serve",
        help="HTTP inference server over the compiled decode loop "
        "(GET /healthz, POST /v1/generate)",
    )
    serve.add_argument("--config", required=True, help="path to the YAML run config")
    serve.add_argument(
        "--from",
        dest="from_spec",
        required=True,
        help="checkpoint file, checkpoint dir, or run id to serve",
    )
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument(
        "--port",
        type=int,
        default=8000,
        help="0 binds an ephemeral port (printed on the ready line)",
    )
    serve.add_argument(
        "--max-new-tokens-cap",
        type=int,
        default=None,
        help="upper bound a request's max_new_tokens may ask for "
        "(default: serving.max_new_tokens_cap from the config)",
    )
    serve.add_argument(
        "--mode",
        choices=("simple", "continuous"),
        default=None,
        help="override serving.mode: 'simple' = one decode at a time "
        "behind the device lock; 'continuous' = paged-KV continuous "
        "batching (N in-flight sequences share one jitted program)",
    )
    serve.add_argument(
        "--draft-config",
        default=None,
        help="YAML config of a DRAFT model: switches the continuous "
        "scheduler to the speculative policy (requires --draft-from)",
    )
    serve.add_argument(
        "--draft-from",
        default=None,
        help="checkpoint file, dir, or run id for the draft model's params",
    )
    serve.add_argument(
        "--gamma",
        type=int,
        default=None,
        help="speculative lookahead (default: serving.speculative_gamma)",
    )
    serve.add_argument(
        "--decode-param-dtype",
        choices=("compute", "param"),
        default="compute",
        help="as in generate: 'compute' streams half the weight bytes "
        "per token for bf16-compute models",
    )
    serve.add_argument(
        "--ema",
        action="store_true",
        help="serve the EMA shadow weights (errors if the checkpoint has none)",
    )
    serve.add_argument(
        "--quantize",
        choices=("none", "int8"),
        default="none",
        help="serve weight-only int8 quantized weights (ops/quant.py)",
    )
    serve.add_argument(
        "--eos-token-id",
        type=int,
        default=None,
        help="default stop token (requests may override; default: the "
        "tokenizer's EOS, if any)",
    )
    serve.add_argument(
        "--router",
        action="store_true",
        help="run the fleet tier: a replica router placing each request "
        "by prefix-cache affinity and load, with rolling zero-downtime "
        "POST /reload (needs the continuous backend)",
    )
    serve.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="in-process replica count for --router "
        "(default: serving.router.replicas)",
    )
    serve.add_argument(
        "--backends",
        default=None,
        help="comma-separated replica base URLs (http://host:port) — "
        "route across separate serve processes instead of in-process "
        "replicas (implies --router)",
    )
    serve.add_argument(
        "--discover",
        default=None,
        help="host[:port] DNS-resolved into one HTTP backend per A "
        "record (k8s headless Service discovery; implies --router)",
    )
    serve.add_argument(
        "--trace-dir",
        default=None,
        help="write per-process timeline.jsonl files (with tail-sampled "
        "request traces) plus a metrics.prom textfile snapshot under "
        "this dir, for `llmtrain trace` to merge; with --router each "
        "in-process replica gets its own subdir",
    )

    promote = sub.add_parser(
        "promote",
        help="continuous train→canary→promote lifecycle: watch a training "
        "run's manifest stream, canary each new commit on one replica, "
        "score it (eval loss + TTFT/per-token SLO soak), then promote "
        "fleet-wide or auto-roll-back (lifecycle/, docs/robustness.md "
        "'Canary, promote, rollback')",
    )
    promote.add_argument(
        "--config", required=True, help="path to the YAML run config"
    )
    promote.add_argument(
        "--watch",
        required=True,
        help="training run dir (or its checkpoints/ dir) whose manifest "
        "stream to watch; promotions.jsonl is written next to the run's "
        "other durable artifacts",
    )
    promote.add_argument(
        "--from",
        dest="from_spec",
        default=None,
        help="initial baseline checkpoint to serve (default: the last "
        "promoted entry in promotions.jsonl, else the stream's newest "
        "commit — promote waits for the first one if needed)",
    )
    promote.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="in-process fleet size (default: serving.router.replicas)",
    )
    promote.add_argument(
        "--max-promotions",
        type=int,
        default=None,
        help="stop after this many promotions (default: promote.max_promotions; "
        "0 = run until the stream ends)",
    )
    promote.add_argument(
        "--idle-timeout-sec",
        type=float,
        default=None,
        help="exit after this long with no new commit and no training "
        "heartbeat (default: promote.idle_timeout_sec)",
    )
    promote.add_argument(
        "--no-eval",
        action="store_true",
        help="skip the held-out eval-loss gate (soak/SLO gates still run)",
    )
    promote.add_argument("--json", action="store_true", help="emit the result as JSON")
    # Decode-stack flags shared with serve's loaders (promote keeps the
    # defaults; the flags exist so _load_decode_params is reused as-is).
    promote.set_defaults(
        draft_config=None,
        draft_from=None,
        gamma=None,
        backends=None,
        discover=None,
        decode_param_dtype="compute",
        quantize="none",
        ema=False,
        mode="continuous",
        router=True,
    )

    bench = sub.add_parser(
        "serve-bench",
        help="seeded open-loop load generator against the continuous-"
        "batching scheduler: p50/p95/p99 TTFT + per-token latency, "
        "tokens/s, occupancy, compile budget — written to report.json/"
        "report.md (docs/serving.md)",
    )
    bench.add_argument("--config", required=True, help="path to the YAML run config")
    bench.add_argument(
        "--from",
        dest="from_spec",
        required=True,
        help="checkpoint file, checkpoint dir, or run id to serve",
    )
    bench.add_argument(
        "--requests", type=int, default=16, help="request population size"
    )
    bench.add_argument(
        "--rate-rps",
        type=float,
        default=8.0,
        help="open-loop Poisson arrival rate (requests/second); arrivals "
        "never wait for completions",
    )
    bench.add_argument("--seed", type=int, default=1234)
    bench.add_argument(
        "--prompt-tokens-min", type=int, default=4, help="shortest prompt"
    )
    bench.add_argument(
        "--prompt-tokens-max",
        type=int,
        default=0,
        help="longest prompt (0 = derived: min(32, block_size - max_new))",
    )
    bench.add_argument("--max-new-tokens", type=int, default=16)
    bench.add_argument(
        "--temperature",
        type=float,
        default=0.0,
        help="0 = greedy (the regime the parity check pins)",
    )
    bench.add_argument("--top-k", type=int, default=None)
    bench.add_argument("--top-p", type=float, default=None)
    bench.add_argument(
        "--timeout-sec",
        type=float,
        default=300.0,
        help="give up on unfinished requests after this long",
    )
    bench.add_argument(
        "--verify-parity",
        action="store_true",
        help="re-decode every request through sequential generate() and "
        "assert batched output token-ids are bitwise identical (exits "
        "nonzero on any mismatch)",
    )
    bench.add_argument(
        "--out",
        default=None,
        help="report directory (default: <output.root_dir>/serve_bench)",
    )
    bench.add_argument(
        "--decode-param-dtype",
        choices=("compute", "param"),
        default="compute",
        help="as in generate/serve",
    )
    bench.add_argument("--ema", action="store_true")
    bench.add_argument(
        "--quantize", choices=("none", "int8"), default="none",
        help="weight-only int8 quantization (ops/quant.py)",
    )
    bench.add_argument(
        "--draft-config",
        default=None,
        help="draft model config for the speculative scheduler policy",
    )
    bench.add_argument(
        "--draft-from", default=None, help="draft model checkpoint/run id"
    )
    bench.add_argument(
        "--gamma", type=int, default=None,
        help="speculative lookahead (default: serving.speculative_gamma)",
    )
    bench.add_argument(
        "--router",
        action="store_true",
        help="drive the replica-router tier instead of one scheduler "
        "(in-process replicas; the report gains fleet prefix hit rate "
        "and per-replica occupancy)",
    )
    bench.add_argument(
        "--replicas",
        type=int,
        default=None,
        help="in-process replica count for --router "
        "(default: serving.router.replicas)",
    )
    bench.add_argument(
        "--shared-prefix-tokens",
        type=int,
        default=0,
        help="prepend one of --shared-prefix-count fixed 'system "
        "prompts' of this many tokens to every request — the workload "
        "shared-prefix KV reuse and router affinity pay off on",
    )
    bench.add_argument(
        "--shared-prefix-count", type=int, default=1,
        help="distinct shared prefixes to draw from",
    )
    bench.add_argument(
        "--long-fraction",
        type=float,
        default=0.0,
        help="fraction of requests using --long-prompt-tokens prompts "
        "(the bimodal long/short mix chunked prefill exists for)",
    )
    bench.add_argument(
        "--long-prompt-tokens", type=int, default=0,
        help="prompt length of the long cohort",
    )
    bench.add_argument(
        "--max-per-token-p99-ms",
        type=float,
        default=None,
        help="fail the run if per-token p99 latency exceeds this bound "
        "(the head-of-line-blocking SLO chunked prefill protects)",
    )
    bench.add_argument(
        "--arrival",
        choices=("poisson", "burst"),
        default="poisson",
        help="arrival process: steady Poisson, or 'burst' (head/tail 20%% "
        "at --rate-rps, middle 60%% at rate * --burst-factor) — the "
        "seeded overload drill for admission control and brownout",
    )
    bench.add_argument(
        "--burst-factor",
        type=float,
        default=10.0,
        help="rate multiplier for the burst window of --arrival burst",
    )
    bench.add_argument(
        "--deadline-ms",
        type=float,
        default=None,
        help="stamp every request with this latency budget; the overload "
        "controller rejects/sheds requests that cannot meet it "
        "(needs serving.overload.enabled)",
    )
    bench.add_argument(
        "--batch-fraction",
        type=float,
        default=0.0,
        help="seeded fraction of requests submitted as priority=batch "
        "(the mixed-class workload the weighted dequeue serves)",
    )
    bench.add_argument(
        "--max-rejected-frac",
        type=float,
        default=None,
        help="fail the run if (rejected+shed)/submitted exceeds this "
        "bound — overload behavior gateable like parity",
    )

    evalp = sub.add_parser(
        "eval", help="run the validation loop on a checkpoint, no training"
    )
    evalp.add_argument("--config", required=True, help="path to the YAML run config")
    evalp.add_argument(
        "--from",
        dest="from_spec",
        default=None,
        help="checkpoint file, checkpoint dir, or run id to evaluate "
        "(default: the freshly initialized model)",
    )
    evalp.add_argument(
        "--ema",
        action="store_true",
        help="evaluate the EMA shadow weights tracked by "
        "trainer.extra.ema_decay (errors if the checkpoint has none)",
    )
    evalp.add_argument(
        "--quantize",
        choices=("none", "int8"),
        default="none",
        help="evaluate under weight-only int8 quantization (ops/quant.py) "
        "— measures the quality cost of the quantized serving path on "
        "the real validation split (composes with --ema)",
    )
    evalp.add_argument("--json", action="store_true", help="emit metrics as JSON")
    evalp.add_argument("-v", "--verbose", action="store_true", help="DEBUG logging")

    traintok = sub.add_parser(
        "train-tokenizer",
        help="train an offline byte-level BPE vocabulary on local text",
    )
    traintok.add_argument(
        "--input",
        required=True,
        action="append",
        help="text file or directory (repeatable); directories are read "
        "recursively for *.txt/*.md/*.py files",
    )
    traintok.add_argument("--vocab-size", type=int, default=8192)
    traintok.add_argument("--output", required=True, help="vocabulary JSON path")
    traintok.add_argument(
        "--max-bytes",
        type=int,
        default=64_000_000,
        help="cap on corpus bytes read for training",
    )
    traintok.add_argument("--json", action="store_true", help="emit stats as JSON")

    export = sub.add_parser(
        "export-checkpoint",
        help="export checkpoint weights as a torch state dict (gpt → "
        "reference GPT names, llama → HF LlamaForCausalLM names)",
    )
    export.add_argument("--config", required=True, help="path to the YAML run config")
    export.add_argument(
        "--from",
        dest="from_spec",
        required=True,
        help="checkpoint file, checkpoint dir, or run id to export",
    )
    export.add_argument("--output", required=True, help="output .pt path")
    export.add_argument(
        "--ema",
        action="store_true",
        help="export the EMA shadow weights tracked by "
        "trainer.extra.ema_decay (errors if the checkpoint has none)",
    )
    export.add_argument("--json", action="store_true", help="emit stats as JSON")

    imp = sub.add_parser(
        "import-checkpoint",
        help="build a resumable checkpoint from a torch state dict "
        "(gpt ← reference GPT names, llama ← HF LlamaForCausalLM names)",
    )
    imp.add_argument("--config", required=True, help="path to the YAML run config")
    imp.add_argument("--input", required=True, help="torch .pt state-dict path")
    imp.add_argument(
        "--output",
        required=True,
        help="checkpoint directory to write step_000000.ckpt into "
        "(use with train --resume <dir>)",
    )
    imp.add_argument("--json", action="store_true", help="emit stats as JSON")

    avg = sub.add_parser(
        "average-checkpoints",
        help="average the params of several checkpoints (model soup) into "
        "a resumable step-0 checkpoint",
    )
    avg.add_argument("--config", required=True, help="path to the YAML run config")
    avg.add_argument(
        "--inputs",
        required=True,
        help="comma-separated checkpoint files/dirs/run-ids (each resolved "
        "like --resume), OR one checkpoint dir with --last-k",
    )
    avg.add_argument(
        "--last-k",
        type=int,
        default=0,
        help="average the last K step_*.ckpt files of the single --inputs dir",
    )
    avg.add_argument(
        "--output",
        required=True,
        help="empty checkpoint directory to write step_000000.ckpt into",
    )
    avg.add_argument("--json", action="store_true", help="emit stats as JSON")

    fleet = sub.add_parser(
        "fleet",
        help="multi-tenant fleet supervisor: schedule fleet.tenants onto a "
        "bounded emulated device pool with preemption-aware scheduling, "
        "quotas, and the SIGTERM->SIGKILL escalation ladder (fleet/, "
        "docs/robustness.md)",
    )
    fleet.add_argument("--config", required=True, help="path to the YAML run config")
    fleet.add_argument(
        "--storm",
        action="store_true",
        help="run the seeded preemption-storm acceptance drill instead of "
        "a plain fleet run: capacity drop + seeded evictions + one "
        "mid-checkpoint kill, then per-tenant bitwise parity against "
        "uninterrupted references (fleet/chaos.py)",
    )
    fleet.add_argument(
        "--seed", type=int, default=0, help="seed for the storm schedule "
        "and the per-tenant respawn-backoff streams"
    )
    fleet.add_argument(
        "--max-steps",
        type=int,
        default=None,
        help="override trainer.max_steps for every tenant (keep it small)",
    )
    fleet.add_argument(
        "--save-every",
        type=int,
        default=None,
        help="override trainer.save_every_steps for every tenant",
    )
    fleet.add_argument(
        "--work-dir",
        default=None,
        help="supervisor working directory (default: "
        "{output.root_dir}/fleet_{run.name} or fleet_storm_{run.name}_s{seed})",
    )
    fleet.add_argument(
        "--timeout-sec",
        type=float,
        default=900.0,
        help="whole-fleet wall-clock budget",
    )
    fleet.add_argument(
        "--step-delay-sec",
        type=float,
        default=0.15,
        help="storm only: per-step tenant throttle so external evictions "
        "land mid-run (trainer.extra.step_delay_sec)",
    )
    fleet.add_argument(
        "--fresh",
        action="store_true",
        help="wipe the work dir's runs tree before starting (default: a "
        "restarted supervisor auto-resumes every tenant from its newest "
        "commit; --storm always starts fresh)",
    )
    fleet.add_argument("--json", action="store_true", help="emit the result as JSON")

    chaos = sub.add_parser(
        "chaos",
        help="seeded chaos-recovery drill: repeated SIGKILL/resume cycles "
        "with crash-consistency invariants checked after every cycle "
        "(resilience/chaos.py, docs/robustness.md)",
    )
    chaos.add_argument("--config", required=True, help="path to the YAML run config")
    chaos.add_argument(
        "--cycles",
        type=int,
        default=5,
        help="number of killed segments before the final uninterrupted one",
    )
    chaos.add_argument(
        "--seed", type=int, default=0, help="seed for the kill-step schedule"
    )
    chaos.add_argument(
        "--max-steps",
        type=int,
        default=None,
        help="override trainer.max_steps for the drill (keep it small)",
    )
    chaos.add_argument(
        "--save-every",
        type=int,
        default=None,
        help="override trainer.save_every_steps for the drill",
    )
    chaos.add_argument(
        "--work-dir",
        default=None,
        help="harness working directory (default: "
        "{output.root_dir}/chaos_{run.name}_s{seed})",
    )
    chaos.add_argument(
        "--timeout-sec",
        type=float,
        default=600.0,
        help="per-segment wall-clock budget",
    )
    chaos.add_argument("--json", action="store_true", help="emit the result as JSON")

    profile = sub.add_parser(
        "profile",
        help="N-step cost probe: XLA cost_analysis + roofline attribution "
        "of the jitted train step (and the paged serving buckets with "
        "--serve) written as profile_report.json "
        "(telemetry/profiling.py, docs/observability.md)",
    )
    profile.add_argument("--config", required=True, help="path to the YAML run config")
    profile.add_argument(
        "--steps",
        type=int,
        default=3,
        help="probe training steps to run for measured step time (default 3)",
    )
    profile.add_argument(
        "--serve",
        action="store_true",
        help="also AOT-profile the paged prefill/decode programs at their "
        "largest shape buckets (abstract shapes; no checkpoint needed)",
    )
    profile.add_argument(
        "--top-k",
        type=int,
        default=10,
        help="HLO op-category rows in each executable's top-ops table",
    )
    profile.add_argument(
        "--output",
        default=None,
        help="report path (default {output.root_dir}/profile_{run.name}/"
        "profile_report.json)",
    )
    profile.add_argument(
        "--json", action="store_true", help="print the full report JSON to stdout"
    )

    goodput = sub.add_parser(
        "goodput",
        help="render the wall-clock goodput ledger for any past run from "
        "its durable artifacts alone — no rerun, no live process "
        "(telemetry/goodput.py, docs/observability.md 'Goodput')",
    )
    goodput.add_argument(
        "--run-dir",
        required=True,
        help="run directory holding telemetry/timeline.jsonl (+ optional "
        "checkpoints/ and heartbeat)",
    )
    goodput.add_argument(
        "--json", action="store_true", help="emit the ledger as JSON"
    )

    trace = sub.add_parser(
        "trace",
        help="merge per-process fleet timelines and reassemble cross-"
        "process request traces (telemetry/trace_collect.py, docs/"
        "observability.md 'Distributed request tracing')",
    )
    trace.add_argument(
        "action",
        choices=("slowest", "show", "summary", "merge"),
        help="slowest: top-k traces by end-to-end latency; show: span "
        "tree + critical-path breakdown of one trace; summary: per-span-"
        "kind p50/p95/p99; merge: one Perfetto trace (track group per "
        "process, flow arrows across the router→replica hop)",
    )
    trace.add_argument(
        "trace_id",
        nargs="?",
        default=None,
        help="trace id (or unique prefix) for 'show' — from `trace "
        "slowest`, a response payload, or a /metrics exemplar",
    )
    trace.add_argument(
        "--run-dir",
        action="append",
        required=True,
        dest="run_dirs",
        help="directory (scanned recursively for *timeline*.jsonl) or a "
        "single timeline file; repeatable — pass every fleet process's "
        "dir to stitch the cross-process tree together",
    )
    trace.add_argument(
        "--k", type=int, default=10, help="how many traces 'slowest' lists"
    )
    trace.add_argument(
        "--out",
        default=None,
        help="output path for 'merge' (default: merged_trace.json under "
        "the first --run-dir; open it in ui.perfetto.dev)",
    )
    trace.add_argument(
        "--json", action="store_true", help="emit machine-readable JSON"
    )

    plan = sub.add_parser(
        "plan",
        help="dry-run the mesh planner: resolve the config's MeshPlan, "
        "predict its roofline class and per-device HBM, run nothing "
        "(autotune/plan.py; exit 2 on an infeasible plan)",
    )
    plan.add_argument("--config", required=True, help="path to the YAML run config")
    plan.add_argument(
        "--devices",
        type=int,
        default=None,
        help="plan against this many devices instead of the locally "
        "visible count (lets you vet a pod-slice plan from a laptop)",
    )
    plan.add_argument("--json", action="store_true", help="emit the plan as JSON")

    tune = sub.add_parser(
        "tune",
        help="auto-tune mesh shape x microbatch x activation tiers x zero stage: "
        "analytic roofline/HBM pruning, then short probe fits scored by "
        "measured perf_attribution MFU; emits the winner as a loadable "
        "config (autotune/, docs/perf.md 'Mesh planning and auto-tuning')",
    )
    tune.add_argument("--config", required=True, help="path to the YAML run config")
    tune.add_argument(
        "--output",
        default=None,
        help="emitted config path (default {output.root_dir}/"
        "tune_{run.name}/tuned.yaml)",
    )
    tune.add_argument(
        "--workdir",
        default=None,
        help="probe-run scratch dir (default {output.root_dir}/tune_{run.name})",
    )
    tune.add_argument(
        "--json", action="store_true", help="print the full tune report JSON"
    )

    validate = sub.add_parser("validate", help="validate a config file")
    validate.add_argument("--config", required=True)
    validate.add_argument("--json", action="store_true")

    printcfg = sub.add_parser("print-config", help="print the resolved config")
    printcfg.add_argument("--config", required=True)
    printcfg.add_argument("--json", action="store_true")

    return parser


def _emit_error(message: str, *, details: Any = None, errors: Any = None) -> None:
    payload = {"error": message}
    if details:
        payload["details"] = details
    if errors:
        payload["errors"] = errors
    print(json.dumps(payload), file=sys.stderr)


def _warn_unknown_extras(cfg) -> None:
    """Typos in the ``extra`` escape hatches are warnings, never errors
    (config/extras.py): the knobs are real but plugins may take keys the
    framework cannot know about."""
    try:
        from .config.extras import unknown_extra_keys

        for section, keys in unknown_extra_keys(cfg).items():
            print(
                f"warning: {section} keys not recognized by "
                f"'{cfg.model.name if section == 'model.extra' else cfg.data.name if section == 'data.extra' else 'trainer'}': "
                f"{', '.join(keys)} (typo? they will be ignored)",
                file=sys.stderr,
            )
    except Exception:  # the check must never break a run
        pass


def _lora_spec_error(cfg) -> str | None:
    """A malformed ``model.extra.lora`` is a CONFIG error (exit 2), not a
    training failure — catch it before any jax work (models/lora.py)."""
    try:
        from .models.lora import LoraSpec

        LoraSpec.from_extra(cfg.model.extra)
    except ValueError as exc:
        return str(exc)
    return None


def _handle_validate(args: argparse.Namespace) -> int:
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR
    _warn_unknown_extras(cfg)
    if args.json:
        print(json.dumps({"valid": True, "config": args.config}))
    else:
        print("Config validation succeeded.")
    return EXIT_OK


def _handle_print_config(args: argparse.Namespace) -> int:
    try:
        _, _, resolved = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    if args.json:
        print(json.dumps(resolved, indent=2))
    else:
        import yaml

        print(yaml.safe_dump(resolved, sort_keys=False), end="")
    return EXIT_OK


def _handle_plan(args: argparse.Namespace) -> int:
    """The analytical half of the tuner as a standalone debugging surface:
    resolve, predict, print — nothing runs, no params materialize."""
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR

    from .autotune.plan import MeshPlanError, plan_from_config
    from .autotune.search import analytic_candidate_cost, resolve_hbm_limit
    from .telemetry.profiling import classify_roofline, resolve_peaks

    initialize_registries()
    try:
        adapter = get_model_adapter(cfg.model.name)
    except RegistryError as exc:
        _emit_error(str(exc))
        return EXIT_CONFIG_ERROR
    if args.devices is not None:
        device_count = args.devices
    else:
        import jax

        device_count = jax.device_count()

    try:
        mesh_plan = plan_from_config(cfg, device_count, adapter=adapter)
    except MeshPlanError as exc:
        _emit_error(f"infeasible plan: {exc}")
        return EXIT_CONFIG_ERROR

    peaks = resolve_peaks(None, cfg.telemetry.device_peaks)
    cost = analytic_candidate_cost(mesh_plan, cfg)
    roofline = classify_roofline(
        flops=cost["flops"],
        bytes_accessed=cost["bytes_accessed"],
        collective_bytes=cost["collective_bytes"],
        peaks=peaks,
    )
    from .autotune.plan import config_loss_impl, predict_hbm_bytes

    # Resolve the loss implementation the run would build (dense /
    # chunked_ce / fused_ce) so the verdict charges the right logits
    # buffer — and say which one it assumed.
    loss_impl, ce_chunk = config_loss_impl(cfg)
    hbm = predict_hbm_bytes(
        mesh_plan,
        n_params=int(cost["n_params"]),
        d_model=cfg.model.d_model,
        n_layers=cfg.model.n_layers,
        vocab_size=int(cfg.model.vocab_size or 50257),
        block_size=cfg.model.block_size,
        dtype_bytes=2 if cfg.model.dtype == "bfloat16" else 4,
        param_dtype_bytes=2 if cfg.model.param_dtype == "bfloat16" else 4,
        loss_impl=loss_impl,
        ce_chunk=ce_chunk,
    )
    hbm_limit = resolve_hbm_limit(
        str(peaks.get("device_kind", "cpu")), cfg.tune.hbm_limit_bytes
    )
    feasible = hbm["total_bytes"] <= hbm_limit
    payload = {
        "plan": {
            "key": mesh_plan.key(),
            "mesh": mesh_plan.axes,
            "device_count": device_count,
            "data_parallel": mesh_plan.data_parallel,
            "global_micro_batch": mesh_plan.global_micro_batch,
            "micro_batch_size": mesh_plan.micro_batch_size,
            "grad_accum_steps": mesh_plan.grad_accum_steps,
            "remat": mesh_plan.remat,
            "zero_stage": mesh_plan.zero_stage,
            "activation_tiers": mesh_plan.activation_tiers,
            "loss_impl": loss_impl,
            "topology": mesh_plan.describe_topology(),
        },
        "roofline": roofline,
        "predicted_hbm": hbm,
        "hbm_limit_bytes": hbm_limit,
        "device_kind": peaks.get("device_kind", "unknown"),
        "feasible": feasible,
    }
    if args.json:
        print(json.dumps(payload, indent=2))
    else:
        print(f"plan      {mesh_plan.key()}")
        print(f"mesh      {mesh_plan.axes}")
        print(
            f"batch     micro={mesh_plan.micro_batch_size} "
            f"global_micro={mesh_plan.global_micro_batch} "
            f"accum={mesh_plan.grad_accum_steps}"
        )
        print(
            f"roofline  {roofline['class']} "
            f"(analytical ms: {roofline['analytical_ms']})"
        )
        print(
            f"hbm       {hbm['total_bytes'] / 2**30:.3f} GiB predicted vs "
            f"{hbm_limit / 2**30:.1f} GiB limit "
            f"[{payload['device_kind']}]"
        )
        print(
            f"loss      {loss_impl} "
            f"(logits buffer {hbm['logits_bytes'] / 2**20:.1f} MiB)"
        )
        by_tier = hbm.get("activation_bytes_by_tier", {})
        if by_tier:
            breakdown = " ".join(
                f"{tier}={v / 2**30:.3f}GiB"
                for tier, v in sorted(by_tier.items())
            )
            host_b = hbm.get("activation_host_bytes", 0)
            line = f"acts      {breakdown}"
            if host_b:
                line += f" host_offload={host_b / 2**30:.3f}GiB"
            print(line)
    if not feasible:
        _emit_error(
            "infeasible plan: predicted per-device HBM "
            f"{hbm['total_bytes'] / 2**30:.3f} GiB exceeds the "
            f"{hbm_limit / 2**30:.1f} GiB limit for "
            f"{payload['device_kind']} (override with tune.hbm_limit_bytes)"
        )
        return EXIT_CONFIG_ERROR
    return EXIT_OK


def _handle_tune(args: argparse.Namespace) -> int:
    try:
        cfg, _, resolved = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR

    from .autotune.plan import MeshPlanError
    from .autotune.tune import run_tune

    workdir = Path(args.workdir or Path(cfg.output.root_dir) / f"tune_{cfg.run.name}")
    output_path = Path(args.output or workdir / "tuned.yaml")
    try:
        report = run_tune(
            cfg, resolved, workdir=workdir, output_path=output_path
        )
    except MeshPlanError as exc:
        _emit_error(f"infeasible plan: {exc}")
        return EXIT_CONFIG_ERROR
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        pruned = report["pruned"]
        print(
            f"tune      {report['enumerated']} candidates enumerated, "
            f"{len(pruned)} pruned analytically, "
            f"{len(report['measured'])} probed "
            f"({report['elapsed_sec']:.1f}s of {report['budget_sec']:.0f}s budget)"
        )
        for record in report["measured"]:
            status = record.get("status")
            if status == "ok":
                marker = "*" if record["key"] == report["winner"]["key"] else " "
                print(
                    f"  {marker} {record['key']}: mfu={record['mfu']:.4f} "
                    f"step={record.get('step_time_sec') or 0:.4f}s"
                    + (" (baseline)" if record.get("baseline") else "")
                )
            else:
                print(f"    {record['key']}: {status} ({record.get('reason', '')})")
        print(f"winner    {report['winner']['key']}")
        print(f"emitted   {report['output_config']}")
        print(f"report    {workdir / 'tune_report.json'}")
    return EXIT_OK


def _abstract_params(cfg, adapter, model):
    """Unboxed abstract (shape/dtype) param tree for checkpoint restore."""
    import jax
    from flax.linen import meta as nn_meta

    return nn_meta.unbox(
        jax.eval_shape(
            lambda rng: adapter.init_params(model, cfg, rng), jax.random.key(0)
        )
    )


def _load_checkpoint_params(cfg, adapter, model, from_spec: str, *, ema: bool = False):
    """Shared inference-checkpoint load (generate / export-checkpoint):
    resolve the spec, restore params against the abstract shape tree, warn
    on config mismatch. Returns ``(ckpt_path, params, step)``.

    ``ema=True`` substitutes the trainable tree with the checkpoint's EMA
    shadow (trainer.extra.ema_decay) in the SAME payload read — for LoRA
    runs the shadow mirrors the factor subtree, the frozen base loads as
    stored."""
    import yaml

    from .training.checkpoint import load_inference_params, resolve_resume_path

    ckpt_path = resolve_resume_path(from_spec, cfg.output.root_dir)
    abstract = _abstract_params(cfg, adapter, model)
    expected_yaml = yaml.safe_dump(cfg.model_dump(), sort_keys=False)
    if not ema:
        params, step = load_inference_params(
            ckpt_path, abstract, expected_config_yaml=expected_yaml
        )
        return ckpt_path, params, step

    import jax
    import jax.numpy as jnp
    from flax import serialization

    from .models.lora import LoraAdapter
    from .training.checkpoint import (
        CheckpointManager,
        ema_from_payload,
        warn_on_config_mismatch,
    )

    payload = CheckpointManager.load(ckpt_path)
    warn_on_config_mismatch(payload, expected_yaml, ckpt_path)
    step = int(payload["step"])
    if isinstance(adapter, LoraAdapter):
        host = serialization.from_state_dict(abstract, payload["params"])
        params = {
            "base": jax.tree.map(jnp.asarray, host["base"]),
            "lora": ema_from_payload(payload, abstract["lora"]),
        }
    else:
        params = ema_from_payload(payload, abstract)
    return ckpt_path, params, step


def _handle_average_checkpoints(args: argparse.Namespace) -> int:
    """Model soup: uniform average of several checkpoints' params.

    Averaging the last few checkpoints of a run (or parallel fine-tunes
    of one init) often beats the final checkpoint alone — a cheap
    post-training win with no new training machinery: the result is a
    standard ``step_000000.ckpt`` (fresh optimizer state) that ``train
    --resume``, ``eval``, and ``generate`` all consume as usual.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    try:
        import jax
        import numpy as np

        from .training.checkpoint import (
            CheckpointManager,
            load_inference_params,
            resolve_resume_path,
            state_to_host,
        )
        from .training.optimizer import build_optimizer
        from .training.train_step import create_train_state

        initialize_registries()
        out_dir = Path(args.output)
        if out_dir.exists() and sorted(out_dir.glob("step_*.ckpt")):
            _emit_error(
                f"output dir {out_dir} already holds checkpoints; "
                "pass an empty directory"
            )
            return EXIT_TRAIN_FAILURE

        specs = [s.strip() for s in args.inputs.split(",") if s.strip()]
        if args.last_k:
            if len(specs) != 1:
                _emit_error("--last-k needs --inputs to be ONE checkpoint dir")
                return EXIT_CONFIG_ERROR
            if args.last_k < 2:
                _emit_error("averaging needs at least 2 checkpoints")
                return EXIT_CONFIG_ERROR
            files = sorted(Path(specs[0]).glob("step_*.ckpt"))
            if len(files) < args.last_k:
                _emit_error(
                    f"{specs[0]} holds {len(files)} checkpoints, "
                    f"fewer than --last-k {args.last_k}"
                )
                return EXIT_CONFIG_ERROR
            paths = files[-args.last_k :]
        else:
            if len(specs) < 2:
                _emit_error("averaging needs at least 2 checkpoints")
                return EXIT_CONFIG_ERROR
            paths = [
                resolve_resume_path(s, cfg.output.root_dir) for s in specs
            ]

        import yaml as _yaml

        from .models.lora import LoraAdapter, build_adapter

        adapter = build_adapter(cfg)
        if isinstance(adapter, LoraAdapter):
            # Averaging factors leafwise keeps the checkpoint resumable,
            # but avg(A) @ avg(B) != avg(A @ B): sound for the near-
            # collinear factors of ONE run's last-k checkpoints, wrong
            # for divergent parallel fine-tunes (merge via
            # export-checkpoint first for those).
            get_logger().warning(
                "LoRA soup: averaging A/B factors leafwise — only "
                "meaningful for checkpoints of a single run; for parallel "
                "fine-tunes, export-checkpoint (merged) and average those"
            )
        model = adapter.build_model(cfg)
        abstract = _abstract_params(cfg, adapter, model)
        expected_yaml = _yaml.safe_dump(cfg.model_dump(), sort_keys=False)

        acc = None
        steps = []
        for p in paths:
            # device=False: the average is pure host work — no reason to
            # round-trip every input through the accelerator. The config-
            # mismatch warning fires like every sibling loader's.
            params, step = load_inference_params(
                p, abstract, expected_config_yaml=expected_yaml, device=False
            )
            steps.append(step)
            # Accumulate FLOAT leaves in float64 (averaging N bf16/f32
            # trees in their own dtype loses low bits N times over);
            # non-float leaves (int buffers) keep the first checkpoint's
            # value — summing them would corrupt the soup.
            as64 = jax.tree.map(
                lambda a: np.asarray(a, np.float64)
                if np.issubdtype(np.asarray(a).dtype, np.floating)
                else np.asarray(a),
                params,
            )
            acc = (
                as64
                if acc is None
                else jax.tree.map(
                    lambda t, x: np.add(t, x)
                    if np.issubdtype(t.dtype, np.floating)
                    else t,
                    acc,
                    as64,
                )
            )
        import jax.numpy as jnp

        avg = jax.tree.map(
            # Divide in f64, THEN cast back to the param dtype.
            lambda s, like: (s / len(paths)).astype(like.dtype)
            if np.issubdtype(like.dtype, np.floating)
            else s,
            acc,
            params,
        )
        # The Trainer resumes against ITS optimizer layout: apply the same
        # adapter-level wrap (LoRA: moments only for the factors) or the
        # printed `train --resume` would hit an opt_state structure
        # mismatch. Mirrors the import-checkpoint path.
        avg_tx = build_optimizer(cfg.trainer)
        wrap_tx = getattr(adapter, "wrap_optimizer", None)
        if wrap_tx is not None:
            avg_tx = wrap_tx(avg_tx)
        state = create_train_state(
            jax.tree.map(jnp.asarray, avg), avg_tx
        )
        target = CheckpointManager(out_dir).save_host(
            0, state_to_host(state), cfg.model_dump()
        )
        stats = {
            "inputs": [str(p) for p in paths],
            "steps": steps,
            "checkpoint": str(target),
        }
        if args.json:
            print(json.dumps(stats))
        else:
            print(
                f"averaged {len(paths)} checkpoints (steps {steps}) -> {target}; "
                f"continue with: train --config {args.config} --resume {out_dir}"
            )
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"averaging failed: {exc}")
        return EXIT_TRAIN_FAILURE


def _handle_export_checkpoint(args: argparse.Namespace) -> int:
    """Export GPT weights to a torch-layout state dict (interop/).

    The layout transforms are the parity-proven ones
    (tests/test_torch_parity.py); output loads into a reference-spec torch
    GPT with `model.load_state_dict(torch.load(path))`.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    try:
        import torch

        from .interop import (
            is_llama_tree,
            is_pipeline_tree,
            llama_params_to_hf_state_dict,
            params_to_torch_state_dict,
            pipeline_params_to_gpt,
        )
        from .models.lora import build_adapter, to_inference_params

        initialize_registries()
        adapter = build_adapter(cfg)
        model = adapter.build_model(cfg)
        ckpt_path, params, step = _load_checkpoint_params(
            cfg, adapter, model, args.from_spec, ema=args.ema
        )
        # LoRA runs export their MERGED weights: the file stays the
        # family's lingua-franca full-rank state dict (models/lora.py).
        params = to_inference_params(adapter, params)
        if is_pipeline_tree(params):
            # Pipeline-trained run: unstack to the per-layer gpt tree
            # first (interop/pipeline_convert.py) — same math, so the
            # export is still reference-exact.
            params = pipeline_params_to_gpt(params)
        # Each family exports in its ecosystem's lingua franca: llama →
        # HF LlamaForCausalLM names (interop/llama_hf.py), gpt → the
        # reference torch GPT names (interop/torch_interop.py).
        convert = (
            llama_params_to_hf_state_dict
            if is_llama_tree(params)
            else params_to_torch_state_dict
        )
        sd = {k: torch.from_numpy(v) for k, v in convert(params).items()}
        out = Path(args.output)
        out.parent.mkdir(parents=True, exist_ok=True)
        torch.save(sd, out)
        n_params = int(sum(v.numel() for v in sd.values()))
        stats = {
            "checkpoint": str(ckpt_path),
            "step": step,
            "output": str(out),
            "tensors": len(sd),
            "parameters": n_params,
        }
        if args.json:
            print(json.dumps(stats))
        else:
            print(
                f"exported step-{step} checkpoint -> {out} "
                f"({len(sd)} tensors, {n_params:,} parameters)"
            )
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"export failed: {exc}")
        return EXIT_TRAIN_FAILURE


def _handle_import_checkpoint(args: argparse.Namespace) -> int:
    """torch state dict → a step-0 checkpoint this framework can resume.

    Inverse of export-checkpoint (interop/torch_interop.py): reference-
    trained GPT weights become ``step_000000.ckpt`` with a fresh optimizer
    state; continue with ``train --resume <output dir>``.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    try:
        import jax
        import numpy as np
        import torch

        from .interop import (
            gpt_params_to_pipeline,
            is_llama_tree,
            is_pipeline_tree,
            llama_params_from_hf_state_dict,
            params_from_torch_state_dict,
            pipeline_params_to_gpt,
        )
        from .models.lora import LoraAdapter, build_adapter, init_lora
        from .training.checkpoint import CheckpointManager, state_to_host
        from .training.optimizer import build_optimizer
        from .training.train_step import create_train_state

        initialize_registries()
        out_dir = Path(args.output)
        existing = sorted(out_dir.glob("step_*.ckpt")) if out_dir.exists() else []
        if existing:
            # keep-last-k pruning would otherwise silently delete the
            # imported step-0 file (or the user's own checkpoints).
            _emit_error(
                f"output dir {out_dir} already holds checkpoints "
                f"({existing[0].name}, ...); pass an empty directory"
            )
            return EXIT_TRAIN_FAILURE
        adapter = build_adapter(cfg)
        model = adapter.build_model(cfg)
        template = _abstract_params(cfg, adapter, model)
        # Importing into a LoRA config is THE fine-tuning entry point:
        # the torch weights fill the frozen base, the factors start at
        # their zero-delta init, and `train --resume` picks it up.
        lora_adapter = adapter if isinstance(adapter, LoraAdapter) else None
        if lora_adapter is not None:
            template = template["base"]
        raw = torch.load(args.input, weights_only=True)
        # .float() first: torch bf16 tensors cannot .numpy() directly, and
        # the converter works in float32 anyway.
        sd = {
            k: (v.float().numpy() if hasattr(v, "numpy") else v)
            for k, v in raw.items()
        }
        if is_pipeline_tree(template):
            # gpt_pipeline config: map the torch per-layer weights through
            # the gpt-shaped template, then restack for the pipeline tree
            # (interop/pipeline_convert.py — abstract-template capable).
            gpt_template = pipeline_params_to_gpt(template)
            params = gpt_params_to_pipeline(
                params_from_torch_state_dict(sd, gpt_template)
            )
        elif is_llama_tree(template):
            # llama config: the input is an HF LlamaForCausalLM state
            # dict (interop/llama_hf.py).
            params = llama_params_from_hf_state_dict(sd, template)
        else:
            params = params_from_torch_state_dict(sd, template)

        tx = build_optimizer(cfg.trainer)
        if lora_adapter is not None:
            params = {
                "base": params,
                "lora": init_lora(
                    params,
                    lora_adapter.spec,
                    jax.random.fold_in(jax.random.key(cfg.run.seed), 0x10A),
                ),
            }
            tx = lora_adapter.wrap_optimizer(tx)
        state = create_train_state(params, tx)
        target = CheckpointManager(out_dir).save_host(
            0, state_to_host(state), cfg.model_dump()
        )
        n_params = int(
            sum(np.prod(np.shape(x)) for x in jax.tree.leaves(params))
        )
        stats = {"input": args.input, "checkpoint": str(target), "parameters": n_params}
        if args.json:
            print(json.dumps(stats))
        else:
            print(
                f"imported {args.input} -> {target} ({n_params:,} parameters); "
                f"continue with: train --config {args.config} --resume {args.output}"
            )
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"import failed: {exc}")
        return EXIT_TRAIN_FAILURE


def _handle_train_tokenizer(args: argparse.Namespace) -> int:
    """Train an offline BPE vocabulary (data/bpe.py) on local text.

    New capability over the reference, whose only tokenizer is the
    downloaded tiktoken gpt2 (reference models/gpt.py:210-212); pairs with
    ``model.extra.tokenizer: "bpe:<output>"``.
    """
    from pathlib import Path

    from .data.bpe import train_bpe

    seen: set[Path] = set()
    files: list[Path] = []

    def _add(q: Path) -> None:
        r = q.resolve()
        if r not in seen:
            seen.add(r)
            files.append(q)

    for spec in args.input:
        p = Path(spec)
        if p.is_dir():
            for q in sorted(
                q for suf in ("*.txt", "*.md", "*.py") for q in p.rglob(suf)
            ):
                _add(q)
        elif p.is_file():
            _add(p)
        else:
            _emit_error(f"input path not found: {spec}")
            return EXIT_CONFIG_ERROR
    if not files:
        _emit_error("no input files found (looked for *.txt, *.md, *.py in dirs)")
        return EXIT_CONFIG_ERROR

    budget = args.max_bytes  # enforced on UTF-8 bytes read, not characters
    pieces: list[str] = []
    for f in files:
        if budget <= 0:
            break
        raw = f.open("rb").read(budget)
        budget -= len(raw)
        pieces.append(raw.decode("utf-8", errors="ignore"))
    corpus = "\n\n".join(pieces)

    import time

    start = time.perf_counter()
    tok = train_bpe(corpus, args.vocab_size)
    elapsed = time.perf_counter() - start
    tok.save(args.output)

    n_tokens = len(tok.encode(corpus[:1_000_000]))
    n_bytes = len(corpus[:1_000_000].encode("utf-8"))
    stats = {
        "output": args.output,
        "vocab_size": tok.n_vocab,
        "corpus_bytes": len(corpus.encode("utf-8")),
        "files": len(files),
        "train_seconds": round(elapsed, 2),
        "bytes_per_token": round(n_bytes / max(n_tokens, 1), 3),
    }
    if args.json:
        print(json.dumps(stats))
    else:
        print(
            f"trained {stats['vocab_size']}-token BPE on {stats['corpus_bytes']} bytes "
            f"({stats['files']} files) in {stats['train_seconds']}s -> {args.output} "
            f"[{stats['bytes_per_token']} bytes/token]"
        )
    return EXIT_OK


def _create_tracker(cfg, dist_state: DistState | None, run_id: str) -> Tracker:
    """A real tracker on the main process when enabled; Null otherwise
    (reference :246-248). Backend selection: tracking/__init__.py
    build_tracker (mlflow / native SQLite / auto)."""
    is_main = dist_state is None or dist_state.is_main
    if cfg.mlflow.enabled and is_main:
        return build_tracker(cfg.mlflow, run_id)
    return NullTracker()


def _log_run_artifacts(tracker: Tracker, run_dir: Path | None) -> None:
    if run_dir is None:
        return
    for name in ("config.yaml", "meta.json"):
        path = run_dir / name
        if path.is_file():
            tracker.log_artifact(str(path))


def _agree_run_id(candidate: str, dist_state: DistState | None) -> str:
    """Make every process use rank 0's run id.

    ``generate_run_id`` is wall-clock/filesystem dependent, so independent
    generation can diverge across hosts; rank 0's id is broadcast instead.
    """
    if dist_state is None or dist_state.num_processes == 1:
        return candidate
    import numpy as np
    from jax.experimental import multihost_utils

    buf = np.zeros(256, dtype=np.uint8)
    encoded = candidate.encode("utf-8")[:256]
    buf[: len(encoded)] = np.frombuffer(encoded, dtype=np.uint8)
    agreed = multihost_utils.broadcast_one_to_all(buf)
    return bytes(np.asarray(agreed)).rstrip(b"\x00").decode("utf-8")


def _agree_flag(local_ok: bool, dist_state: DistState | None) -> bool:
    """Broadcast rank 0's boolean to every process (single-process: identity)."""
    if dist_state is None or dist_state.num_processes == 1:
        return local_ok
    from .distributed import broadcast_int_from_main

    return bool(broadcast_int_from_main(1 if local_ok else 0))


def _build_decode_stack(cfg, logger, label: str = ""):
    """Adapter + (optional) tokenizer + model for an inference command.

    One implementation for generate/serve (and generate's draft model)
    so they stay bit-identical; raises with the actionable remediation
    when the model needs a vocab size the absent tokenizer would supply.
    """
    from .models.lora import build_adapter

    adapter = build_adapter(cfg)
    tokenizer = None
    try:
        tokenizer = adapter.build_tokenizer(cfg)
    except Exception as exc:  # offline environments: tokenizer optional
        logger.warning(
            "%sbuild_tokenizer failed (%s); continuing without one", label, exc
        )
    try:
        model = adapter.build_model(cfg)
    except Exception:
        if cfg.model.vocab_size is None and tokenizer is None:
            # e.g. gpt derives vocab_size from the tokenizer, which this
            # environment could not build (gpt.py:330-336).
            raise ValueError(
                "building the model needs a vocab size but no tokenizer is "
                "available; set model.vocab_size explicitly in the config"
            ) from None
        raise
    return adapter, tokenizer, model


def _load_decode_params(
    cfg,
    adapter,
    model,
    from_spec: str,
    *,
    ema: bool,
    decode_param_dtype: str,
    quantize: str,
    logger,
    label: str = "",
):
    """Checkpoint → decode-ready (model, params): the shared load tail.

    LoRA merge, EMA extraction, pipeline→gpt conversion, decode dtype
    cast, optional int8 quantization — generate, its draft branch, and
    serve all run THIS function, so a served model is bit-identical to
    the one ``generate`` would run.
    """
    from .models.lora import to_inference_params

    ckpt_path, params, step = _load_checkpoint_params(
        cfg, adapter, model, from_spec, ema=ema
    )
    logger.info("%sloaded checkpoint %s (step %d)", label, ckpt_path, step)
    if ema:
        logger.info("%susing EMA shadow weights", label)
    # LoRA checkpoints decode on the merged weights (models/lora.py).
    params = to_inference_params(adapter, params)
    model, params = _prepare_decode_model(
        model, params, decode_param_dtype, logger, label=label
    )
    if quantize == "int8":
        from .ops.quant import quant_stats, quantize_tree

        params = quantize_tree(params)
        stats = quant_stats(params)
        logger.info(
            "%sint8 weight quantization: %d/%d params quantized, "
            "%.2fx weight-byte compression",
            label,
            stats["quantized_params"],
            stats["total_params"],
            stats["compression"],
        )
    return model, params, ckpt_path, step


def _build_serving_backend(
    cfg,
    args: argparse.Namespace,
    model,
    params,
    logger,
    registry=None,
    trace_dir=None,
    name=None,
):
    """Continuous-batching scheduler + metrics registry for serve/serve-bench.

    Policy resolution: ``--draft-config`` forces ``speculative`` (and the
    config may also select it, in which case the draft flags are
    required); otherwise ``serving.policy`` from the config. Raises
    ``ValueError`` with the actionable message on a bad combination —
    callers map it to EXIT_CONFIG_ERROR.

    ``trace_dir`` (``serve --trace-dir`` / serve-bench's out dir) makes
    the timeline file-backed at ``{trace_dir}/{name}/timeline.jsonl`` so
    ``llmtrain trace`` can merge this process into the fleet-wide view.
    """
    from .serving import ContinuousBatchingScheduler, PagedDecodeEngine
    from .telemetry.registry import MetricsRegistry
    from .telemetry.timeline import EventTimeline

    scfg = cfg.serving
    if registry is None:
        registry = MetricsRegistry(None)
    # Serving timeline: request-id-tagged queue-wait/prefill/decode spans
    # (scheduler.py). Memory-only here unless --trace-dir asks for JSONL;
    # serve-bench exports the Perfetto trace next to its report.
    timeline = None
    if cfg.telemetry.enabled and cfg.telemetry.timeline:
        tl_path = (
            Path(trace_dir) / (name or "serve") / "timeline.jsonl"
            if trace_dir is not None
            else None
        )
        timeline = EventTimeline(
            tl_path,
            max_events=cfg.telemetry.max_events,
            xprof_annotations=cfg.telemetry.xprof_annotations,
        )
    overload = None
    if scfg.overload.enabled:
        from .serving import OverloadController

        overload = OverloadController.from_config(scfg.overload)
        logger.info(
            "overload control: queue_cap %d, classes %s, brownout %.0f/%.0f ms",
            scfg.overload.queue_cap,
            dict(scfg.overload.classes),
            scfg.overload.brownout_high_ms,
            scfg.overload.brownout_low_ms,
        )
    policy = "speculative" if args.draft_config is not None else scfg.policy
    if policy == "speculative":
        if args.draft_config is None or args.draft_from is None:
            raise ValueError(
                "the speculative serving policy needs --draft-config AND "
                "--draft-from (serving.policy: speculative in the config "
                "selects it; the draft checkpoint must come from the CLI)"
            )
        from .models.lora import build_adapter

        draft_cfg, _, _ = load_and_validate_config(args.draft_config)
        draft_adapter = build_adapter(draft_cfg)
        draft_model = draft_adapter.build_model(draft_cfg)
        draft_model, draft_params, _, _ = _load_decode_params(
            draft_cfg,
            draft_adapter,
            draft_model,
            args.draft_from,
            ema=False,
            decode_param_dtype=args.decode_param_dtype,
            quantize=args.quantize,
            logger=logger,
            label="draft ",
        )
        if draft_model.vocab_size != model.vocab_size:
            raise ValueError(
                f"draft vocab_size ({draft_model.vocab_size}) != target "
                f"vocab_size ({model.vocab_size}) — speculative decoding "
                "needs a shared vocabulary"
            )
        # Batched speculative: when both models support paged decoding,
        # attach target + draft engines so greedy requests draft in
        # batch and the target scores every row's slab in ONE bucketed
        # verify call. Otherwise the scheduler falls back to the batch-1
        # speculative_generate path.
        engine = draft_engine = None
        if hasattr(model, "for_paged_decoding") and hasattr(
            draft_model, "for_paged_decoding"
        ):
            engine_kwargs = dict(
                block_tokens=scfg.block_tokens,
                num_blocks=scfg.num_blocks or None,
                max_batch_slots=scfg.max_batch_slots,
                prompt_buckets=scfg.prompt_buckets or None,
                batch_buckets=scfg.batch_buckets or None,
            )
            engine = PagedDecodeEngine(model, params, **engine_kwargs)
            draft_engine = PagedDecodeEngine(
                draft_model, draft_params, **engine_kwargs
            )
            logger.info(
                "batched speculative serving: %d slots, gamma from %s",
                engine.max_batch_slots,
                "--gamma" if args.gamma is not None else "config",
            )
        scheduler = ContinuousBatchingScheduler(
            engine,
            policy="speculative",
            registry=registry,
            model=model,
            params=params,
            draft_model=draft_model,
            draft_params=draft_params,
            draft_engine=draft_engine,
            gamma=args.gamma if args.gamma is not None else scfg.speculative_gamma,
            timeline=timeline,
            overload=overload,
        )
    else:
        engine = PagedDecodeEngine(
            model,
            params,
            block_tokens=scfg.block_tokens,
            num_blocks=scfg.num_blocks or None,
            max_batch_slots=scfg.max_batch_slots,
            prompt_buckets=scfg.prompt_buckets or None,
            batch_buckets=scfg.batch_buckets or None,
            prefix_cache=scfg.prefix_cache,
            prefill_chunk=scfg.prefill_chunk,
        )
        logger.info(
            "continuous batching: %d slots, %d-token blocks x %d pool blocks, "
            "prompt buckets %s, batch buckets %s",
            engine.max_batch_slots,
            engine.block_tokens,
            engine.pool.num_blocks,
            engine.prompt_buckets,
            engine.batch_buckets,
        )
        scheduler = ContinuousBatchingScheduler(
            engine, registry=registry, timeline=timeline, overload=overload
        )
    _configure_request_tracer(cfg, scheduler, timeline)
    return scheduler, registry


def _configure_request_tracer(cfg, backend, timeline) -> None:
    """Replace a scheduler/router's auto-created request tracer with one
    built from ``telemetry.tracing`` (tail-sampling knobs), or strip it
    when tracing is disabled — the backends default to a tracer whenever
    they have a timeline, so the config gate must be applied here."""
    tcfg = cfg.telemetry.tracing
    if timeline is None or not tcfg.enabled:
        backend.tracer = None
        return
    from .telemetry.tracing import TailSampler, Tracer

    backend.tracer = Tracer(
        timeline,
        sampler=TailSampler(
            slow_frac=tcfg.slow_keep_frac,
            reservoir=tcfg.reservoir,
            warmup=tcfg.warmup_keep,
        ),
        max_spans=tcfg.max_spans_per_trace,
    )


def _build_router_backend(
    cfg,
    args: argparse.Namespace,
    model,
    params,
    logger,
    trace_dir=None,
):
    """Replica-router tier for ``serve --router`` / ``serve-bench --router``.

    Default: ``serving.router.replicas`` (or ``--replicas``) in-process
    replicas, each a full scheduler+engine stack behind one router.
    ``--backends``/``--discover`` route across separate serve processes
    over HTTP instead — the k8s shape, where each replica is its own pod
    behind a headless Service (k8s/router.yaml).
    """
    from .serving import (
        HTTPReplica,
        InProcessReplica,
        ReplicaRouter,
        resolve_backends,
    )
    from .telemetry.registry import MetricsRegistry

    rcfg = cfg.serving.router
    registry = MetricsRegistry(None)
    replicas: list[Any] = []
    if getattr(args, "backends", None):
        urls = [u.strip() for u in args.backends.split(",") if u.strip()]
        if not urls:
            raise ValueError("--backends must list at least one base URL")
        replicas = [
            HTTPReplica(
                u,
                timeout_sec=cfg.serving.request_timeout_sec,
                probe_timeout_sec=rcfg.probe_timeout_sec,
            )
            for u in urls
        ]
    elif getattr(args, "discover", None):
        replicas = [
            HTTPReplica(
                u,
                timeout_sec=cfg.serving.request_timeout_sec,
                probe_timeout_sec=rcfg.probe_timeout_sec,
            )
            for u in resolve_backends(args.discover)
        ]
    else:
        n = getattr(args, "replicas", None) or rcfg.replicas
        for i in range(n):
            # In-process replicas share the router's registry so the
            # scheduler-level overload series (rejected{reason}, brownout,
            # predicted wait) reach the fleet /metrics scrape; counters
            # sum across replicas, gauges are last-writer-wins.
            sched, _ = _build_serving_backend(
                cfg,
                args,
                model,
                params,
                logger,
                registry=registry,
                trace_dir=trace_dir,
                name=f"replica{i}",
            )
            sched.start()
            replicas.append(InProcessReplica(sched, f"replica{i}"))
    # The router gets its own timeline so its placement/failover/hop
    # spans land in a separate JSONL track (`{trace_dir}/router/`) that
    # `llmtrain trace` stitches to the replica tracks via traceparent.
    router_timeline = None
    if cfg.telemetry.enabled and cfg.telemetry.timeline:
        from .telemetry.timeline import EventTimeline

        router_timeline = EventTimeline(
            (Path(trace_dir) / "router" / "timeline.jsonl")
            if trace_dir is not None
            else None,
            max_events=cfg.telemetry.max_events,
            xprof_annotations=False,
        )
    router = ReplicaRouter(
        replicas,
        registry=registry,
        affinity_weight=rcfg.affinity_weight,
        max_affinity_entries=rcfg.max_affinity_entries,
        fail_threshold=rcfg.fail_threshold,
        revive_sec=rcfg.revive_sec,
        block_tokens=cfg.serving.block_tokens,
        retry_budget=rcfg.retry_budget,
        retry_window_sec=rcfg.retry_window_sec,
        timeline=router_timeline,
    )
    _configure_request_tracer(cfg, router, router_timeline)
    logger.info(
        "replica router: %d %s replicas, affinity_weight %.1f, "
        "fail_threshold %d",
        len(replicas),
        "HTTP" if isinstance(replicas[0], HTTPReplica) else "in-process",
        rcfg.affinity_weight,
        rcfg.fail_threshold,
    )
    return router, registry


def _handle_serve(args: argparse.Namespace) -> int:
    """Checkpoint → compiled decode loop → stdlib HTTP server (serving/).

    Loading mirrors ``generate`` exactly (LoRA merge, EMA extraction,
    pipeline→gpt conversion, decode dtype cast, int8 quantization) so a
    served model is bit-identical to the one ``generate`` would run.
    ``serving.mode: continuous`` (or ``--mode continuous``) swaps the
    one-decode-at-a-time device lock for the paged-KV continuous-batching
    scheduler — handler threads submit into the admission queue and N
    in-flight sequences share one jitted decode program (docs/serving.md).
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR
    if (args.draft_config is None) != (args.draft_from is None):
        _emit_error("--draft-config and --draft-from must be given together")
        return EXIT_CONFIG_ERROR
    mode = args.mode or cfg.serving.mode
    if mode != "continuous" and args.draft_config is not None:
        # Silently ignoring the draft flags would serve plain
        # single-request decode while the user asked for speculative.
        _emit_error(
            "--draft-config/--draft-from need the continuous backend; "
            "set serving.mode: continuous (or pass --mode continuous)"
        )
        return EXIT_CONFIG_ERROR
    if args.backends and args.discover:
        _emit_error("--backends and --discover are mutually exclusive")
        return EXIT_CONFIG_ERROR
    use_router = bool(args.router or args.backends or args.discover)
    if use_router and mode != "continuous":
        _emit_error(
            "--router needs the continuous backend; set serving.mode: "
            "continuous (or pass --mode continuous)"
        )
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    scheduler = None
    metrics_stop = None
    metrics_thread = None
    try:
        from .serving import ServerState, make_server

        initialize_registries()
        adapter, tokenizer, model = _build_decode_stack(cfg, logger)
        model, params, ckpt_path, step = _load_decode_params(
            cfg,
            adapter,
            model,
            args.from_spec,
            ema=args.ema,
            decode_param_dtype=args.decode_param_dtype,
            quantize=args.quantize,
            logger=logger,
        )
        eos = args.eos_token_id
        if eos is None and tokenizer is not None:
            eos = getattr(tokenizer, "eot_token", None)

        if mode == "continuous":
            try:
                trace_dir = getattr(args, "trace_dir", None)
                if use_router:
                    scheduler, registry = _build_router_backend(
                        cfg, args, model, params, logger, trace_dir=trace_dir
                    )
                else:
                    scheduler, registry = _build_serving_backend(
                        cfg, args, model, params, logger, trace_dir=trace_dir
                    )
            except ConfigLoadError as exc:
                _emit_error(exc.message, details=exc.details, errors=exc.errors)
                return EXIT_CONFIG_ERROR
            except ValueError as exc:
                _emit_error(str(exc))
                return EXIT_CONFIG_ERROR
            scheduler.start()
        else:
            # Simple mode still serves GET /metrics (request counter and
            # latency come from ServerStats; the scheduler gauges need
            # the continuous backend).
            from .telemetry.registry import MetricsRegistry

            registry = MetricsRegistry(None)

        cap = (
            args.max_new_tokens_cap
            if args.max_new_tokens_cap is not None
            else cfg.serving.max_new_tokens_cap
        )
        client_gate = None
        ocfg = cfg.serving.overload
        if ocfg.enabled and ocfg.client_rate_rps > 0:
            from .serving import ClientRateGate

            client_gate = ClientRateGate(
                ocfg.client_rate_rps,
                ocfg.client_burst,
                max_clients=ocfg.max_tracked_clients,
            )
        state = ServerState(
            model=model,
            params=params,
            tokenizer=tokenizer,
            step=step,
            checkpoint=str(ckpt_path),
            eos_token_id=eos,
            max_new_tokens_cap=cap,
            default_max_new_tokens=cfg.serving.default_max_new_tokens,
            scheduler=scheduler,
            registry=registry,
            request_timeout_sec=cfg.serving.request_timeout_sec,
            liveness_stale_sec=cfg.serving.liveness_stale_sec,
            client_gate=client_gate,
        )

        if mode == "continuous":
            # Zero-downtime checkpoint hot-swap: POST /reload re-resolves
            # the --from spec (a dir or run id resolves to the NEWEST
            # manifest-committed checkpoint, training/checkpoint.py) and
            # swaps the params without dropping a request — in-flight
            # sequences finish on the params they were admitted under,
            # new admissions use the new ones. With --router the swap
            # rolls one replica at a time.
            def _reload(body: dict) -> dict:
                spec = str(body.get("from") or args.from_spec)
                _, new_params, new_ckpt, new_step = _load_decode_params(
                    cfg,
                    adapter,
                    model,
                    spec,
                    ema=args.ema,
                    decode_param_dtype=args.decode_param_dtype,
                    quantize=args.quantize,
                    logger=logger,
                    label="reload ",
                )
                out: dict[str, Any] = {
                    "step": new_step,
                    "checkpoint": str(new_ckpt),
                }
                if hasattr(scheduler, "rolling_reload"):
                    out["replicas"] = scheduler.rolling_reload(
                        params=new_params,
                        step=new_step,
                        checkpoint=str(new_ckpt),
                    )
                else:
                    scheduler.hot_swap(
                        new_params, step=new_step, checkpoint=str(new_ckpt)
                    )
                state.params = new_params
                state.step, state.checkpoint = new_step, str(new_ckpt)
                return out

            state.reloader = _reload

        # Textfile fallback for serving replicas (mirrors the training
        # facade's metrics.prom snapshot): a node-exporter textfile
        # collector can pick up the scrape even when /metrics is behind
        # a router or the pod network is unreachable. Histograms ride
        # along, exemplar trace ids included.
        serve_trace_dir = getattr(args, "trace_dir", None)
        if (
            serve_trace_dir
            and cfg.telemetry.enabled
            and cfg.telemetry.prometheus_textfile
        ):
            import threading

            from .telemetry.prometheus import render_prometheus, write_textfile

            prom_path = Path(serve_trace_dir) / "metrics.prom"
            metrics_stop = threading.Event()

            def _snapshot_metrics() -> None:
                try:
                    write_textfile(
                        prom_path,
                        render_prometheus(
                            registry.latest(),
                            registry.counters(),
                            {"component": "serve"},
                            histograms=registry.histograms(),
                        ),
                    )
                except Exception:  # noqa: BLE001 — snapshot must not kill serving
                    pass

            def _metrics_loop() -> None:
                while True:
                    _snapshot_metrics()
                    if metrics_stop.wait(5.0):
                        _snapshot_metrics()
                        return

            metrics_thread = threading.Thread(
                target=_metrics_loop, name="metrics-prom", daemon=True
            )
            metrics_thread.start()

        httpd = make_server(state, args.host, args.port)
        host, port = httpd.server_address[:2]
        # Machine-readable ready line: tests (and orchestration) read the
        # bound port from here, which is what makes --port 0 usable.
        print(
            json.dumps(
                {
                    "serving": str(ckpt_path),
                    "host": host,
                    "port": port,
                    "mode": mode,
                    "policy": scheduler.policy if scheduler else None,
                    "router": (
                        len(scheduler.replicas) if use_router else None
                    ),
                }
            ),
            flush=True,
        )
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"serve failed: {exc}")
        return exit_code_for_exception(exc)
    finally:
        if metrics_stop is not None:
            metrics_stop.set()
        if metrics_thread is not None:
            metrics_thread.join(timeout=10.0)
        if scheduler is not None:
            scheduler.close()


def _resolve_watch_dirs(watch: str) -> tuple[Path, Path]:
    """``--watch`` path → (run_dir, ckpt_dir). Accepts the run dir (the
    conventional layout puts checkpoints in ``{run_dir}/checkpoints``)
    or the checkpoints dir itself."""
    path = Path(watch)
    if path.name == "checkpoints":
        return path.parent, path
    if (path / "checkpoints").is_dir() or not any(
        p.name.startswith("step_") for p in (path.glob("step_*") if path.is_dir() else [])
    ):
        return path, path / "checkpoints"
    # A dir holding step_* files directly IS the checkpoint dir.
    return path.parent, path


def _handle_promote(args: argparse.Namespace) -> int:
    """Continuous train→canary→promote lifecycle (lifecycle/controller.py).

    Watches the training run's manifest stream (durable artifacts only),
    serves an in-process replica fleet from the promoted baseline,
    canaries each new commit on one replica, scores it over a soak
    window (held-out eval loss + TTFT/per-token percentiles, optional
    A/B traffic split), then promotes fleet-wide via rolling reload or
    auto-rolls the canary back. Every decision is a durable
    ``promotions.jsonl`` entry the goodput ledger attributes.

    Exit taxonomy: training finished (report.json) or the promotion
    budget spent → 0; the training run dying mid-stream (stale
    heartbeat, no report) → EXIT_TRAIN_FAILURE.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR
    pcfg = cfg.promote
    overrides: dict[str, Any] = {}
    if args.max_promotions is not None:
        overrides["max_promotions"] = args.max_promotions
    if args.idle_timeout_sec is not None:
        overrides["idle_timeout_sec"] = args.idle_timeout_sec
    if overrides:
        pcfg = pcfg.model_copy(update=overrides)

    run_dir, ckpt_dir = _resolve_watch_dirs(args.watch)
    if not run_dir.is_dir():
        _emit_error(f"--watch run dir not found: {run_dir}")
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    router = None
    timeline = None
    try:
        from .lifecycle import (
            CheckpointWatcher,
            PromotionController,
            PromotionLedger,
            RouterFleet,
        )

        initialize_registries()
        ledger = PromotionLedger(run_dir / "promotions.jsonl")
        watcher = CheckpointWatcher(ckpt_dir, run_dir=run_dir)

        # Baseline: the last promoted checkpoint (ledger replay — a
        # SIGKILLed promote resumes where it decided, never re-promotes),
        # else --from, else the stream's first commit (waited for).
        spec = None
        promoted = ledger.last_promoted()
        if promoted and promoted.get("checkpoint") and Path(
            promoted["checkpoint"]
        ).exists():
            spec = promoted["checkpoint"]
            logger.info(
                "promote: resuming from ledger — step %d is the baseline",
                promoted["step"],
            )
        elif args.from_spec:
            spec = args.from_spec
        else:
            deadline = time.monotonic() + pcfg.idle_timeout_sec
            while spec is None:
                polled = watcher.poll(after_step=-1)
                if polled is not None:
                    spec = str(polled[0])
                    break
                if time.monotonic() > deadline:
                    _emit_error(
                        f"promote: no committed checkpoint appeared in "
                        f"{ckpt_dir} within {pcfg.idle_timeout_sec:.0f}s"
                    )
                    return EXIT_TRAIN_FAILURE
                time.sleep(pcfg.poll_sec)

        adapter, tokenizer, model = _build_decode_stack(cfg, logger)
        model, params, ckpt_path, step = _load_decode_params(
            cfg,
            adapter,
            model,
            str(spec),
            ema=args.ema,
            decode_param_dtype=args.decode_param_dtype,
            quantize=args.quantize,
            logger=logger,
            label="promote ",
        )
        router, registry = _build_router_backend(cfg, args, model, params, logger)
        if len(router.replicas) < 2:
            logger.warning(
                "promote: a 1-replica fleet has no reference replica — "
                "the SLO A/B gate is skipped (only failures and eval "
                "loss gate promotion)"
            )

        def load_params(ckpt: Path) -> Any:
            _, p, _, _ = _load_decode_params(
                cfg,
                adapter,
                model,
                str(ckpt),
                ema=args.ema,
                decode_param_dtype=args.decode_param_dtype,
                quantize=args.quantize,
                logger=logger,
                label="candidate ",
            )
            return p

        evaluator = None
        if not args.no_eval:
            from .tracking.base import NullTracker
            from .training.trainer import Trainer

            eval_trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())

            def evaluator(ckpt: Path) -> float | None:
                metrics = eval_trainer.evaluate(resume_from=str(ckpt))
                if metrics is None:
                    return None
                return float(metrics["val/loss"])

        if cfg.telemetry.enabled and cfg.telemetry.timeline:
            from .telemetry.timeline import EventTimeline

            tdir = run_dir / "telemetry"
            tdir.mkdir(parents=True, exist_ok=True)
            # Separate file: appending promote segments into the
            # trainer's timeline.jsonl would corrupt the goodput
            # ledger's segment accounting.
            timeline = EventTimeline(
                tdir / "promote_timeline.jsonl",
                max_events=cfg.telemetry.max_events,
                xprof_annotations=False,
            )

        fleet = RouterFleet(
            router,
            vocab_size=model.vocab_size,
            max_new_tokens=min(8, cfg.serving.max_new_tokens_cap),
        )
        try:
            controller = PromotionController(
                cfg=pcfg,
                watcher=watcher,
                fleet=fleet,
                ledger=ledger,
                baseline_params=params,
                baseline_step=step,
                baseline_checkpoint=str(ckpt_path),
                load_params=load_params,
                evaluator=evaluator,
                registry=registry,
                timeline=timeline,
            )
        except ValueError as exc:
            _emit_error(str(exc))
            return EXIT_CONFIG_ERROR
        result = controller.run()

        payload = {
            "status": result.status,
            "promotions": result.promotions,
            "rollbacks": result.rollbacks,
            "aborts": result.aborts,
            "last_promoted_step": result.last_promoted_step,
            "ledger": str(ledger.path),
        }
        if args.json:
            print(json.dumps(payload))
        else:
            print(
                f"promote: {result.status} — {result.promotions} promoted, "
                f"{result.rollbacks} rolled back, {result.aborts} aborted "
                f"(serving step {result.last_promoted_step}); "
                f"ledger {ledger.path}"
            )
        if result.status == "training_dead":
            # The watched run died mid-stream: surface it on the exit
            # taxonomy so a supervisor treats promote like the trainer.
            return EXIT_TRAIN_FAILURE
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"promote failed: {exc}")
        return exit_code_for_exception(exc)
    finally:
        if timeline is not None:
            try:
                timeline.flush()
            except Exception:  # noqa: BLE001 — best-effort telemetry
                pass
        if router is not None:
            try:
                from .telemetry.prometheus import render_prometheus

                tdir = run_dir / "telemetry"
                tdir.mkdir(parents=True, exist_ok=True)
                (tdir / "promote_metrics.prom").write_text(
                    render_prometheus(
                        dict(router.registry.latest()),
                        router.registry.counters(),
                        {"component": "promote"},
                    ),
                    encoding="utf-8",
                )
            except Exception:  # noqa: BLE001 — best-effort telemetry
                pass
            router.close()


def _handle_serve_bench(args: argparse.Namespace) -> int:
    """Seeded open-loop load run against the continuous-batching scheduler.

    The SLO harness (docs/serving.md): a seeded request population
    arrives on an open-loop Poisson clock (arrivals never wait for
    completions — the regime under which tail latency means anything),
    the scheduler serves them with continuous batching, and the
    measurements land in three sinks: a ``serving`` block in
    ``report.json``/``report.md``, ``llmtrain_serve_*`` gauges, and the
    JSON summary on stdout. ``--verify-parity`` re-decodes every request
    through sequential single-request ``generate()`` and exits nonzero
    unless the batched token-ids are bitwise identical; a compile count
    over the bucket budget also fails the run.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR
    if (args.draft_config is None) != (args.draft_from is None):
        _emit_error("--draft-config and --draft-from must be given together")
        return EXIT_CONFIG_ERROR
    if args.requests < 1:
        _emit_error("--requests must be >= 1")
        return EXIT_CONFIG_ERROR
    if args.prompt_tokens_min < 1:
        _emit_error("--prompt-tokens-min must be >= 1")
        return EXIT_CONFIG_ERROR
    if args.max_new_tokens < 1:
        # 0 would "succeed" with one unavoidable prefill token per request
        # and then fail parity against generate()'s empty continuation —
        # a misleading EXIT_TRAIN_FAILURE instead of a config error.
        _emit_error("--max-new-tokens must be >= 1")
        return EXIT_CONFIG_ERROR
    if args.long_fraction and not args.long_prompt_tokens:
        _emit_error("--long-fraction needs --long-prompt-tokens")
        return EXIT_CONFIG_ERROR
    if not (0.0 <= args.long_fraction <= 1.0):
        _emit_error("--long-fraction must be in [0, 1]")
        return EXIT_CONFIG_ERROR
    if args.shared_prefix_tokens < 0 or args.shared_prefix_count < 1:
        _emit_error(
            "--shared-prefix-tokens must be >= 0 and "
            "--shared-prefix-count >= 1"
        )
        return EXIT_CONFIG_ERROR
    if args.burst_factor <= 0:
        _emit_error("--burst-factor must be > 0")
        return EXIT_CONFIG_ERROR
    if args.deadline_ms is not None and args.deadline_ms <= 0:
        _emit_error("--deadline-ms must be > 0")
        return EXIT_CONFIG_ERROR
    if not (0.0 <= args.batch_fraction <= 1.0):
        _emit_error("--batch-fraction must be in [0, 1]")
        return EXIT_CONFIG_ERROR
    if args.max_rejected_frac is not None and not (
        0.0 <= args.max_rejected_frac <= 1.0
    ):
        _emit_error("--max-rejected-frac must be in [0, 1]")
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    scheduler = None
    try:
        import jax
        import numpy as np

        from .serving import build_requests, run_loadgen

        initialize_registries()
        adapter, tokenizer, model = _build_decode_stack(cfg, logger)
        model, params, ckpt_path, _step = _load_decode_params(
            cfg,
            adapter,
            model,
            args.from_spec,
            ema=args.ema,
            decode_param_dtype=args.decode_param_dtype,
            quantize=args.quantize,
            logger=logger,
        )
        block_size = int(model.block_size)
        if args.max_new_tokens >= block_size:
            _emit_error(
                f"--max-new-tokens ({args.max_new_tokens}) must leave room "
                f"for a prompt within block_size ({block_size})"
            )
            return EXIT_CONFIG_ERROR
        pmax = args.prompt_tokens_max or min(32, block_size - args.max_new_tokens)
        pmax = min(pmax, block_size - args.max_new_tokens)
        pmin = min(args.prompt_tokens_min, pmax)
        # The mix knobs can push prompts past what a request may hold.
        worst_prompt = args.shared_prefix_tokens + max(
            pmax, args.long_prompt_tokens if args.long_fraction else 0
        )
        if worst_prompt + args.max_new_tokens > block_size:
            _emit_error(
                f"longest possible prompt ({worst_prompt} tokens incl. "
                f"shared prefix) + --max-new-tokens "
                f"({args.max_new_tokens}) exceeds block_size ({block_size})"
            )
            return EXIT_CONFIG_ERROR

        # out_dir is resolved before the backend so per-process timeline
        # JSONL lands under {out_dir}/telemetry — `llmtrain trace
        # --run-dir {out_dir}` merges the run after the fact.
        out_dir = Path(args.out or (Path(cfg.output.root_dir) / "serve_bench"))
        bench_trace_dir = out_dir / "telemetry"
        try:
            if args.router:
                scheduler, registry = _build_router_backend(
                    cfg, args, model, params, logger,
                    trace_dir=bench_trace_dir,
                )
            else:
                scheduler, registry = _build_serving_backend(
                    cfg, args, model, params, logger,
                    trace_dir=bench_trace_dir,
                )
        except ConfigLoadError as exc:
            _emit_error(exc.message, details=exc.details, errors=exc.errors)
            return EXIT_CONFIG_ERROR
        except ValueError as exc:
            _emit_error(str(exc))
            return EXIT_CONFIG_ERROR

        requests = build_requests(
            num_requests=args.requests,
            seed=args.seed,
            vocab_size=int(model.vocab_size),
            prompt_tokens_min=pmin,
            prompt_tokens_max=pmax,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            shared_prefix_tokens=args.shared_prefix_tokens,
            shared_prefix_count=args.shared_prefix_count,
            long_fraction=args.long_fraction,
            long_prompt_tokens=args.long_prompt_tokens,
            deadline_ms=args.deadline_ms,
            batch_fraction=args.batch_fraction,
        )
        logger.info(
            "serve-bench: %d requests, prompts %d-%d tokens, %d new tokens, "
            "%.1f rps %s open-loop (seed %d, policy %s)",
            len(requests), pmin, pmax, args.max_new_tokens,
            args.rate_rps, args.arrival, args.seed, scheduler.policy,
        )
        scheduler.start()
        block = run_loadgen(
            scheduler,
            requests,
            rate_rps=args.rate_rps,
            seed=args.seed,
            timeout_sec=args.timeout_sec,
            arrival=args.arrival,
            burst_factor=args.burst_factor,
        )
        scheduler.close()
        block["checkpoint"] = str(ckpt_path)
        tracer = getattr(scheduler, "tracer", None)
        if tracer is not None:
            block["tracing"] = tracer.stats()

        failures: list[str] = []
        compile_block = block.get("compile")
        if compile_block is not None and not compile_block["within_budget"]:
            failures.append(
                f"decode-loop compile count exceeded the bucket budget: "
                f"{compile_block['prefill_programs']} prefill + "
                f"{compile_block['decode_programs']} decode > "
                f"{compile_block['budget']}"
            )
        if block["requests"]["failed"] or block["requests"]["timed_out"]:
            failures.append(
                f"{block['requests']['failed']} failed / "
                f"{block['requests']['timed_out']} timed-out requests"
            )
        if args.max_per_token_p99_ms is not None:
            p99 = block["slo"]["per_token_ms"]["p99"]
            if p99 is None or p99 > args.max_per_token_p99_ms:
                failures.append(
                    f"per-token p99 {p99} ms exceeds the "
                    f"--max-per-token-p99-ms bound "
                    f"({args.max_per_token_p99_ms} ms)"
                )
        if args.max_rejected_frac is not None:
            reqs_blk = block["requests"]
            frac = (
                reqs_blk.get("rejected", 0) + reqs_blk.get("shed", 0)
            ) / max(1, reqs_blk["submitted"])
            if frac > args.max_rejected_frac:
                failures.append(
                    f"rejected+shed fraction {frac:.3f} exceeds the "
                    f"--max-rejected-frac bound ({args.max_rejected_frac})"
                )

        if args.verify_parity:
            # The exactness contract: batched continuous decode must emit
            # the SAME token ids sequential single-request generate()
            # produces for identical seeds/sampling params.
            from .generation import generate

            mismatched = 0
            for req in requests:
                if req.finish_reason not in ("eos", "length"):
                    continue
                out = generate(
                    model,
                    params,
                    req.prompt_ids[None, :],
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature,
                    top_k=req.top_k,
                    top_p=req.top_p,
                    eos_token_id=req.eos_token_id,
                    rng=jax.random.key(req.seed),
                )
                ref = [int(t) for t in np.asarray(out)[0, req.prompt_ids.shape[0]:]]
                if req.eos_token_id is not None and req.eos_token_id in ref:
                    ref = ref[: ref.index(req.eos_token_id) + 1]
                if ref != req.tokens:
                    mismatched += 1
                    logger.warning(
                        "parity mismatch on request %s: served %s != "
                        "generate() %s",
                        req.request_id, req.tokens, ref,
                    )
            checked = sum(
                1 for r in requests if r.finish_reason in ("eos", "length")
            )
            block["parity"] = {
                "checked": checked,
                "mismatched": mismatched,
                "bitwise_identical": mismatched == 0 and checked > 0,
            }
            if mismatched:
                failures.append(
                    f"{mismatched}/{checked} requests diverged from "
                    "sequential generate()"
                )

        # report.json / report.md with the serving block (telemetry
        # pipeline contract — the same writer training runs use).
        from .telemetry.report import build_report, write_reports
        from .telemetry.timeline import EventTimeline

        # The scheduler's request-id-tagged timeline (queue_wait → prefill
        # → decode spans) feeds the report AND a Perfetto-loadable trace.
        timeline = getattr(scheduler, "timeline", None) or EventTimeline(None)
        report = build_report(
            run_id="serve-bench",
            run_name=cfg.run.name,
            registry=registry,
            timeline=timeline,
            memory=None,
            wall_time_sec=block["throughput"]["wall_sec"],
            serving=block,
        )
        json_path, md_path = write_reports(out_dir, report)
        trace_path = timeline.export_perfetto(out_dir / "trace.json")
        summary = {
            "serving": block,
            "report_json": str(json_path) if json_path else None,
            "report_md": str(md_path) if md_path else None,
            "trace_json": str(trace_path) if trace_path else None,
            "trace_dir": str(bench_trace_dir),
            "ok": not failures,
        }
        if failures:
            summary["failures"] = failures
        print(json.dumps(summary, indent=2), flush=True)
        if failures:
            _emit_error("; ".join(failures))
            return EXIT_TRAIN_FAILURE
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"serve-bench failed: {exc}")
        return exit_code_for_exception(exc)
    finally:
        if scheduler is not None:
            scheduler.close()


def _handle_eval(args: argparse.Namespace) -> int:
    """Eval-only: restore a checkpoint and run the validation loop once.

    New capability over the reference (its eval exists only inside the
    train loop, reference trainer.py:243-289); pairs with the loss-parity
    story — evaluate any checkpoint against any config's val split.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    level = "DEBUG" if args.verbose else cfg.logging.level
    configure_logging(level=level, json_output=cfg.logging.json_output)
    try:
        from .tracking.base import NullTracker
        from .training.trainer import Trainer

        initialize_registries()
        trainer = Trainer(cfg, run_dir=None, tracker=NullTracker())
        metrics = trainer.evaluate(
            resume_from=args.from_spec,
            use_ema=args.ema,
            quantize=args.quantize if args.quantize != "none" else None,
        )
        if metrics is None:
            _emit_error("data module has no validation split to evaluate")
            return EXIT_TRAIN_FAILURE
        if args.json:
            print(json.dumps({"checkpoint": args.from_spec, "metrics": metrics}))
        else:
            rendered = "  ".join(f"{k}={v:.6f}" for k, v in sorted(metrics.items()))
            print(rendered)
        return EXIT_OK
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        _emit_error(f"evaluation failed: {exc}")
        return exit_code_for_exception(exc)


def _prepare_decode_model(model, params, decode_param_dtype: str, logger, label=""):
    """Inference-load post-processing shared by the target and draft paths.

    * Pipeline-trained runs decode through the equivalent plain GPT
      (interop/pipeline_convert.py — same math), which has the KV-cache
      path; the stacked model would fall back to the windowed re-forward
      loop. The rebuild keeps the validated attention impl so a flash
      config doesn't revert to dense and materialize (T, T).
    * ``decode_param_dtype == "compute"`` casts floating params to the
      model compute dtype — decode is weight-bandwidth bound and a bf16
      model reading f32 weights pays 2x the bytes (tools/diag_decode.py).
      Models without a dtype/param_dtype split (e.g. dummy_gpt) have
      nothing to cast.
    """
    import jax
    import jax.numpy as jnp

    from .interop import is_pipeline_tree, pipeline_params_to_gpt

    if is_pipeline_tree(params):
        from .models.gpt import GPT

        params = pipeline_params_to_gpt(params)
        model = GPT(
            vocab_size=model.vocab_size,
            block_size=model.block_size,
            d_model=model.d_model,
            n_layers=model.n_layers,
            n_heads=model.n_heads,
            d_ff=model.d_ff,
            dropout=0.0,
            tie_embeddings=model.tie_embeddings,
            dtype=model.dtype,
            param_dtype=model.param_dtype,
            attention=model.attention,
            n_kv_heads=model.n_kv_heads,
            # A windowed pipeline checkpoint must keep its window at
            # decode time (rolling cache + masked reads).
            sliding_window=getattr(model, "sliding_window", 0),
            kv_cache_dtype=getattr(model, "kv_cache_dtype", "model"),
        )
        logger.info(
            "%spipeline checkpoint converted to the gpt tree for KV-cache "
            "decoding",
            label,
        )

    if decode_param_dtype == "compute":
        if getattr(model, "dtype", None) is not None and (
            model.dtype != getattr(model, "param_dtype", model.dtype)
        ):
            params = jax.tree.map(
                lambda a: a.astype(model.dtype)
                if jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                params,
            )
            logger.info(
                "%scast floating params to %s for decode (--decode-param-dtype "
                "param keeps the checkpoint's master precision)",
                label,
                jnp.dtype(model.dtype).name,
            )
    return model, params


def _handle_generate(args: argparse.Namespace) -> int:
    """First-class serving path: checkpoint → jit-compiled sampling.

    The reference exposes generation only as eager notebook cells
    (reference notebooks/trained_vs_random_completion.ipynb); here it is a
    CLI subcommand over the single-compile decode loop in
    ``llmtrain_tpu.generation``.
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()

    # Fail fast on inconsistent speculative flags — before any expensive
    # model/checkpoint work.
    if (args.draft_config is None) != (args.draft_from is None):
        _emit_error("--draft-config and --draft-from must be given together")
        return EXIT_CONFIG_ERROR
    if args.draft_config is not None and args.gamma < 1:
        _emit_error(f"--gamma must be >= 1, got {args.gamma}")
        return EXIT_CONFIG_ERROR
    if args.draft_config is not None and args.logprobs:
        _emit_error("--logprobs is not supported with speculative decoding")
        return EXIT_CONFIG_ERROR

    # Fail fast on a bad prompts file — before the expensive registry/
    # tokenizer/model build, and with a clean error instead of a traceback.
    file_prompts: list[str] | None = None
    if args.prompts_file is not None:
        try:
            lines = Path(args.prompts_file).read_text(encoding="utf-8").splitlines()
        except OSError as exc:
            _emit_error(f"cannot read --prompts-file: {exc}")
            return EXIT_TRAIN_FAILURE
        file_prompts = [ln for ln in lines if ln.strip()]
        if not file_prompts:
            _emit_error(f"{args.prompts_file}: no non-empty prompt lines")
            return EXIT_TRAIN_FAILURE

    try:
        import jax
        import numpy as np

        from .generation import generate
        from .models.lora import build_adapter

        initialize_registries()
        adapter, tokenizer, model = _build_decode_stack(cfg, logger)

        prompts: list[str] | None = None  # text prompts (file mode keeps all)
        if args.prompt_ids is not None:
            prompt_batches = [
                np.asarray(
                    [int(t) for t in args.prompt_ids.split(",") if t.strip()],
                    dtype=np.int32,
                )
            ]
        else:
            if tokenizer is None:
                _emit_error(
                    "no tokenizer available for --prompt/--prompts-file; "
                    "pass --prompt-ids instead"
                )
                return EXIT_TRAIN_FAILURE
            prompts = file_prompts if file_prompts is not None else [args.prompt]
            prompt_batches = [
                np.asarray(tokenizer.encode(p), dtype=np.int32) for p in prompts
            ]
        if any(ids.size == 0 for ids in prompt_batches):
            _emit_error("every prompt must contain at least one token")
            return EXIT_TRAIN_FAILURE
        if args.draft_config is not None:
            # Fail fast on a prompt that cannot fit the speculative
            # buffer — before any checkpoint I/O.
            longest = max(len(ids) for ids in prompt_batches)
            need = longest + args.max_new_tokens + args.gamma + 1
            if need > cfg.model.block_size:
                _emit_error(
                    f"prompt+max_new_tokens+gamma ({need}) exceeds the "
                    f"target model's block_size ({cfg.model.block_size})"
                )
                return EXIT_CONFIG_ERROR

        model, params, ckpt_path, step = _load_decode_params(
            cfg,
            adapter,
            model,
            args.from_spec,
            ema=args.ema,
            decode_param_dtype=args.decode_param_dtype,
            quantize=args.quantize,
            logger=logger,
        )

        # --- speculative decoding: load the draft model, then decode each
        # prompt via draft-and-verify (speculative.py). Exact w.r.t. the
        # target: greedy output is bit-identical, sampling follows the
        # target's distribution.
        draft = None
        if args.draft_config is not None:
            try:
                draft_cfg, _, _ = load_and_validate_config(args.draft_config)
            except ConfigLoadError as exc:
                _emit_error(exc.message, details=exc.details, errors=exc.errors)
                return EXIT_CONFIG_ERROR
            draft_lora_err = _lora_spec_error(draft_cfg)
            if draft_lora_err is not None:
                _emit_error(draft_lora_err)
                return EXIT_CONFIG_ERROR
            # Same fail-fast bound as the target's, BEFORE checkpoint I/O.
            longest = max(len(ids) for ids in prompt_batches)
            need = longest + args.max_new_tokens + args.gamma + 1
            if need > draft_cfg.model.block_size:
                _emit_error(
                    f"prompt+max_new_tokens+gamma ({need}) exceeds the "
                    f"draft model's block_size ({draft_cfg.model.block_size})"
                )
                return EXIT_CONFIG_ERROR
            draft_adapter = build_adapter(draft_cfg)
            draft_model = draft_adapter.build_model(draft_cfg)
            draft_model, draft_params, _, _ = _load_decode_params(
                draft_cfg,
                draft_adapter,
                draft_model,
                args.draft_from,
                ema=False,
                decode_param_dtype=args.decode_param_dtype,
                quantize=args.quantize,
                logger=logger,
                label="draft ",
            )
            if draft_model.vocab_size != model.vocab_size:
                _emit_error(
                    f"draft vocab_size ({draft_model.vocab_size}) != target "
                    f"vocab_size ({model.vocab_size}) — speculative decoding "
                    "needs a shared vocabulary"
                )
                return EXIT_CONFIG_ERROR
            draft = (draft_model, draft_params)

        eos_token_id = args.eos_token_id
        if eos_token_id is None and tokenizer is not None:
            # tiktoken encodings expose the end-of-text id as eot_token.
            eos_token_id = getattr(tokenizer, "eot_token", None)

        # Batch per prompt length: generate() takes a rectangular (B, Tp)
        # batch, so equal-length prompts share ONE compiled decode loop.
        by_len: dict[int, list[int]] = {}
        for i, ids in enumerate(prompt_batches):
            by_len.setdefault(len(ids), []).append(i)
        results: list[dict] = [{} for _ in prompt_batches]
        for tp, idxs in sorted(by_len.items()):
            stacked = np.stack([prompt_batches[i] for i in idxs])
            group_lps = None
            if draft is not None:
                from .speculative import speculative_generate

                # speculative_generate is batch-1: decode the group's
                # rows one at a time (same compiled program per length).
                rows = [
                    speculative_generate(
                        model,
                        params,
                        draft[0],
                        draft[1],
                        stacked[row : row + 1],
                        max_new_tokens=args.max_new_tokens,
                        gamma=args.gamma,
                        temperature=args.temperature,
                        top_k=args.top_k if args.top_k > 0 else None,
                        # generate()'s convention: 0 or 1 disables nucleus.
                        top_p=(
                            args.top_p
                            if args.top_p is not None and 0 < args.top_p < 1
                            else None
                        ),
                        eos_token_id=eos_token_id,
                        # Two folds (group, then row): collision-free
                        # streams however large a prompt-length group is.
                        rng=jax.random.fold_in(
                            jax.random.fold_in(jax.random.key(args.seed), tp),
                            row,
                        ),
                    )
                    for row in range(stacked.shape[0])
                ]
                out = np.concatenate(rows, axis=0)
            else:
                gen_out = generate(
                    model,
                    params,
                    stacked,
                    max_new_tokens=args.max_new_tokens,
                    # Fold the length-group in so different groups don't draw
                    # from identical sample streams at each decode step.
                    rng=jax.random.fold_in(jax.random.key(args.seed), tp),
                    temperature=args.temperature,
                    top_k=args.top_k,  # generate() maps <=0 to "disabled"
                    top_p=args.top_p,
                    eos_token_id=eos_token_id,
                    return_logprobs=args.logprobs,
                )
                if args.logprobs:
                    out, group_lps = gen_out
                else:
                    out = gen_out
            for row, i in enumerate(idxs):
                output_ids = [int(t) for t in out[row]]
                results[i] = {
                    "prompt_ids": [int(t) for t in prompt_batches[i]],
                    "completion_ids": output_ids[tp:],
                    "output_ids": output_ids,
                    "text": (
                        tokenizer.decode(output_ids) if tokenizer is not None else None
                    ),
                }
                if args.logprobs and group_lps is not None:
                    results[i]["logprobs"] = [
                        round(float(x), 6) for x in group_lps[row]
                    ]
                if prompts is not None:
                    results[i]["prompt"] = prompts[i]

        if args.json:
            payload: dict[str, Any] = {"checkpoint": str(ckpt_path), "step": step}
            if args.prompts_file is not None:
                # File mode ALWAYS emits "results" (even for one line) so
                # consumers get a stable schema per input mode.
                payload["results"] = results
            else:
                payload.update(results[0])  # single-prompt contract unchanged
            print(json.dumps(payload))
        else:
            rendered = [
                r["text"]
                if r["text"] is not None
                else " ".join(str(t) for t in r["output_ids"])
                for r in results
            ]
            print("\n\n---\n\n".join(rendered))
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        logger.exception("generation failed: %s", exc)
        _emit_error(f"generation failed: {exc}")
        return exit_code_for_exception(exc)
    return EXIT_OK


def _handle_chaos(args: argparse.Namespace) -> int:
    """Seeded kill/resume drill over real train subprocesses.

    Exit 0 only when every cycle's invariants held AND the final trajectory
    is bitwise-identical to the uninterrupted reference; exit 1 when the
    crash-consistency contract broke (that is the signal this command
    exists to produce); exit 2 for config problems."""
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    if args.cycles < 1:
        _emit_error("--cycles must be >= 1")
        return EXIT_CONFIG_ERROR
    configure_platform(cfg.run.device)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    from .resilience.chaos import ChaosInvariantError, run_chaos

    try:
        result = run_chaos(
            args.config,
            cycles=args.cycles,
            seed=args.seed,
            max_steps=args.max_steps,
            save_every=args.save_every,
            work_dir=args.work_dir,
            timeout_sec=args.timeout_sec,
        )
    except ChaosInvariantError as exc:
        logger.error("chaos drill FAILED: %s", exc)
        _emit_error(f"chaos invariant violated: {exc}")
        return EXIT_TRAIN_FAILURE
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        logger.exception("chaos drill errored: %s", exc)
        _emit_error(f"chaos drill errored: {exc}")
        return exit_code_for_exception(exc)
    if args.json:
        print(json.dumps(result))
    else:
        print(
            f"chaos drill passed: {result['kills_delivered']} kill(s) "
            f"(incl. {result['kill_during_checkpoint_cycles']} inside a "
            f"checkpoint write) over {result['max_steps']} steps; "
            f"{result['trajectory_points_compared']} trajectory point(s) and "
            f"the final checkpoint are bitwise-identical to the "
            f"uninterrupted reference (final_loss="
            f"{result['final_loss']}); artifacts in {result['work_dir']}"
        )
        if result.get("goodput"):
            gp = result["goodput"]
            print(
                f"goodput: {gp['goodput_frac']:.4f} of {gp['wall_clock_sec']}s "
                f"wall-clock across {gp['num_segments']} segment(s) "
                f"(recomputed {gp['categories']['recomputed']}s, "
                f"restart_overhead {gp['categories']['restart_overhead']}s) — "
                "full ledger via `llmtrain goodput --run-dir "
                f"{result['work_dir']}/runs/chaos`"
            )
    return EXIT_OK


def _handle_goodput(args: argparse.Namespace) -> int:
    """Post-hoc goodput ledger for any past run directory.

    Pure artifact read (timeline.jsonl + manifests + heartbeat mtime):
    works with every process of the run dead, which is the point. Exit 0
    with the ledger; exit 1 when the run dir has no segment-delimited
    timeline (pre-ledger run or telemetry disabled)."""
    from pathlib import Path

    from .telemetry.goodput import compute_goodput, render_goodput_md

    run_dir = Path(args.run_dir)
    if not run_dir.is_dir():
        _emit_error(f"run dir not found: {run_dir}")
        return EXIT_CONFIG_ERROR
    ledger = compute_goodput(run_dir)
    if ledger is None:
        _emit_error(
            f"no goodput ledger for {run_dir}: telemetry/timeline.jsonl is "
            "missing or carries no segment headers (run predates the "
            "ledger, or telemetry.timeline was disabled)"
        )
        return EXIT_TRAIN_FAILURE
    if args.json:
        print(json.dumps(ledger))
    else:
        print(f"# Goodput — {run_dir}\n")
        print(render_goodput_md(ledger), end="")
    return EXIT_OK


def _handle_trace(args: argparse.Namespace) -> int:
    """Fleet-wide request-trace reassembly (telemetry/trace_collect.py).

    Pure artifact read, like ``goodput``: scans every --run-dir for
    ``*timeline*.jsonl``, rebuilds cross-process span trees from the
    tail-sampled ``cat="trace"`` events (router root → traceparent-
    propagated replica children), and answers slowest/show/summary/merge.
    Works with every fleet process dead."""
    from .telemetry.trace_collect import (
        collect_traces,
        critical_path,
        discover_sources,
        format_tree,
        merge_perfetto,
        slowest,
        summarize,
    )

    missing = [d for d in args.run_dirs if not Path(d).exists()]
    if missing:
        _emit_error(f"run dir(s) not found: {', '.join(missing)}")
        return EXIT_CONFIG_ERROR
    sources = discover_sources(args.run_dirs)
    if not sources:
        _emit_error(
            "no *timeline*.jsonl under the given --run-dir(s) — serve "
            "with --trace-dir (or point at a serve-bench out dir) so "
            "each process writes its timeline"
        )
        return EXIT_CONFIG_ERROR
    traces = collect_traces(sources)

    if args.action == "merge":
        out = Path(
            args.out or (Path(args.run_dirs[0]) / "merged_trace.json")
        )
        merge_perfetto(sources, out, traces=traces)
        unaligned = [s.label for s in sources if s.start_unix_time is None]
        if unaligned and any(s.start_unix_time is not None for s in sources):
            print(
                "warning: timeline(s) with no segment header could not be "
                f"time-aligned with the fleet: {', '.join(unaligned)} — "
                "their events are rebased to the merge start, so cross-"
                "process ordering against them is not meaningful",
                file=sys.stderr,
            )
        print(
            json.dumps(
                {
                    "merged": str(out),
                    "processes": [s.label for s in sources],
                    "traces": len(traces),
                    "unaligned": unaligned,
                    "viewer": "https://ui.perfetto.dev",
                },
                indent=None if args.json else 2,
            )
        )
        return EXIT_OK

    if not traces:
        _emit_error(
            "timelines found but no sampled request traces in them — "
            "only slow/errored/failed-over/forced requests keep full "
            "detail (tail sampling); force one with the `X-Trace: force` "
            "header or check telemetry.tracing.enabled"
        )
        return EXIT_TRAIN_FAILURE

    if args.action == "summary":
        print(json.dumps(summarize(traces), indent=None if args.json else 2))
        return EXIT_OK

    if args.action == "slowest":
        rows = []
        for tr in slowest(traces, k=args.k):
            root = tr.root
            rows.append(
                {
                    "trace_id": tr.trace_id,
                    "total_ms": round(tr.duration_ms, 3),
                    "root": root.name if root else None,
                    "spans": len(tr.spans),
                    "processes": tr.sources,
                    "sampled": (root.args.get("sampled") if root else None),
                    "request_id": (
                        root.args.get("request_id") if root else None
                    ),
                }
            )
        if args.json:
            print(json.dumps(rows))
        else:
            print(json.dumps(rows, indent=2))
        return EXIT_OK

    # show
    if not args.trace_id:
        _emit_error(
            "`trace show` needs a trace id (or unique prefix) — list "
            "candidates with `llmtrain trace slowest`"
        )
        return EXIT_CONFIG_ERROR
    matches = [
        t for t in traces.values() if t.trace_id.startswith(args.trace_id)
    ]
    if not matches:
        _emit_error(f"no trace matching {args.trace_id!r} in the run dirs")
        return EXIT_TRAIN_FAILURE
    if len(matches) > 1:
        _emit_error(
            f"trace id prefix {args.trace_id!r} is ambiguous "
            f"({len(matches)} matches) — give more hex digits"
        )
        return EXIT_CONFIG_ERROR
    tr = matches[0]
    path = critical_path(tr)
    if args.json:
        print(json.dumps({"tree": format_tree(tr), "critical_path": path}))
    else:
        for line in format_tree(tr):
            print(line)
        print()
        print(json.dumps(path, indent=2))
    return EXIT_OK


def _handle_fleet(args: argparse.Namespace) -> int:
    """Multi-tenant fleet supervisor / preemption-storm drill.

    Exit 0 when every tenant completed (and, under --storm, every parity
    and scheduling invariant held); exit 1 when a tenant failed or an
    invariant broke; exit 2 for config problems."""
    try:
        cfg, _, resolved = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    configure_platform(cfg.run.device)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    from .resilience.harness import DrillInvariantError

    try:
        if args.storm:
            from .fleet.chaos import run_fleet_storm

            result = run_fleet_storm(
                args.config,
                seed=args.seed,
                max_steps=args.max_steps,
                save_every=args.save_every,
                work_dir=args.work_dir,
                timeout_sec=args.timeout_sec,
                step_delay_sec=args.step_delay_sec,
            )
            if args.json:
                print(json.dumps(result))
            else:
                parities = {
                    n: r["parity"] for n, r in result["tenants"].items()
                }
                print(
                    f"fleet storm passed: {result['total_evictions']} "
                    f"eviction(s) (mid-checkpoint kill on "
                    f"{result['mid_checkpoint_kill_tenant']}), "
                    f"{result['total_respawns']} respawn(s), "
                    f"{result['capacity_changes']} capacity change(s) across "
                    f"{len(result['tenants'])} tenant(s); per-tenant parity "
                    f"{parities}; artifacts in {result['work_dir']}"
                )
            return EXIT_OK

        from .fleet.supervisor import FleetSupervisor

        work_dir = args.work_dir or str(
            Path(cfg.output.root_dir) / f"fleet_{cfg.run.name}"
        )
        try:
            sup = FleetSupervisor(
                cfg,
                resolved,
                work_dir=work_dir,
                seed=args.seed,
                max_steps=args.max_steps,
                save_every=args.save_every,
                fresh=args.fresh,
            )
        except ValueError as exc:
            # Constructor-time validation only (no tenants, wrong device,
            # infeasible world sizes): deterministic config problems. A
            # ValueError INSIDE the run is a runtime failure and takes the
            # taxonomy path below.
            _emit_error(str(exc))
            return EXIT_CONFIG_ERROR
        try:
            report = sup.run(timeout_sec=args.timeout_sec)
        except DrillInvariantError:
            raise  # the outer handler maps it to EXIT_TRAIN_FAILURE
        except Exception as exc:  # noqa: BLE001 — run-time, NOT config
            # Includes ValueError: past construction, nothing about the
            # config is in question — route through the taxonomy instead
            # of the outer config-error mapping.
            logger.exception("fleet run errored: %s", exc)
            _emit_error(f"fleet run errored: {exc}")
            return exit_code_for_exception(exc)
        if args.json:
            print(json.dumps(report))
        else:
            print(
                f"fleet run finished: {report['totals']['completed']}/"
                f"{len(report['tenants'])} tenant(s) completed, "
                f"{report['totals']['evictions']} eviction(s), "
                f"{report['totals']['respawns']} respawn(s); report in "
                f"{sup.work_dir / 'fleet_report.json'}"
            )
        return EXIT_OK if report["totals"]["failed"] == 0 else EXIT_TRAIN_FAILURE
    except DrillInvariantError as exc:
        logger.error("fleet invariant violated: %s", exc)
        _emit_error(f"fleet invariant violated: {exc}")
        return EXIT_TRAIN_FAILURE
    except ValueError as exc:
        # Storm pre-run validation (tenant count, infeasible fault
        # windows, supervisor construction) raises ValueError before any
        # subprocess launches — deterministic config problems.
        _emit_error(str(exc))
        return EXIT_CONFIG_ERROR
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        logger.exception("fleet run errored: %s", exc)
        _emit_error(f"fleet run errored: {exc}")
        return exit_code_for_exception(exc)


def _handle_profile(args: argparse.Namespace) -> int:
    """N-step cost probe → ``profile_report.json`` (docs/observability.md).

    Runs ``--steps`` real training steps on the config (run-dir-less, so
    no checkpoints/reports are written), then AOT-lowers AND -compiles the
    jitted train step to mine XLA's cost_analysis, the per-op HLO table,
    compile wall-times and the compiled memory footprint — the probe-run
    signal ``llmtrain tune`` (ROADMAP item 3) will sweep over. ``--serve``
    additionally profiles the paged prefill/decode programs at their
    largest shape buckets against abstract parameters (no checkpoint
    needed; nothing executes).
    """
    try:
        cfg, _, _ = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    if args.steps < 1:
        _emit_error("--steps must be >= 1")
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    configure_logging(level=cfg.logging.level, json_output=cfg.logging.json_output)
    logger = get_logger()
    initialize_registries()
    try:
        get_model_adapter(cfg.model.name)
        get_data_module(cfg.data.name)
    except RegistryError as exc:
        _emit_error(str(exc))
        return EXIT_CONFIG_ERROR

    # Probe config: N steps, every boundary logged, no endpoint bind, no
    # competing report/attribution work (the profile builds its own).
    # Config models are frozen — rebuild through validation.
    dump = cfg.model_dump()
    dump["trainer"]["max_steps"] = args.steps
    dump["trainer"]["log_every_steps"] = 1
    dump["telemetry"]["prometheus"] = False
    dump["telemetry"]["report"] = False
    dump["telemetry"]["perf_attribution"] = False
    probe_cfg = type(cfg).model_validate(dump)

    import jax

    from .telemetry import profiling
    from .training import Trainer
    from .utils.hw import transformer_flops_per_token

    try:
        trainer = Trainer(probe_cfg, run_dir=None, tracker=None)
        t0 = time.perf_counter()
        result = trainer.fit()
        probe_wall = time.perf_counter() - t0
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        logger.exception("profile probe run failed: %s", exc)
        _emit_error(f"profile probe run failed: {exc}")
        return exit_code_for_exception(exc)

    peaks = profiling.resolve_peaks(None, cfg.telemetry.device_peaks)
    latest = {k: v[0] for k, v in trainer._telemetry.metrics.latest().items()}
    step_time_sec = latest.get("train/step_time_sec") or 0.0
    run_key = jax.random.key(cfg.run.seed)

    executables: list[dict[str, Any]] = []
    if trainer._batch_struct is not None:
        train_prof = profiling.aot_profile(
            trainer._jit_train_step,
            (trainer._state, trainer._batch_struct, run_key),
            name="train_step",
            peaks=peaks,
            collective_bytes=profiling.gradient_collective_bytes(
                {a: s for a, s in trainer._mesh.shape.items()},
                float(trainer._trainable_count) * 4.0,
            ),
            top_k=args.top_k,
            n_chips=int(trainer._mesh.devices.size),
        )
        if train_prof is not None:
            executables.append(train_prof)

    if args.serve:
        executables += _profile_serving_buckets(
            cfg, peaks=peaks, top_k=args.top_k, logger=logger
        )

    if not executables:
        _emit_error("no executable could be profiled (see logs)")
        return EXIT_TRAIN_FAILURE

    palm = transformer_flops_per_token(
        n_params=trainer._param_count,
        n_layers=cfg.model.n_layers,
        seq_len=trainer._train_seqlen,
        d_model=cfg.model.d_model,
        n_trainable_params=trainer._trainable_count,
    )
    attribution = profiling.build_perf_attribution(
        executables=executables,
        peaks=peaks,
        n_chips=int(trainer._mesh.devices.size),
        step_time_ms=step_time_sec * 1e3 if step_time_sec > 0 else None,
        tokens_per_step=float(trainer._tokens_per_step) or None,
        palm_flops_per_token=palm,
        measured_mfu=latest.get("train/mfu"),
        span_totals=trainer._telemetry.timeline.span_totals(),
        steps=args.steps,
    )

    # HBM footprint, two views side by side: the memory monitor's live
    # accounting during the probe vs the compiled executable's static
    # buffer analysis — disagreement localizes fragmentation/runtime
    # overhead vs model-inherent footprint.
    memory_block: dict[str, Any] = {}
    if trainer._telemetry.memory is not None:
        memory_block["monitor_peaks"] = dict(trainer._telemetry.memory.peaks())
        memory_block["monitor_source"] = trainer._telemetry.memory.source
    primary_mem = (executables[0].get("memory") or {}) if executables else {}
    if primary_mem:
        memory_block["compiled_train_step"] = primary_mem

    report = {
        "schema": "llmtrain-profile-report/1",
        "config": str(args.config),
        "run_name": cfg.run.name,
        "device_kind": peaks.get("device_kind", "unknown"),
        "n_devices": int(trainer._mesh.devices.size),
        "peaks": {k: peaks[k] for k in ("peak_flops", "hbm_bytes_per_sec", "ici_bytes_per_sec")},
        "probe": {
            "steps": args.steps,
            "wall_time_sec": round(probe_wall, 3),
            "step_time_ms": round(step_time_sec * 1e3, 3),
            "tokens_per_sec": latest.get("train/tokens_per_sec"),
            "mfu_measured": latest.get("train/mfu"),
            "final_loss": result.final_loss,
        },
        "executables": executables,
        "perf_attribution": attribution,
        "memory": memory_block,
    }

    if args.output is not None:
        out_path = Path(args.output)
    else:
        out_path = (
            Path(cfg.output.root_dir)
            / f"profile_{cfg.run.name}"
            / "profile_report.json"
        )
    try:
        out_path.parent.mkdir(parents=True, exist_ok=True)
        out_path.write_text(
            json.dumps(report, indent=2, sort_keys=False), encoding="utf-8"
        )
    except (OSError, TypeError, ValueError) as exc:
        _emit_error(f"writing {out_path} failed: {exc}")
        return EXIT_TRAIN_FAILURE

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        lines = [f"profile report: {out_path}"]
        for exe in executables:
            roof = exe.get("roofline") or {}
            lines.append(
                f"  {exe['name']}: {exe.get('flops', 0.0):.3g} flops, "
                f"{exe.get('bytes_accessed', 0.0):.3g} bytes, "
                f"compile {exe.get('compile_time_s', 0.0):.2f}s → "
                f"{roof.get('class', '?')}-bound"
            )
            for row in profiling.render_top_ops_markdown(exe.get("top_ops") or []):
                lines.append("    " + row)
        mfu_block = attribution.get("mfu") or {}
        if mfu_block:
            lines.append(
                f"  MFU analytical {mfu_block.get('analytical')} vs measured "
                f"{mfu_block.get('measured')} (ratio "
                f"{mfu_block.get('ratio_analytical_over_measured')}, "
                f"reconciled: {mfu_block.get('reconciled')})"
            )
        print("\n".join(lines))
    return EXIT_OK


def _profile_serving_buckets(
    cfg, *, peaks: dict[str, float], top_k: int, logger
) -> list[dict[str, Any]]:
    """AOT profiles of the paged prefill/decode programs, checkpoint-free.

    The engine's :meth:`cost_profile` only reads parameter SHAPES, so an
    ``eval_shape`` of ``model.init`` stands in for real weights — zero
    init work, nothing executes. Failures degrade to an empty list (the
    train-step profile stands on its own).
    """
    try:
        import jax
        import jax.numpy as jnp

        from .serving import PagedDecodeEngine

        adapter, _, model = _build_decode_stack(cfg, logger, label="profile: ")
        if not hasattr(model, "for_paged_decoding"):
            logger.warning(
                "model %s has no paged-decoding support; skipping serve profiles",
                cfg.model.name,
            )
            return []
        variables = jax.eval_shape(
            lambda: model.init(
                jax.random.key(0),
                jnp.zeros((1, int(model.block_size)), jnp.int32),
                deterministic=True,
            )
        )
        scfg = cfg.serving
        engine = PagedDecodeEngine(
            model,
            variables["params"],
            block_tokens=scfg.block_tokens,
            num_blocks=scfg.num_blocks or None,
            max_batch_slots=scfg.max_batch_slots,
            prompt_buckets=scfg.prompt_buckets or None,
            batch_buckets=scfg.batch_buckets or None,
        )
        return engine.cost_profile(peaks=peaks, top_k=top_k)
    except Exception as exc:  # noqa: BLE001 — serve profiles are additive
        logger.warning("serving bucket profile failed: %s", exc)
        return []


def _handle_train(args: argparse.Namespace) -> int:
    try:
        cfg, _, resolved = load_and_validate_config(args.config)
    except ConfigLoadError as exc:
        _emit_error(exc.message, details=exc.details, errors=exc.errors)
        return EXIT_CONFIG_ERROR
    lora_err = _lora_spec_error(cfg)
    if lora_err is not None:
        _emit_error(lora_err)
        return EXIT_CONFIG_ERROR

    configure_platform(cfg.run.device)
    configure_compilation_cache(cfg.run.compilation_cache_dir)
    dist_state: DistState | None = None
    if cfg.distributed.enabled:
        # Rendezvous against a coordinator that is still coming up (k8s pods
        # start in arbitrary order) is retried with exponential backoff
        # instead of failing the pod; the flaky() wrapper is the
        # fault-injection hook exercising this path in tests.
        from .distributed import resolve_topology
        from .resilience import FaultPlan, retry, retry_rng

        plan = FaultPlan.from_config(cfg.resilience.faults)
        # Full-jitter backoff seeded per (run seed, rank): every pod of a
        # Job retries the coordinator on its own decorrelated schedule —
        # synchronized ladders are exactly how a transient rendezvous blip
        # becomes a repeated thundering herd. The rank comes from the SAME
        # resolution setup_distributed uses (resolve_topology: JAX-native
        # env beats torch-style env beats config) so per-rank
        # decorrelation holds on every deployment flavor; a topology too
        # broken to resolve falls back to rank 0 and lets the retried
        # setup_distributed surface the real error.
        try:
            rank_hint, _, _ = resolve_topology(cfg.distributed)
        except Exception:  # noqa: BLE001 — jitter seeding must not mask it
            rank_hint = 0
        try:
            dist_state = retry(
                plan.flaky(
                    "distributed_init", lambda: setup_distributed(cfg.distributed)
                ),
                attempts=cfg.resilience.retry_attempts,
                base_delay=cfg.resilience.retry_base_delay,
                description="distributed init",
                rng=retry_rng(cfg.run.seed, rank_hint),
            )
        except ValueError as exc:
            # Topology/coordinator misconfiguration (resolve_topology and
            # setup_distributed raise ValueError for these) is deterministic
            # — restarting the pod replays it, so fail the Job fast.
            _emit_error(f"distributed init failed: {exc}")
            return EXIT_CONFIG_ERROR
        except Exception as exc:  # noqa: BLE001 — CLI boundary
            # Everything else at the rendezvous stage is environmental
            # (coordinator pod still scheduling, DNS not propagated,
            # timeout): exit EX_TEMPFAIL so the orchestrator restarts this
            # pod instead of failing the whole Job (k8s/job.yaml
            # podFailurePolicy).
            _emit_error(f"distributed init failed: {exc}")
            return EXIT_RETRYABLE_INFRA
    is_main = dist_state is None or dist_state.is_main

    logger = get_logger()
    tracker: Tracker = NullTracker()
    exit_code = EXIT_OK
    tracker_started = False
    try:
        run_id = args.run_id or cfg.output.run_id
        if args.auto_resume and run_id is None:
            _emit_error(
                "--auto-resume requires a stable run id (--run-id or output.run_id): "
                "a generated id is fresh on every restart"
            )
            return EXIT_CONFIG_ERROR
        if run_id is None:
            run_id = generate_run_id(cfg.run.name, cfg.output.root_dir)
        run_id = _agree_run_id(run_id, dist_state)

        # Rank-0-only I/O: non-main ranks never touch the run dir
        # (reference cli.py:246-248, trainer.py:402-406). All ranks must
        # agree on the outcome — if only rank 0 bailed here, the other ranks
        # would run on into the first collective and hang until timeout.
        run_dir: Path | None = None
        run_dir_ok = True
        resuming_existing = False
        if is_main:
            try:
                run_dir = create_run_directory(cfg.output.root_dir, run_id)
            except FileExistsError:
                if args.auto_resume:
                    # Preemption restart: reuse the dir, continue from its
                    # latest checkpoint if one exists (new capability — the
                    # reference only has manual --resume, SURVEY §5).
                    run_dir = Path(cfg.output.root_dir) / run_id
                    (run_dir / "logs").mkdir(parents=True, exist_ok=True)
                    from .training.checkpoint import CheckpointManager

                    resuming_existing = (
                        CheckpointManager(run_dir / "checkpoints").latest_checkpoint()
                        is not None
                    )
                else:
                    run_dir_ok = False
        if not _agree_flag(run_dir_ok, dist_state):
            if is_main:
                _emit_error(
                    f"run directory already exists for run id {run_id!r}",
                    details="pass a fresh --run-id or let the run id be generated",
                )
            return EXIT_TRAIN_FAILURE
        resume_spec = args.resume
        if _agree_flag(resuming_existing, dist_state):
            # Unambiguous dir spec, computable on every rank. (A bare run id
            # would first be tried as a CWD-relative path by
            # resolve_resume_path and can collide with unrelated entries.)
            resume_spec = str(Path(cfg.output.root_dir) / run_id / "checkpoints")

        log_file = None
        if cfg.logging.log_to_file and run_dir is not None:
            log_file = run_dir / "logs" / cfg.logging.file_name
        level = "DEBUG" if args.verbose else cfg.logging.level
        # Under --json, all logs go to stderr so stdout stays machine-parseable
        # (reference cli.py:281-288). Logs already default to stderr.
        configure_logging(
            level=level, json_output=cfg.logging.json_output, log_file=log_file
        )

        if run_dir is not None:
            if cfg.output.save_config_copy:
                write_resolved_config(run_dir, resolved)
            if cfg.output.save_meta_json:
                meta = generate_meta(
                    run_id=run_id,
                    run_name=cfg.run.name,
                    config_path=args.config,
                    resolved_config_path=run_dir / "config.yaml",
                )
                write_meta_json(run_dir, meta)

        initialize_registries()
        _warn_unknown_extras(cfg)
        try:
            get_model_adapter(cfg.model.name)
            get_data_module(cfg.data.name)
        except RegistryError as exc:
            _emit_error(str(exc))
            return EXIT_CONFIG_ERROR

        tracker = _create_tracker(cfg, dist_state, run_id)
        tracker.start_run(run_id, cfg.mlflow.run_name)
        tracker_started = True

        if args.dry_run:
            from .training import run_dry_run

            dry_result = run_dry_run(cfg)
            summary = format_run_summary(
                cfg,
                run_id=run_id,
                run_dir=str(run_dir) if run_dir else None,
                dry_run=True,
                dry_run_result=dry_result,
                as_json=args.json,
            )
        else:
            from .training import Trainer

            # Non-main ranks get the run-dir PATH too (never created or
            # written by them — every write stays rank-0-gated inside the
            # Trainer): on the shared runs volume it gives all ranks a
            # readable checkpoint dir, which is what makes the loss-spike
            # rollback consensus restore the same file on every host.
            trainer_run_dir = run_dir
            if (
                run_dir is None
                and dist_state is not None
                and dist_state.num_processes > 1
            ):
                trainer_run_dir = Path(cfg.output.root_dir) / run_id
            trainer = Trainer(cfg, trainer_run_dir, tracker, dist_state)
            result = trainer.fit(resume_from=resume_spec)
            summary = format_run_summary(
                cfg,
                run_id=run_id,
                run_dir=str(run_dir) if run_dir else None,
                dry_run=False,
                train_result=result,
                as_json=args.json,
            )
        if is_main:
            print(json.dumps(summary) if args.json else summary)
            _log_run_artifacts(tracker, run_dir)
    except Exception as exc:  # noqa: BLE001 — CLI boundary
        logger.exception("training failed: %s", exc)
        # Taxonomy (resilience/exit_codes.py): transient infra causes exit
        # EX_TEMPFAIL-style retryable codes; deterministic failures exit
        # fatal so the orchestrator does not replay them.
        exit_code = exit_code_for_exception(exc)
        _emit_error(f"training failed: {exc} (exit {exit_code})")
    finally:
        try:
            if tracker_started:
                tracker.end_run("FINISHED" if exit_code == EXIT_OK else "FAILED")
        finally:
            teardown_distributed()
    return exit_code


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "train":
        return _handle_train(args)
    if args.command == "chaos":
        return _handle_chaos(args)
    if args.command == "fleet":
        return _handle_fleet(args)
    if args.command == "generate":
        return _handle_generate(args)
    if args.command == "serve":
        return _handle_serve(args)
    if args.command == "serve-bench":
        return _handle_serve_bench(args)
    if args.command == "promote":
        return _handle_promote(args)
    if args.command == "eval":
        return _handle_eval(args)
    if args.command == "train-tokenizer":
        return _handle_train_tokenizer(args)
    if args.command == "export-checkpoint":
        return _handle_export_checkpoint(args)
    if args.command == "import-checkpoint":
        return _handle_import_checkpoint(args)
    if args.command == "average-checkpoints":
        return _handle_average_checkpoints(args)
    if args.command == "profile":
        return _handle_profile(args)
    if args.command == "plan":
        return _handle_plan(args)
    if args.command == "tune":
        return _handle_tune(args)
    if args.command == "goodput":
        return _handle_goodput(args)
    if args.command == "trace":
        return _handle_trace(args)
    if args.command == "validate":
        return _handle_validate(args)
    if args.command == "print-config":
        return _handle_print_config(args)
    parser.error(f"unknown command {args.command!r}")
    return EXIT_CONFIG_ERROR


if __name__ == "__main__":
    sys.exit(main())
