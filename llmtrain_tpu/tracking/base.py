"""Tracker protocol + no-op implementation.

Parity target: reference ``src/llmtrain/tracking/base.py`` — ``Tracker``
Protocol with start_run/log_params/log_metrics/log_artifact/end_run (:10-26)
and ``NullTracker`` (:29).
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable


@runtime_checkable
class Tracker(Protocol):
    def start_run(self, run_id: str, run_name: str | None = None) -> None: ...

    def log_params(self, params: dict[str, Any]) -> None: ...

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None: ...

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None: ...

    def end_run(self, status: str = "FINISHED") -> None: ...


class NullTracker:
    """No-op tracker for non-main ranks and disabled tracking."""

    def start_run(self, run_id: str, run_name: str | None = None) -> None:
        pass

    def log_params(self, params: dict[str, Any]) -> None:
        pass

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None:
        pass

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None:
        pass

    def end_run(self, status: str = "FINISHED") -> None:
        pass
