"""MLflow tracker.

Parity target: reference ``src/llmtrain/tracking/mlflow.py`` — lazy mlflow
import raising a clear RuntimeError when the extra is missing (:45-51),
set_tracking_uri/set_experiment/start_run (:54-61), nested-param flattening
to dot keys with JSON-encoded lists (:11-29).

Join semantics (reference mlflow.py:57-59, adapted): the reference joins by
an explicit MLflow run id; here the join key is the ``llmtrain.run_id`` tag.
``start_run`` searches the experiment for a run already tagged with the
framework run id and reattaches to it — so an ``--auto-resume`` relaunch
after preemption CONTINUES the original MLflow run instead of opening a
second one. Only one process (rank 0) ever gets a real tracker (non-main
ranks get NullTracker, see cli.py), so there is no concurrent-writer risk.
"""

from __future__ import annotations

import json
from typing import Any


def _flatten_params(params: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    flat: dict[str, Any] = {}
    for key, value in params.items():
        full = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(value, dict):
            flat.update(_flatten_params(value, full))
        elif isinstance(value, (list, tuple)):
            flat[full] = json.dumps(list(value))
        else:
            flat[full] = value
    return flat


class MLflowTracker:
    def __init__(
        self,
        tracking_uri: str,
        experiment: str,
        *,
        run_name: str | None = None,
    ) -> None:
        self._tracking_uri = tracking_uri
        self._experiment = experiment
        self._run_name = run_name
        self._mlflow = None
        self._active = False

    def _require_mlflow(self):
        if self._mlflow is None:
            try:
                import mlflow
            except ImportError as exc:
                raise RuntimeError(
                    "mlflow is not installed; install the [mlflow] extra or set "
                    "mlflow.enabled: false"
                ) from exc
            self._mlflow = mlflow
        return self._mlflow

    def start_run(self, run_id: str, run_name: str | None = None) -> None:
        mlflow = self._require_mlflow()
        mlflow.set_tracking_uri(self._tracking_uri)
        mlflow.set_experiment(self._experiment)
        existing = self._find_existing_run(run_id)
        if existing is not None:
            mlflow.start_run(run_id=existing)
        else:
            mlflow.start_run(run_name=run_name or self._run_name or run_id)
            mlflow.set_tag("llmtrain.run_id", run_id)
        self._active = True

    def _find_existing_run(self, run_id: str) -> str | None:
        """MLflow run id of an existing run tagged with this framework run id.

        The join key for crash-restart continuity: a relaunch with the same
        stable run id (``--auto-resume``) reattaches instead of starting a
        second MLflow run. Best-effort — any search failure means a fresh
        run, never a crashed launch.
        """
        mlflow = self._require_mlflow()
        if "'" in run_id or '"' in run_id:
            # Quotes can't be escaped portably in MLflow filter strings;
            # generated ids never contain them (run_id.py slugs), only a
            # hand-picked --run-id can. Skip the join rather than crash.
            from ..utils.logging import get_logger

            get_logger().warning(
                "run id %r contains quotes; skipping MLflow run-join search",
                run_id,
            )
            return None
        try:
            experiment = mlflow.get_experiment_by_name(self._experiment)
            if experiment is None:
                return None
            runs = mlflow.search_runs(
                experiment_ids=[experiment.experiment_id],
                filter_string=f"tags.\"llmtrain.run_id\" = '{run_id}'",
                max_results=1,
                output_format="list",
            )
        except Exception as exc:  # noqa: BLE001
            from ..utils.logging import get_logger

            get_logger().warning(
                "could not search for an existing MLflow run (%s); starting fresh",
                exc,
            )
            return None
        return runs[0].info.run_id if runs else None

    def log_params(self, params: dict[str, Any]) -> None:
        if self._active:
            self._require_mlflow().log_params(_flatten_params(params))

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None:
        if self._active:
            self._require_mlflow().log_metrics(metrics, step=step)

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None:
        if self._active:
            self._require_mlflow().log_artifact(local_path, artifact_path=artifact_path)

    def end_run(self, status: str = "FINISHED") -> None:
        if self._active:
            self._require_mlflow().end_run(status=status)
            self._active = False
