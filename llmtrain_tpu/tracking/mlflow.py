"""MLflow tracker.

Parity target: reference ``src/llmtrain/tracking/mlflow.py`` — lazy mlflow
import raising a clear RuntimeError when the extra is missing (:45-51),
set_tracking_uri/set_experiment/start_run (:54-61), nested-param flattening
to dot keys with JSON-encoded lists (:11-29).

Intentional divergence: the reference's join-an-existing-mlflow-run path is
not implemented — in this framework exactly one process (rank 0) ever gets a
real tracker (non-main ranks get NullTracker, see cli.py), so every tracked
run is fresh and the framework run id is recorded as a tag.
"""

from __future__ import annotations

import json
from typing import Any


def _flatten_params(params: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    flat: dict[str, Any] = {}
    for key, value in params.items():
        full = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(value, dict):
            flat.update(_flatten_params(value, full))
        elif isinstance(value, (list, tuple)):
            flat[full] = json.dumps(list(value))
        else:
            flat[full] = value
    return flat


class MLflowTracker:
    def __init__(
        self,
        tracking_uri: str,
        experiment: str,
        *,
        run_name: str | None = None,
    ) -> None:
        self._tracking_uri = tracking_uri
        self._experiment = experiment
        self._run_name = run_name
        self._mlflow = None
        self._active = False

    def _require_mlflow(self):
        if self._mlflow is None:
            try:
                import mlflow
            except ImportError as exc:
                raise RuntimeError(
                    "mlflow is not installed; install the [mlflow] extra or set "
                    "mlflow.enabled: false"
                ) from exc
            self._mlflow = mlflow
        return self._mlflow

    def start_run(self, run_id: str, run_name: str | None = None) -> None:
        mlflow = self._require_mlflow()
        mlflow.set_tracking_uri(self._tracking_uri)
        mlflow.set_experiment(self._experiment)
        mlflow.start_run(run_name=run_name or self._run_name or run_id)
        mlflow.set_tag("llmtrain.run_id", run_id)
        self._active = True

    def log_params(self, params: dict[str, Any]) -> None:
        if self._active:
            self._require_mlflow().log_params(_flatten_params(params))

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None:
        if self._active:
            self._require_mlflow().log_metrics(metrics, step=step)

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None:
        if self._active:
            self._require_mlflow().log_artifact(local_path, artifact_path=artifact_path)

    def end_run(self, status: str = "FINISHED") -> None:
        if self._active:
            self._require_mlflow().end_run(status=status)
            self._active = False
