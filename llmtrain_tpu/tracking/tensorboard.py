"""Native TensorBoard event-file tracker (zero dependencies).

Beyond-reference tracking backend (the reference ships MLflow only,
``src/llmtrain/tracking/mlflow.py``). Like the native SQLite store
(tracking/sqlite.py) this writes its format by hand so air-gapped TPU
images track out of the box: TensorBoard's on-disk protocol is TFRecord
framing (masked CRC-32C) around hand-encoded ``tensorflow.Event``
protobuf messages — both stable, versioned wire formats. Scalars land
as ``simple_value`` summaries (one event per ``log_metrics`` call);
params land once as a markdown table through the text plugin, which is
how TensorBoard renders run configuration.

Any TensorBoard (``tensorboard --logdir <dir>``) reads the output; the
tests parse it back with the real ``tensorboard`` reader when that
package is installed, and with a standalone TFRecord parser either way.

Protobuf wire encoding used (proto3, all hand-rolled below):

* ``Event``: 1 wall_time (double), 2 step (int64), 3 file_version
  (string), 5 summary (message).
* ``Summary``: 1 value (repeated message); ``Summary.Value``: 1 tag
  (string), 2 simple_value (float), 8 tensor (message), 9 metadata.
* ``SummaryMetadata``: 1 plugin_data (message: 1 plugin_name string);
  ``TensorProto``: 1 dtype (enum, DT_STRING=7), 8 string_val (bytes).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from pathlib import Path
from typing import Any

# ---------------------------------------------------------------- CRC-32C
# Castagnoli polynomial (reflected 0x1EDC6F41 -> 0x82F63B78), table-driven.
_CRC_TABLE: list[int] = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """TFRecord's rotated+offset mask over the raw CRC."""
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf
def _key(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _pb_bytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _pb_string(field: int, s: str) -> bytes:
    return _pb_bytes(field, s.encode("utf-8"))


def _pb_double(field: int, x: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", x)


def _pb_float(field: int, x: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", float(x))


def _pb_int64(field: int, n: int) -> bytes:
    return _key(field, 0) + _varint(n & 0xFFFFFFFFFFFFFFFF)


def _scalar_value(tag: str, value: float) -> bytes:
    return _pb_bytes(1, _pb_string(1, tag) + _pb_float(2, value))


def _text_value(tag: str, text: str) -> bytes:
    """Summary.Value carrying a string TensorProto for the text plugin."""
    tensor = _pb_int64(1, 7) + _pb_bytes(8, text.encode("utf-8"))  # DT_STRING
    metadata = _pb_bytes(1, _pb_string(1, "text"))  # plugin_data.plugin_name
    return _pb_bytes(1, _pb_string(1, tag) + _pb_bytes(8, tensor) + _pb_bytes(9, metadata))


def _event(wall_time: float, step: int | None, body: bytes) -> bytes:
    ev = _pb_double(1, wall_time)
    if step is not None:
        ev += _pb_int64(2, step)
    return ev + body


def _tfrecord(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


def resolve_logdir(tracking_uri: str) -> Path:
    """``file:`` URIs and plain paths both point at a logdir root."""
    uri = tracking_uri
    if uri.startswith("file://"):
        uri = uri[len("file://") :]
    elif uri.startswith("file:"):
        uri = uri[len("file:") :]
    return Path(uri)


class TensorBoardTracker:
    """Tracker backend writing one event file per run.

    Layout is TensorBoard's convention: ``<logdir>/<experiment>/<run>``
    is a run directory holding a single ``events.out.tfevents.*`` file,
    so ``tensorboard --logdir <logdir>`` shows experiments/runs as
    nested groups. Metrics flush on every call — a killed training run
    (the failure-detection story) loses at most the current event, and
    the file is readable DURING the run, which is the point of choosing
    TensorBoard over a post-hoc store.
    """

    def __init__(
        self,
        tracking_uri: str,
        experiment: str,
        *,
        run_name: str | None = None,
    ) -> None:
        self._root = resolve_logdir(tracking_uri)
        self._experiment = experiment
        self._run_name = run_name
        self._fh: Any | None = None

    # ------------------------------------------------------------ runs
    def start_run(self, run_id: str, run_name: str | None = None) -> None:
        if self._fh is not None:
            raise RuntimeError("start_run called twice on this tracker")
        run_dir = self._root / self._experiment / (run_name or self._run_name or run_id)
        run_dir.mkdir(parents=True, exist_ok=True)
        fname = (
            f"events.out.tfevents.{int(time.time())}."
            f"{socket.gethostname()}.{os.getpid()}.v2"
        )
        self._fh = open(run_dir / fname, "wb")
        # The version record must be the file's first event.
        self._write(_event(time.time(), None, _pb_string(3, "brain.Event:2")))

    def _write(self, event: bytes) -> None:
        if self._fh is None:
            raise RuntimeError("tracker is not started (or already ended)")
        self._fh.write(_tfrecord(event))
        self._fh.flush()

    # ------------------------------------------------------------ logging
    def log_params(self, params: dict[str, Any]) -> None:
        from .mlflow import _flatten_params

        flat = _flatten_params(params)
        rows = "\n".join(
            "| {} | {} |".format(
                k, str(flat[k]).replace("|", "\\|").replace("\n", " ")
            )
            for k in sorted(flat, key=str)
        )
        table = "| param | value |\n|---|---|\n" + rows
        self._write(
            _event(time.time(), 0, _pb_bytes(5, _text_value("params/config", table)))
        )

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None:
        if not metrics:
            return
        body = b"".join(
            _scalar_value(tag, value) for tag, value in metrics.items()
        )
        self._write(_event(time.time(), step, _pb_bytes(5, body)))

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None:
        # TensorBoard has no artifact store; record the path as text so
        # the run page links back to it (parity with how the reference
        # surfaces artifacts by reference, not by copy).
        self._write(
            _event(
                time.time(),
                0,
                _pb_bytes(
                    5,
                    _text_value(
                        "artifacts/" + (artifact_path or Path(local_path).name),
                        str(local_path),
                    ),
                ),
            )
        )

    def end_run(self, status: str = "FINISHED") -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


__all__ = ["TensorBoardTracker", "resolve_logdir"]
