"""Experiment tracking: Tracker protocol, MLflow/SQLite/Null backends."""

from __future__ import annotations

from typing import Any

from .base import NullTracker, Tracker
from .mlflow import MLflowTracker
from .sqlite import SqliteTracker


def _mlflow_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("mlflow") is not None


def build_tracker(mlflow_cfg: Any, run_id: str) -> Tracker:
    """Backend selection for the main process (``mlflow.backend``):

    * ``mlflow`` — the MLflow client (raises a clear error at start_run
      when the extra is missing; reference behavior).
    * ``native`` — the stdlib SQLite store (tracking/sqlite.py).
    * ``auto`` (default) — MLflow when importable, else the native store
      pointed at the same tracking URI, so tracking works out of the box
      on hosts without the extra (air-gapped TPU images included). The
      two backends share the URI convention but NOT an on-disk schema —
      a given DB file belongs to whichever backend created it.
    """
    backend = getattr(mlflow_cfg, "backend", "auto")
    run_name = mlflow_cfg.run_name or run_id
    if backend == "mlflow" or (backend == "auto" and _mlflow_available()):
        return MLflowTracker(
            mlflow_cfg.tracking_uri, mlflow_cfg.experiment, run_name=run_name
        )
    if backend == "auto":
        from ..utils.logging import get_logger

        get_logger().info(
            "mlflow not installed; tracking with the native SQLite backend "
            "at %s (mlflow.backend: native silences this)",
            mlflow_cfg.tracking_uri,
        )
    return SqliteTracker(
        mlflow_cfg.tracking_uri, mlflow_cfg.experiment, run_name=run_name
    )


__all__ = [
    "MLflowTracker",
    "NullTracker",
    "SqliteTracker",
    "Tracker",
    "build_tracker",
]
