"""Experiment tracking: Tracker protocol, MLflow/SQLite/Null backends."""

from __future__ import annotations

from typing import Any

from .base import NullTracker, Tracker
from .mlflow import MLflowTracker
from .sqlite import SqliteTracker
from .tensorboard import TensorBoardTracker


def _mlflow_available() -> bool:
    import importlib.util

    return importlib.util.find_spec("mlflow") is not None


def _reject_native_owned_db(tracking_uri: str) -> None:
    """The reverse of SqliteTracker's foreign-schema sniff.

    An image that GAINS the mlflow extra flips ``backend: auto`` from the
    native store to MLflow at the same tracking URI (the k8s configmap
    shares ``sqlite:////mlflow/mlflow.db``). mlflow's SqlAlchemy store
    would then initialize against a file whose runs/params/metrics/tags
    tables have the native backend's columns — dying in an opaque
    alembic/OperationalError (and possibly writing migration state into
    the native file). Sniff the native marker columns up front and name
    the fix instead. Only sqlite: URIs can collide; server URIs pass.
    """
    if not tracking_uri.startswith("sqlite:"):
        return
    from .sqlite import resolve_db_path

    db_path = resolve_db_path(tracking_uri)
    if not db_path.exists():
        return
    import sqlite3

    try:
        with sqlite3.connect(db_path) as conn:
            cols = {row[1] for row in conn.execute("PRAGMA table_info(runs)")}
    except sqlite3.Error:
        return  # unreadable/odd file: let mlflow produce its own error
    if cols and {"run_id", "experiment"} <= cols:
        raise RuntimeError(
            f"tracking DB {str(db_path)!r} was created by the native SQLite "
            "backend; the mlflow backend cannot share it. Point "
            "mlflow.tracking_uri at a separate file, or set "
            "mlflow.backend: native to keep using this DB."
        )


def build_tracker(mlflow_cfg: Any, run_id: str) -> Tracker:
    """Backend selection for the main process (``mlflow.backend``):

    * ``mlflow`` — the MLflow client (raises a clear error at start_run
      when the extra is missing; reference behavior).
    * ``native`` — the stdlib SQLite store (tracking/sqlite.py).
    * ``tensorboard`` — native event-file writer
      (tracking/tensorboard.py); ``tracking_uri`` is the logdir root.
    * ``auto`` (default) — MLflow when importable, else the native store
      pointed at the same tracking URI, so tracking works out of the box
      on hosts without the extra (air-gapped TPU images included). The
      two backends share the URI convention but NOT an on-disk schema —
      a given DB file belongs to whichever backend created it.
    """
    backend = getattr(mlflow_cfg, "backend", "auto")
    run_name = mlflow_cfg.run_name or run_id
    if backend == "tensorboard":
        return TensorBoardTracker(
            mlflow_cfg.tracking_uri, mlflow_cfg.experiment, run_name=run_name
        )
    if backend == "mlflow" or (backend == "auto" and _mlflow_available()):
        _reject_native_owned_db(mlflow_cfg.tracking_uri)
        return MLflowTracker(
            mlflow_cfg.tracking_uri, mlflow_cfg.experiment, run_name=run_name
        )
    if backend == "auto":
        from ..utils.logging import get_logger

        get_logger().info(
            "mlflow not installed; tracking with the native SQLite backend "
            "at %s (mlflow.backend: native silences this)",
            mlflow_cfg.tracking_uri,
        )
    return SqliteTracker(
        mlflow_cfg.tracking_uri, mlflow_cfg.experiment, run_name=run_name
    )


__all__ = [
    "MLflowTracker",
    "NullTracker",
    "SqliteTracker",
    "TensorBoardTracker",
    "Tracker",
    "build_tracker",
]
