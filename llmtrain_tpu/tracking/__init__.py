"""Experiment tracking: Tracker protocol, MLflow and Null implementations."""

from .base import NullTracker, Tracker
from .mlflow import MLflowTracker

__all__ = ["MLflowTracker", "NullTracker", "Tracker"]
