"""Native SQLite tracking backend — a real store with zero dependencies.

The reference ships MLflow-backed tracking and exercises it end-to-end
against a SQLite tracking URI (reference tests/test_cli.py:628-704; the
k8s configmap wires ``sqlite:////mlflow/mlflow.db``). mlflow itself is an
optional heavyweight extra; on hosts without it this backend persists the
same information (runs, params, metrics with steps, tags, artifacts) to a
plain SQLite file with the stdlib ``sqlite3`` module, so the tracking
round trip is testable — and USED — everywhere, including air-gapped TPU
images. ``mlflow.backend: auto`` (config/schemas.py) picks mlflow when
importable and this store otherwise; ``native`` forces it.

Semantics mirror the MLflow tracker (tracking/mlflow.py):

* ``start_run`` joins an existing run carrying the same framework run id
  (``--auto-resume`` relaunches CONTINUE the run instead of opening a
  second one), else inserts a fresh row.
* Only rank 0 ever holds a real tracker (cli.py), so there is a single
  writer; WAL mode keeps concurrent readers (dashboards, the query
  helpers below) safe.
* Params are flattened to dot keys exactly like the MLflow tracker, so a
  run recorded by either backend reads the same.

The module-level ``read_runs``/``read_params``/``read_metrics`` helpers
are the query surface the round-trip tests (and users) consume.
"""

from __future__ import annotations

import sqlite3
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any

from .mlflow import _flatten_params

_SCHEMA = """
CREATE TABLE IF NOT EXISTS runs (
    run_uuid     TEXT PRIMARY KEY,
    run_id       TEXT NOT NULL,
    experiment   TEXT NOT NULL,
    run_name     TEXT,
    status       TEXT NOT NULL,
    start_time   REAL NOT NULL,
    end_time     REAL,
    UNIQUE (run_id, experiment)
);
CREATE TABLE IF NOT EXISTS params (
    run_uuid TEXT NOT NULL REFERENCES runs(run_uuid),
    key      TEXT NOT NULL,
    value    TEXT NOT NULL,
    PRIMARY KEY (run_uuid, key)
);
CREATE TABLE IF NOT EXISTS metrics (
    run_uuid  TEXT NOT NULL REFERENCES runs(run_uuid),
    key       TEXT NOT NULL,
    -- Nullable: Python's sqlite3 binds float('nan') as NULL, and a
    -- diverged run logging loss=nan must not crash training. Reads map
    -- NULL back to nan (read_metrics).
    value     REAL,
    step      INTEGER,
    timestamp REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_metrics_run_key ON metrics (run_uuid, key, step);
CREATE TABLE IF NOT EXISTS tags (
    run_uuid TEXT NOT NULL REFERENCES runs(run_uuid),
    key      TEXT NOT NULL,
    value    TEXT NOT NULL,
    PRIMARY KEY (run_uuid, key)
);
CREATE TABLE IF NOT EXISTS artifacts (
    run_uuid      TEXT NOT NULL REFERENCES runs(run_uuid),
    local_path    TEXT NOT NULL,
    artifact_path TEXT
);
"""


def resolve_db_path(tracking_uri: str) -> Path:
    """Map a tracking URI to the SQLite file this backend uses.

    ``sqlite:///relative.db`` / ``sqlite:////abs/path.db`` follow
    MLflow's SQLite URI convention (three slashes relative, four
    absolute — so the k8s configmap value resolves identically under
    either backend); ``file:<dir>`` and plain paths get ``llmtrain.db``
    inside the directory.
    """
    if tracking_uri.startswith("sqlite:"):
        p = tracking_uri[len("sqlite:") :].lstrip("/")
        return Path("/" + p) if tracking_uri.startswith("sqlite:////") else Path(p)
    if tracking_uri.startswith("file:"):
        return Path(tracking_uri[len("file:") :]) / "llmtrain.db"
    return Path(tracking_uri) / "llmtrain.db"


class SqliteTracker:
    """Tracker protocol implementation over a local SQLite file."""

    def __init__(
        self,
        tracking_uri: str,
        experiment: str,
        *,
        run_name: str | None = None,
    ) -> None:
        self._db_path = resolve_db_path(tracking_uri)
        self._experiment = experiment
        self._run_name = run_name
        self._conn: sqlite3.Connection | None = None
        self._run_uuid: str | None = None

    # ------------------------------------------------------------- plumbing
    def _connect(self) -> sqlite3.Connection:
        if self._conn is None:
            self._db_path.parent.mkdir(parents=True, exist_ok=True)
            conn = sqlite3.connect(str(self._db_path))
            # Sniff BEFORE the WAL pragma: journal_mode=WAL is a persistent
            # on-disk change (+ -wal/-shm sidecars), and a foreign file must
            # be rejected untouched.
            self._reject_foreign_schema(conn)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.executescript(_SCHEMA)
            self._migrate_nullable_metric_values(conn)
            conn.commit()
            self._conn = conn
        return self._conn

    @staticmethod
    def _reject_foreign_schema(conn: sqlite3.Connection) -> None:
        """Refuse a DB whose ``runs`` table belongs to another product.

        MLflow's own SQLite store also has runs/params/metrics/tags
        tables (with ``experiment_id`` instead of this backend's
        ``run_id``/``experiment`` columns). With ``mlflow.backend: auto``
        and a shared tracking file (the k8s configmap's
        ``sqlite:////mlflow/mlflow.db``), an image that gains or loses
        the mlflow extra would silently point this backend at an
        mlflow-owned file: ``CREATE TABLE IF NOT EXISTS`` accepts the
        foreign tables and the first INSERT dies mid-training with an
        opaque OperationalError. Sniff up front and fail with a message
        that names the fix instead.
        """
        cols = {row[1] for row in conn.execute("PRAGMA table_info(runs)")}
        if cols and not {"run_id", "experiment"} <= cols:
            path = conn.execute("PRAGMA database_list").fetchone()[2]
            conn.close()
            raise RuntimeError(
                f"tracking DB {path!r} has a 'runs' table from a different "
                "product (likely MLflow's own SQLite store; its columns are "
                f"{sorted(cols)}). The native backend cannot share a file "
                "with the mlflow backend — point mlflow.tracking_uri at a "
                "separate file, or set mlflow.backend explicitly so both "
                "relaunches resolve to the backend that created this DB."
            )

    @staticmethod
    def _migrate_nullable_metric_values(conn: sqlite3.Connection) -> None:
        """v1 DBs declared metrics.value NOT NULL; CREATE IF NOT EXISTS
        can't relax that, and a NaN metric (bound as NULL) would still
        crash a resumed run against such a file. Rebuild the table once."""
        notnull = {
            row[1]: bool(row[3]) for row in conn.execute("PRAGMA table_info(metrics)")
        }
        if not notnull.get("value"):
            return
        conn.executescript(
            "DROP INDEX IF EXISTS idx_metrics_run_key;"
            "ALTER TABLE metrics RENAME TO _metrics_v1;"
        )
        conn.executescript(_SCHEMA)  # recreates metrics (nullable) + index
        conn.execute(
            "INSERT INTO metrics (run_uuid, key, value, step, timestamp) "
            "SELECT run_uuid, key, value, step, timestamp FROM _metrics_v1"
        )
        conn.execute("DROP TABLE _metrics_v1")

    # ------------------------------------------------------------- protocol
    def start_run(self, run_id: str, run_name: str | None = None) -> None:
        conn = self._connect()
        row = conn.execute(
            "SELECT run_uuid FROM runs WHERE run_id = ? AND experiment = ?",
            (run_id, self._experiment),
        ).fetchone()
        if row is not None:
            # Crash-restart continuity: an --auto-resume relaunch with the
            # same stable run id reattaches (mlflow.py join semantics).
            self._run_uuid = row[0]
            conn.execute(
                "UPDATE runs SET status = 'RUNNING', end_time = NULL "
                "WHERE run_uuid = ?",
                (self._run_uuid,),
            )
        else:
            import uuid

            self._run_uuid = uuid.uuid4().hex
            conn.execute(
                "INSERT INTO runs (run_uuid, run_id, experiment, run_name, "
                "status, start_time) VALUES (?, ?, ?, ?, 'RUNNING', ?)",
                (
                    self._run_uuid,
                    run_id,
                    self._experiment,
                    run_name or self._run_name or run_id,
                    time.time(),
                ),
            )
            conn.execute(
                "INSERT OR REPLACE INTO tags (run_uuid, key, value) "
                "VALUES (?, 'llmtrain.run_id', ?)",
                (self._run_uuid, run_id),
            )
        conn.commit()

    def log_params(self, params: dict[str, Any]) -> None:
        if self._run_uuid is None:
            return
        conn = self._connect()
        conn.executemany(
            "INSERT OR REPLACE INTO params (run_uuid, key, value) VALUES (?, ?, ?)",
            [
                (self._run_uuid, k, str(v))
                for k, v in _flatten_params(params).items()
            ],
        )
        conn.commit()

    def log_metrics(self, metrics: dict[str, float], step: int | None = None) -> None:
        if self._run_uuid is None:
            return
        conn = self._connect()
        now = time.time()
        # NaN binds as NULL (nullable column; read_metrics maps it back) —
        # a diverged run logging loss=nan must log, not crash training.
        conn.executemany(
            "INSERT INTO metrics (run_uuid, key, value, step, timestamp) "
            "VALUES (?, ?, ?, ?, ?)",
            [(self._run_uuid, k, float(v), step, now) for k, v in metrics.items()],
        )
        conn.commit()

    def log_artifact(self, local_path: str, artifact_path: str | None = None) -> None:
        if self._run_uuid is None:
            return
        conn = self._connect()
        conn.execute(
            "INSERT INTO artifacts (run_uuid, local_path, artifact_path) "
            "VALUES (?, ?, ?)",
            (self._run_uuid, local_path, artifact_path),
        )
        conn.commit()

    def end_run(self, status: str = "FINISHED") -> None:
        if self._run_uuid is None:
            return
        conn = self._connect()
        conn.execute(
            "UPDATE runs SET status = ?, end_time = ? WHERE run_uuid = ?",
            (status, time.time(), self._run_uuid),
        )
        conn.commit()
        conn.close()
        self._conn = None
        self._run_uuid = None


# ------------------------------------------------------------------ queries
@contextmanager
def _reader(db_path: str | Path):
    # sqlite3's own context manager only commits/rolls back — it never
    # closes, which would leak a connection (and its WAL read lock) per
    # query in a polling dashboard.
    conn = sqlite3.connect(str(db_path))
    conn.row_factory = sqlite3.Row
    try:
        yield conn
    finally:
        conn.close()


def read_runs(db_path: str | Path, experiment: str | None = None) -> list[dict]:
    """All runs (optionally one experiment's), newest first."""
    with _reader(db_path) as conn:
        sql = "SELECT * FROM runs"
        args: tuple = ()
        if experiment is not None:
            sql += " WHERE experiment = ?"
            args = (experiment,)
        sql += " ORDER BY start_time DESC"
        return [dict(r) for r in conn.execute(sql, args)]


def read_params(
    db_path: str | Path, run_id: str, experiment: str | None = None
) -> dict[str, str]:
    """One run's params. Pass ``experiment`` when the DB may hold the
    same run id under several experiments (uniqueness is per pair) —
    without it, params from every matching run merge."""
    with _reader(db_path) as conn:
        sql = (
            "SELECT p.key, p.value FROM params p "
            "JOIN runs r ON r.run_uuid = p.run_uuid WHERE r.run_id = ?"
        )
        args: tuple = (run_id,)
        if experiment is not None:
            sql += " AND r.experiment = ?"
            args = (run_id, experiment)
        return {r["key"]: r["value"] for r in conn.execute(sql, args)}


def read_metrics(
    db_path: str | Path,
    run_id: str,
    key: str | None = None,
    experiment: str | None = None,
) -> list[dict]:
    """Metric rows (key, value, step, timestamp) in insertion order.

    NULL values read back as nan (NaN binds as NULL on insert). Pass
    ``experiment`` to disambiguate a run id shared across experiments.
    """
    with _reader(db_path) as conn:
        sql = (
            "SELECT m.key, m.value, m.step, m.timestamp FROM metrics m "
            "JOIN runs r ON r.run_uuid = m.run_uuid WHERE r.run_id = ?"
        )
        args: list = [run_id]
        if key is not None:
            sql += " AND m.key = ?"
            args.append(key)
        if experiment is not None:
            sql += " AND r.experiment = ?"
            args.append(experiment)
        sql += " ORDER BY m.rowid"
        rows = [dict(r) for r in conn.execute(sql, tuple(args))]
    for r in rows:
        if r["value"] is None:
            r["value"] = float("nan")
    return rows


__all__ = [
    "SqliteTracker",
    "resolve_db_path",
    "read_runs",
    "read_params",
    "read_metrics",
]
