"""Autoregressive sampling from a trained model.

Parity target: the reference exposes generation only inside
``notebooks/trained_vs_random_completion.ipynb`` (``generate_text`` /
``top_next_tokens`` cells) — an eager python loop calling the model per
token. Here decoding is a first-class module and ONE jit-compiled program.

Two paths, chosen automatically:

* **KV-cache decode** (models exposing ``for_decoding()``, e.g. GPT, with
  the whole output fitting in ``block_size``): prefill writes the prompt's
  keys/values into per-layer cache variables, then a ``lax.scan`` appends
  one token per step — O(T) attention per step instead of O(T²) re-forward.
* **Sliding-window re-forward** (fallback, any model): a ``lax.fori_loop``
  over a fixed-size token buffer with a ``dynamic_slice`` context window.
  Handles outputs longer than ``block_size`` and cache-less models.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def filter_logits(
    scaled: jax.Array,  # (..., V), already temperature-scaled
    *,
    top_k: int | None,
    top_p: float | None,
) -> jax.Array:
    """top-k / nucleus masking (-inf outside the kept set).

    THE single filtering implementation: `_sample_next` below and
    speculative decoding (speculative.py) both use it — the speculative
    exactness contract requires the target's plain sampling and both
    models' speculative distributions to be filtered identically.
    """
    if top_k is not None:
        k = min(top_k, scaled.shape[-1])
        kth = jax.lax.top_k(scaled, k)[0][..., -1, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None and top_p < 1.0:
        # Nucleus: keep the smallest prefix of the descending-prob order
        # whose EXCLUSIVE cumulative mass is < top_p (always keeps the
        # argmax). Composes after top-k (already -inf-masked there).
        sorted_logits = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive = jnp.cumsum(probs, axis=-1) - probs
        keep = exclusive < top_p
        thr = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        scaled = jnp.where(scaled < thr, -jnp.inf, scaled)
    return scaled


def _chosen_logprob(logits: jax.Array, tok: jax.Array) -> jax.Array:
    """The MODEL's logprob of the emitted token (raw log-softmax —
    temperature/top-k/top-p shape the CHOICE, not the report; the
    OpenAI-style serving convention). Shared by both decode paths."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(lp, tok[:, None].astype(jnp.int32), axis=-1)[:, 0]


def _sample_next(
    next_logits: jax.Array,  # (B, V) float32
    rng: jax.Array,
    i: jax.Array | int,
    *,
    temperature: float,
    top_k: int | None,
    top_p: float | None = None,
) -> jax.Array:
    """One sampling decision, shared by both decode paths."""
    if temperature == 0.0:
        return jnp.argmax(next_logits, axis=-1)
    scaled = filter_logits(next_logits / temperature, top_k=top_k, top_p=top_p)
    return jax.random.categorical(jax.random.fold_in(rng, i), scaled, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "max_new_tokens", "temperature", "top_k", "top_p",
        "eos_token_id", "with_logprobs",
    ),
)
def _generate_cached_jit(
    model: Any,  # decode-mode module (cache variables enabled)
    params: Any,
    cache: Any,  # zero-initialized cache pytree
    prompt: jax.Array,  # (B, Tp) rectangular
    rng: jax.Array,
    *,
    max_new_tokens: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    eos_token_id: int | None,
    with_logprobs: bool = False,
) -> tuple[jax.Array, jax.Array]:
    def apply(cache, tokens):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens,
            deterministic=True,
            mutable=["cache"],
        )
        return mutated["cache"], logits.astype(jnp.float32)

    # Prefill: one forward over the whole prompt fills every layer's cache.
    cache, logits = apply(cache, prompt)
    tok0 = _sample_next(
        logits[:, -1], rng, 0, temperature=temperature, top_k=top_k, top_p=top_p
    ).astype(prompt.dtype)
    # with_logprobs is STATIC: the default path keeps its pre-logprob
    # cost (greedy decode pays only the argmax, no O(V) log-softmax).
    lp0 = _chosen_logprob(logits[:, -1], tok0) if with_logprobs else jnp.zeros(
        (prompt.shape[0],), jnp.float32
    )
    done0 = jnp.zeros((prompt.shape[0],), jnp.bool_)
    if eos_token_id is not None:
        done0 = tok0 == eos_token_id

    def step(carry, i):
        cache, tok, done = carry
        done_in = done  # rows already ended BEFORE this step
        cache, logits = apply(cache, tok[:, None])
        nxt = _sample_next(
            logits[:, 0], rng, i, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(tok.dtype)
        if eos_token_id is not None:
            nxt = jnp.where(done, jnp.asarray(eos_token_id, tok.dtype), nxt)
            done = done | (nxt == eos_token_id)
        lp = (
            _chosen_logprob(logits[:, 0], nxt)
            if with_logprobs
            else jnp.zeros((nxt.shape[0],), jnp.float32)
        )
        if with_logprobs and eos_token_id is not None:
            # Post-eos padding is not an emission: report 0.0 so
            # sum(logprobs) scores exactly the real sequence (the FIRST
            # eos keeps its true logprob).
            lp = jnp.where(done_in, 0.0, lp)
        return (cache, nxt, done), (nxt, lp)

    _, (rest, rest_lps) = jax.lax.scan(
        step, (cache, tok0, done0), jnp.arange(1, max_new_tokens)
    )  # rest: (max_new_tokens-1, B)
    new_tokens = jnp.concatenate([tok0[:, None], rest.T], axis=1)
    logprobs = (
        jnp.concatenate([lp0[:, None], rest_lps.T], axis=1)
        if with_logprobs
        else jnp.zeros((prompt.shape[0], 0), jnp.float32)
    )
    return jnp.concatenate([prompt, new_tokens], axis=1), logprobs


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "max_new_tokens", "window_len", "temperature", "top_k",
        "top_p", "with_logprobs",
    ),
)
def _generate_jit(
    model: Any,
    params: Any,
    buffer: jax.Array,  # (B, L) prompt left-aligned, zero-padded
    prompt_len: jax.Array,  # (B,) int32
    rng: jax.Array,
    *,
    max_new_tokens: int,
    window_len: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    with_logprobs: bool = False,
) -> tuple[jax.Array, jax.Array]:
    total_len = buffer.shape[1]

    def step(i, carry):
        buf, lps, done = carry
        done_in = done  # rows already ended BEFORE this step
        cur = prompt_len + i  # (B,) next position to fill

        # Fixed-size context window ending at the longest current position.
        # Rows with shorter prompts read their logits at their own last
        # token's index inside the window.
        hi = jnp.max(cur)
        start = jnp.clip(hi - window_len, 0, total_len - window_len)
        window = jax.lax.dynamic_slice(
            buf, (0, start), (buf.shape[0], window_len)
        )
        mask = (start + jnp.arange(window_len))[None, :] < cur[:, None]
        logits = model.apply(
            {"params": params},
            window,
            mask.astype(jnp.int32),
            deterministic=True,
        )  # (B, W, V)
        last_idx = jnp.clip(cur - 1 - start, 0, window_len - 1)
        next_logits = jnp.take_along_axis(
            logits, last_idx[:, None, None], axis=1
        )[:, 0, :].astype(jnp.float32)

        next_tok = _sample_next(
            next_logits, rng, i, temperature=temperature, top_k=top_k, top_p=top_p
        ).astype(buf.dtype)

        if eos_token_id is not None:
            next_tok = jnp.where(done, jnp.asarray(eos_token_id, buf.dtype), next_tok)
            done = done | (next_tok == eos_token_id)

        buf = jax.vmap(
            lambda row, pos, tok: jax.lax.dynamic_update_slice(row, tok[None], (pos,))
        )(buf, cur, next_tok)
        if with_logprobs:
            chosen = _chosen_logprob(next_logits, next_tok)
            if eos_token_id is not None:
                # done_in (pre-update) marks post-eos padding — see the
                # cached path: report 0.0 there.
                chosen = jnp.where(done_in, 0.0, chosen)
            lps = jax.lax.dynamic_update_slice(lps, chosen[:, None], (0, i))
        return buf, lps, done

    done0 = jnp.zeros((buffer.shape[0],), jnp.bool_)
    lps0 = jnp.zeros(
        (buffer.shape[0], max_new_tokens if with_logprobs else 0), jnp.float32
    )
    buffer, logprobs, _ = jax.lax.fori_loop(
        0, max_new_tokens, step, (buffer, lps0, done0)
    )
    return buffer, logprobs


def generate(
    model: Any,
    params: Any,
    prompt_ids: np.ndarray | jax.Array,  # (B, Tp) or (Tp,)
    *,
    max_new_tokens: int,
    rng: jax.Array | None = None,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    use_cache: bool | None = None,
    return_logprobs: bool = False,
) -> np.ndarray | tuple[np.ndarray, np.ndarray]:
    """Sample ``max_new_tokens`` continuations; returns (B, Tp+max_new_tokens).

    ``temperature=0`` decodes greedily; otherwise categorical sampling with
    optional top-k and/or top-p (nucleus) filtering — top-p keeps the
    smallest set of tokens whose probability mass reaches ``top_p``.
    ``use_cache=None`` auto-selects KV-cache decode
    when the model supports it (``for_decoding()``) and the whole output fits
    in ``block_size``; ``False`` forces the sliding-window re-forward path
    (which also handles outputs longer than ``block_size``).
    ``return_logprobs=True`` also returns the MODEL's log-probability of
    each emitted token (raw log-softmax, (B, max_new_tokens) f32 —
    temperature/top-k/top-p shape the choice, not the report).
    """
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0; got {max_new_tokens}")
    ids = np.asarray(prompt_ids, dtype=np.int32)
    if ids.ndim == 1:
        ids = ids[None, :]
    b, tp = ids.shape
    if tp == 0:
        raise ValueError("prompt must contain at least one token")
    vocab_size = getattr(model, "vocab_size", None)
    if vocab_size is not None and (ids.min() < 0 or ids.max() >= vocab_size):
        raise ValueError(
            f"prompt token ids must be in [0, {vocab_size}); "
            f"got range [{ids.min()}, {ids.max()}]"
        )
    if top_k is not None and top_k <= 0:
        top_k = None  # CLI convention: 0 disables top-k filtering
    if top_p is not None:
        if top_p <= 0.0 or top_p >= 1.0:
            # CLI convention mirrors --top-k: out-of-band values (0 and 1
            # included) disable the filter rather than erroring.
            top_p = None
    total = tp + max_new_tokens

    block_size = int(getattr(model, "block_size", total))
    window_len = min(block_size, total)
    if rng is None:
        rng = jax.random.key(0)

    cache_capable = hasattr(model, "for_decoding") and total <= block_size
    if use_cache is None:
        use_cache = cache_capable
    elif use_cache and not cache_capable:
        if not hasattr(model, "for_decoding"):
            raise ValueError(
                "use_cache=True needs a model exposing for_decoding(); "
                f"{type(model).__name__} does not"
            )
        raise ValueError(
            "use_cache=True needs prompt+max_new_tokens <= block_size "
            f"(got {total} > {block_size})"
        )

    if max_new_tokens == 0:
        empty_lp = np.zeros((b, 0), np.float32)
        return (ids.copy(), empty_lp) if return_logprobs else ids.copy()

    if use_cache:
        decode_model = model.for_decoding(cache_len=total)
        # Zero cache pytree from an eval_shape trace — no param init work.
        var_shapes = jax.eval_shape(
            lambda: decode_model.init(
                jax.random.key(0), jnp.zeros((b, 1), jnp.int32), deterministic=True
            )
        )
        cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), var_shapes["cache"]
        )
        out, lps = _generate_cached_jit(
            decode_model,
            params,
            cache,
            jnp.asarray(ids),
            rng,
            max_new_tokens=max_new_tokens,
            temperature=float(temperature),
            top_k=top_k,
            top_p=top_p,
            eos_token_id=eos_token_id,
            with_logprobs=return_logprobs,
        )
        tokens = np.asarray(jax.device_get(out))
        if return_logprobs:
            return tokens, np.asarray(jax.device_get(lps))
        return tokens

    buffer = np.zeros((b, total), dtype=np.int32)
    buffer[:, :tp] = ids
    prompt_len = jnp.full((b,), tp, jnp.int32)

    out, lps = _generate_jit(
        model,
        params,
        jnp.asarray(buffer),
        prompt_len,
        rng,
        max_new_tokens=max_new_tokens,
        window_len=window_len,
        temperature=float(temperature),
        top_k=top_k,
        top_p=top_p,
        eos_token_id=eos_token_id,
        with_logprobs=return_logprobs,
    )
    tokens = np.asarray(jax.device_get(out))
    if return_logprobs:
        return tokens, np.asarray(jax.device_get(lps))
    return tokens


def generate_text(
    model: Any,
    params: Any,
    tokenizer: Any,
    prompt: str,
    *,
    max_new_tokens: int = 48,
    temperature: float = 0.8,
    top_k: int | None = 40,
    top_p: float | None = None,
    seed: int = 1234,
) -> str:
    """Tokenize → sample → decode (the notebook ``generate_text`` contract)."""
    ids = np.asarray(tokenizer.encode(prompt), dtype=np.int32)
    out = generate(
        model,
        params,
        ids,
        max_new_tokens=max_new_tokens,
        rng=jax.random.key(seed),
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
    )
    return tokenizer.decode([int(t) for t in out[0]])


def top_next_tokens(
    model: Any,
    params: Any,
    tokenizer: Any,
    text: str,
    *,
    k: int = 10,
) -> list[tuple[str, float]]:
    """The k most likely next tokens with probabilities (notebook parity)."""
    ids = np.asarray(tokenizer.encode(text), dtype=np.int32)
    block_size = int(getattr(model, "block_size", len(ids)))
    window = jnp.asarray(ids[-block_size:][None, :])
    logits = model.apply({"params": params}, window, deterministic=True)
    probs = jax.nn.softmax(logits[0, -1].astype(jnp.float32))
    k = min(k, probs.shape[-1])
    top_p, top_i = jax.lax.top_k(probs, k)
    return [
        (tokenizer.decode([int(i)]), float(p))
        for i, p in zip(np.asarray(top_i), np.asarray(top_p))
    ]


__all__ = ["generate", "generate_text", "top_next_tokens"]
