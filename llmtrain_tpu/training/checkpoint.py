"""Checkpoint save/load/prune/resume-resolution.

Parity target: reference ``src/llmtrain/training/checkpoint.py`` —
``step_{step:06d}`` file naming (:70-71), keep-last-k pruning (default 3,
override via ``trainer.extra.keep_last_k``), payload key validation (:88-92),
``latest_checkpoint`` by parsed step number (:96-103) — and the resume-spec
resolution from reference trainer.py:215-241 (file | dir→latest |
run-id→root/run_id/checkpoints→latest).

TPU design: the payload is a msgpack file of host numpy arrays via
``flax.serialization`` — step, params, opt_state, and the resolved config
(for the mismatch warning, reference trainer.py:315-318). There are NO RNG
states in the payload: dropout keys and data order are pure functions of
(seed, step) in this framework, so restoring ``step`` alone reproduces the
exact stream — this is what makes resume exact under any process count,
where the reference's skip-ahead replay was single-process-only
(reference trainer.py:336-347).

Atomic commit protocol (docs/robustness.md "Crash consistency"): a
checkpoint step is a SET of files (payload + sha-256 sidecar, historically
growing), and a kill can land between any two of their writes. Every save
therefore stages its files (tmp write + fsync + rename) and then publishes
one ``step_N.manifest.json`` — file list with sizes and sha-256 digests,
plus the saving run's mesh/topology and sampler progress — via atomic
rename. The manifest IS the commit: selection (``latest_valid_checkpoint``,
and through it ``resolve_resume_path``) only ever returns manifested steps
whose listed files verify, so a partially committed step is invisible no
matter where the kill landed. ``_prune`` garbage-collects orphaned stages
(torn tmp files, non-verifying unmanifested payloads) and ADOPTS complete
unmanifested payloads by synthesizing their manifest — which is also the
backward-compat path for pre-manifest checkpoint dirs.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from concurrent.futures import TimeoutError as FuturesTimeoutError
from pathlib import Path
from typing import Any, Callable

import jax
import numpy as np
import yaml
from flax import serialization
from flax.linen import meta as nn_meta

CHECKPOINT_VERSION = 1
MANIFEST_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d{6,})\.ckpt$")
_MANIFEST_RE = re.compile(r"^step_(\d{6,})\.manifest\.json$")
_REQUIRED_KEYS = {"checkpoint_version", "step", "params", "opt_state", "config_yaml"}


def sidecar_path(ckpt: Path) -> Path:
    """``step_NNNNNN.ckpt`` → its ``step_NNNNNN.ckpt.sha256`` sidecar."""
    return ckpt.with_name(ckpt.name + ".sha256")


def manifest_path(ckpt: Path) -> Path:
    """``step_NNNNNN.ckpt`` → its ``step_NNNNNN.manifest.json`` commit record."""
    return ckpt.with_name(ckpt.name[: -len(".ckpt")] + ".manifest.json")


def read_manifest(ckpt: Path) -> dict[str, Any] | None:
    """The parsed commit manifest next to ``ckpt``, or None when absent or
    unparseable (pre-manifest checkpoints; a torn manifest tmp never gets
    the final name, so a parse failure here means external damage)."""
    try:
        raw = manifest_path(Path(ckpt)).read_text(encoding="utf-8")
        data = json.loads(raw)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def _fsync_file(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: Path) -> None:
    """Durably record renames in the directory itself. Best-effort: some
    filesystems (and platforms) refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _read_sidecar_digest(ckpt: Path) -> str | None:
    """Hex digest recorded for ``ckpt``, or None when no sidecar exists.

    Sidecar format is ``sha256sum`` output (``<hex>  <name>``) so integrity
    is also checkable by hand: ``cd checkpoints && sha256sum -c *.sha256``.
    """
    side = sidecar_path(ckpt)
    try:
        first = side.read_text(encoding="utf-8").split()
    except OSError:
        return None
    return first[0].lower() if first else None


def owned_host_copy(x: Any) -> np.ndarray:
    """``np.asarray`` that always OWNS its bytes.

    On the CPU backend ``np.asarray`` of a jax.Array is a zero-copy VIEW
    of the device buffer — the aliasing trap behind both the async
    checkpoint-vs-donation race (see :func:`_to_host`) and the ZeRO
    host-offload round-trip (trainer._opt_state_to_host). One home for
    the copy-when-foreign rule so the two stay in sync."""
    arr = np.asarray(x)
    if arr.base is not None:
        arr = arr.copy()
    return arr


def host_fetch(x: Any) -> np.ndarray:
    """Owned host materialization of ONE leaf: multi-host sharded arrays
    (shards on other processes) gather via ``process_allgather`` — a
    collective, so every process must reach this together — and
    everything else takes the :func:`owned_host_copy` path."""
    if isinstance(x, jax.Array) and not (
        x.is_fully_addressable or x.is_fully_replicated
    ):
        from jax.experimental import multihost_utils

        return owned_host_copy(multihost_utils.process_allgather(x, tiled=True))
    return owned_host_copy(x)


def start_host_transfers(tree: Any) -> None:
    """Kick off every addressable leaf's device→host DMA so subsequent
    ``np.asarray`` materializations pipeline instead of serializing
    leaf-by-leaf (measured ~4x on a tunneled v5e — see :func:`_to_host`)."""
    for x in jax.tree.leaves(tree):
        if isinstance(x, jax.Array) and (
            x.is_fully_addressable or x.is_fully_replicated
        ):
            x.copy_to_host_async()


def _to_host(tree: Any) -> Any:
    """Unbox metadata and materialize every leaf as host numpy.

    Multi-host sharded leaves (FSDP/TP params whose shards live on other
    processes) are gathered with ``process_allgather`` — a collective, so
    EVERY process must call this; only the main process then writes (see
    Trainer.fit's save path).
    """
    unboxed = nn_meta.unbox(tree)

    # Phase 1: start every addressable leaf's device→host DMA up front so
    # the transfers pipeline instead of serializing leaf-by-leaf inside
    # np.asarray (measured ~4x on a tunneled v5e: 104s → 24s for the
    # 1.5 GB GPT-2-small train state).
    start_host_transfers(unboxed)
    # The snapshot must OWN its bytes (host_fetch/owned_host_copy): the
    # next train step DONATES the state buffers (donate_argnums=(0,)) and
    # XLA writes the new state into them in place — while the async
    # checkpoint writer may still be serializing a zero-copy view.
    # Result: a checkpoint whose step field says N but whose params are
    # from a later step (caught by the prefetch determinism suite, which
    # removes the host-assembly slack that usually hid the race).
    return jax.tree.map(host_fetch, unboxed)


def state_to_host(state: Any) -> dict[str, Any]:
    """Collective-safe host materialization of a TrainState's saved fields.

    One ``_to_host`` call over both subtrees so ALL leaves' DMAs start
    before any materialization blocks (two calls would serialize opt_state
    behind params — and Adam's opt_state is ~2x the params bytes).

    Gather-on-save is what keeps manifests topology-portable: ZeRO-sharded
    optimizer state (trainer.zero) arrives here as per-replica shards and
    leaves as FULL host arrays — ``np.asarray`` assembles locally-
    addressable shards, ``process_allgather`` covers multi-host ones — so
    a checkpoint restores onto any dp size and any zero on/off setting
    (tests/test_zero.py pins both round-trips).
    """
    host = _to_host({"params": state.params, "opt_state": state.opt_state})
    return {
        "step": int(state.step),
        "params": serialization.to_state_dict(host["params"]),
        "opt_state": serialization.to_state_dict(host["opt_state"]),
    }


class CheckpointError(Exception):
    """Raised for malformed or missing checkpoints."""


class CheckpointManager:
    def __init__(
        self,
        directory: str | Path,
        *,
        keep_last_k: int = 3,
        on_commit: Callable[[int, Path], None] | None = None,
    ) -> None:
        self._dir = Path(directory)
        self._keep_last_k = max(1, keep_last_k)
        self._pending: Any = None  # in-flight async write (Future)
        # Commit observer: called (step, manifest_path) right after the
        # manifest rename lands — from the WRITER thread on async saves, so
        # consumers must be thread-safe (the telemetry registry is). Drives
        # the llmtrain_checkpoint_commits_total counter.
        self.on_commit = on_commit
        # Verification results keyed by (path, size, mtime_ns): pruning and
        # rollback re-verify the same unchanged files every save; hashing a
        # multi-GB checkpoint repeatedly would be pure waste.
        self._verify_cache: dict[tuple[str, int, int], bool] = {}

    @property
    def directory(self) -> Path:
        return self._dir

    def save(self, step: int, state: Any, resolved_config: dict[str, Any]) -> Path:
        """Serialize (step, params, opt_state, config) to ``step_{step:06d}.ckpt``.

        Single-host convenience wrapper; multi-host callers run
        ``state_to_host`` on every process and pass the result to
        ``save_host`` on the main process only.
        """
        host_state = state_to_host(state)
        return self.save_host(step, host_state, resolved_config)

    def save_host(
        self,
        step: int,
        host_state: dict[str, Any],
        resolved_config: dict[str, Any],
        *,
        resilience: dict[str, Any] | None = None,
        manifest_extra: dict[str, Any] | None = None,
        inject_kill: bool = False,
    ) -> Path:
        """Stage + atomically commit one checkpoint step.

        Order of operations (each stage is tmp-write → fsync → rename):
        payload, then sidecar, then the ``step_N.manifest.json`` publish —
        the manifest rename IS the commit point. A kill anywhere before it
        leaves an uncommitted stage that selection never sees and the next
        save's :meth:`_prune` cleans up (or adopts, when the payload is in
        fact complete). ``manifest_extra`` (topology/sampler metadata from
        the trainer) rides in the manifest, not the payload, so resume can
        validate a topology change without deserializing gigabytes.

        ``inject_kill`` is the ``faults.kill_during_checkpoint`` hook: a
        REAL ``SIGKILL`` fired between the staged files and the manifest
        publish, i.e. inside the exact crash window the protocol exists to
        make survivable (resilience/chaos.py drives it).
        """
        self._dir.mkdir(parents=True, exist_ok=True)
        payload = {
            "checkpoint_version": CHECKPOINT_VERSION,
            "step": np.int64(step),
            "params": host_state["params"],
            "opt_state": host_state["opt_state"],
            "config_yaml": yaml.safe_dump(resolved_config, sort_keys=False),
        }
        if resilience:
            # Optional small scalar dict (guard skip counter, rollback
            # bookkeeping, spike-detector EWMA) — not in _REQUIRED_KEYS, so
            # checkpoints stay readable both ways across versions.
            payload["resilience"] = {k: np.asarray(v) for k, v in resilience.items()}
        target = self._dir / f"step_{step:06d}.ckpt"
        blob = serialization.msgpack_serialize(payload)
        digest = hashlib.sha256(blob).hexdigest()
        # Re-saving a step (rollback replay): withdraw the old step before
        # staging the new bytes — a crash mid-rewrite must leave the step
        # unselectable (previous commit restores), never pair stale files
        # with new ones. PAYLOAD FIRST: with the payload gone the step can
        # neither verify against its (momentarily surviving) manifest nor
        # be adopted by the orphan sweep as a pre-rollback snapshot with
        # stale data_offset/rollback bookkeeping — whereas manifest-first
        # would open exactly that window between the two unlinks. A
        # briefly-dangling manifest fails verification closed and is
        # garbage-collected by the next prune.
        target.unlink(missing_ok=True)
        sidecar_path(target).unlink(missing_ok=True)
        manifest_path(target).unlink(missing_ok=True)
        tmp = target.with_suffix(".ckpt.tmp")
        tmp.write_bytes(blob)
        _fsync_file(tmp)
        tmp.replace(target)
        side = sidecar_path(target)
        side_body = f"{digest}  {target.name}\n"
        side_tmp = side.with_name(side.name + ".tmp")
        side_tmp.write_text(side_body, encoding="utf-8")
        _fsync_file(side_tmp)
        side_tmp.replace(side)
        if inject_kill:
            from ..utils.logging import get_logger

            get_logger().warning(
                "fault injection: SIGKILL inside the checkpoint write at "
                "step %d (staged files present, manifest NOT published)",
                step,
            )
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        self._publish_manifest(
            target,
            [(target.name, len(blob), digest), _file_entry(side)],
            manifest_extra,
        )
        stat = target.stat()
        self._verify_cache[(str(target), stat.st_size, stat.st_mtime_ns)] = True
        # Seed the manifest-keyed cache too (verify_manifest keys on the
        # manifest path + payload stat): the first selection scan after a
        # save — e.g. the rollback restore-point search — must not re-read
        # and re-hash the multi-GB payload it just wrote.
        self._verify_cache[
            (str(manifest_path(target)), stat.st_size, stat.st_mtime_ns)
        ] = True
        if self.on_commit is not None:
            try:
                self.on_commit(step, manifest_path(target))
            except Exception:  # noqa: BLE001 — observer must not fail the save
                pass
        self._prune()
        return target

    def _publish_manifest(
        self,
        target: Path,
        files: list[tuple[str, int, str]],
        manifest_extra: dict[str, Any] | None,
        *,
        synthesized: bool = False,
    ) -> Path:
        """Atomic-rename publish of the commit record for ``target``."""
        step = int(_STEP_RE.match(target.name).group(1))
        manifest = {
            "manifest_version": MANIFEST_VERSION,
            "step": step,
            "files": [
                {"name": name, "bytes": size, "sha256": digest}
                for name, size, digest in files
            ],
        }
        if synthesized:
            # Pre-manifest checkpoint adopted on first scan/prune: no
            # topology metadata exists, so elastic validation treats the
            # saved topology as unknown (resume proceeds, no reshard check).
            manifest["synthesized"] = True
        if manifest_extra:
            manifest.update(manifest_extra)
        mpath = manifest_path(target)
        mtmp = mpath.with_name(mpath.name + ".tmp")
        mtmp.write_text(json.dumps(manifest, indent=1, sort_keys=False), encoding="utf-8")
        _fsync_file(mtmp)
        mtmp.replace(mpath)
        _fsync_dir(self._dir)
        return mpath

    def save_host_async(
        self,
        step: int,
        host_state: dict[str, Any],
        resolved_config: dict[str, Any],
        *,
        resilience: dict[str, Any] | None = None,
        manifest_extra: dict[str, Any] | None = None,
        inject_kill: bool = False,
    ) -> None:
        """Queue ``save_host`` on a background thread (one write in flight).

        The device→host gather has already happened in ``state_to_host``, so
        the remaining msgpack serialization + disk IO can overlap the next
        training steps — the reference's ``torch.save`` blocks the step loop
        (reference trainer.py:402-413). At most one write runs at a time;
        queueing a new one first drains (and re-raises errors from) the
        previous. Call ``wait_pending`` before reading checkpoints back.

        A plain DAEMON thread + Future, deliberately not ThreadPoolExecutor:
        executor workers are non-daemon and joined by an atexit hook, so a
        write wedged on dead storage would deadlock interpreter exit even
        after ``close(timeout)`` "abandoned" it — the abort-path contract
        (docs/robustness.md) requires the process to actually get out.
        """
        import threading
        from concurrent.futures import Future

        self.wait_pending()
        future: Future = Future()

        def work() -> None:
            # False = wait_pending cancelled the write before we started.
            if not future.set_running_or_notify_cancel():
                return
            try:
                future.set_result(
                    self.save_host(
                        step,
                        host_state,
                        resolved_config,
                        resilience=resilience,
                        manifest_extra=manifest_extra,
                        inject_kill=inject_kill,
                    )
                )
            except BaseException as exc:  # noqa: BLE001 — delivered via result()
                future.set_exception(exc)

        threading.Thread(target=work, name="ckpt-write", daemon=True).start()
        self._pending = future

    def poll(self) -> None:
        """Non-blocking failure check: if the in-flight async write has
        already finished with an error, re-raise it now. Called by the
        trainer each log interval so a failed write surfaces within one
        interval instead of at the next save or at close()."""
        pending = self._pending
        if pending is not None and pending.done():
            self._pending = None
            pending.result()

    def wait_pending(self, timeout: float | None = None) -> bool:
        """Block until the in-flight async write (if any) finishes; re-raise
        its error. With a ``timeout``, give up after that many seconds and
        return False, leaving the write in flight — abort/watchdog exit
        paths must never deadlock behind a write wedged on dead storage.
        Returns True when nothing is (any longer) pending."""
        pending = self._pending
        if pending is None:
            return True
        if timeout is not None and not pending.done():
            # A queued-but-unstarted write can simply be withdrawn — but
            # loudly, same as the timeout path: a checkpoint that silently
            # never lands makes the next resume inexplicable.
            if pending.cancel():
                from ..utils.logging import get_logger

                get_logger().error(
                    "queued async checkpoint write cancelled before it "
                    "started (bounded drain); the newest on-disk checkpoint "
                    "may be one save behind"
                )
                self._pending = None
                return True
        self._pending = None
        try:
            pending.result(timeout)
        except FuturesTimeoutError:
            # Still running: put it back so a later unbounded drain (or a
            # repeat bounded attempt) can still observe its outcome.
            self._pending = pending
            return False
        return True

    def close(self, timeout: float | None = None) -> None:
        """Drain the pending write. A ``timeout`` bounds the drain: on
        expiry the write is ABANDONED (logged as an error; the daemon
        writer thread cannot block process exit) instead of deadlocking —
        the abort-path contract (docs/robustness.md)."""
        try:
            drained = self.wait_pending(timeout)
            if not drained:
                from ..utils.logging import get_logger

                get_logger().error(
                    "async checkpoint write still in flight after %.1fs; "
                    "abandoning it (the newest on-disk checkpoint may be one "
                    "save behind)",
                    timeout,
                )
        finally:
            self._pending = None

    def _prune(self) -> None:
        """Keep the last k checkpoints by step — but NEVER delete the newest
        VERIFIED one. Retention keyed on file count alone would, with a
        corrupt newest file, delete the only restorable checkpoint and leave
        the run with nothing but garbage to resume from.

        Also garbage-collects orphaned commit stages: leftover ``*.tmp``
        files and unmanifested payloads whose write was cut before the
        manifest publish. An unmanifested payload that VERIFIES (the kill
        landed after its fsync'd rename) is a complete snapshot of the same
        deterministic trajectory — it is adopted via a synthesized manifest
        instead of deleted, which is also how pre-manifest checkpoint dirs
        migrate in place."""
        self._collect_orphans()
        ckpts = self.all_checkpoints()
        doomed = ckpts[: -self._keep_last_k]
        if not doomed:
            return
        newest_valid = next(
            (p for p in reversed(ckpts) if self.verify(p)), None
        )
        for path in doomed:
            if path == newest_valid:
                continue
            path.unlink(missing_ok=True)
            sidecar_path(path).unlink(missing_ok=True)
            manifest_path(path).unlink(missing_ok=True)

    def _collect_orphans(self) -> None:
        """Sweep uncommitted stage leftovers (see :meth:`_prune`). Only
        called between writes of THIS manager — writes are serialized (one
        async write in flight, drained before the next queues), so any tmp
        file or unmanifested payload found here is a dead stage, not an
        in-flight one."""
        if not self._dir.is_dir():
            return
        from ..utils.logging import get_logger

        manifested = {
            int(_MANIFEST_RE.match(p.name).group(1))
            for p in self._dir.iterdir()
            if _MANIFEST_RE.match(p.name)
        }
        if not manifested:
            # Pre-manifest directory: nothing to reconcile against; legacy
            # selection (and synthesis on scan) handles it.
            return
        for path in list(self._dir.iterdir()):
            if path.name.endswith(".tmp"):
                path.unlink(missing_ok=True)
                continue
            mm = _MANIFEST_RE.match(path.name)
            if mm and not (
                self._dir / f"step_{int(mm.group(1)):06d}.ckpt"
            ).is_file():
                # Manifest whose payload vanished (external deletion):
                # a dangling commit record must not shadow older steps.
                path.unlink(missing_ok=True)
                continue
            m = _STEP_RE.match(path.name)
            if not m or int(m.group(1)) in manifested:
                continue
            if self.verify(path):
                try:
                    self.synthesize_manifest(path)
                    get_logger().warning(
                        "adopted unmanifested checkpoint %s (complete payload "
                        "whose commit was interrupted): synthesized its manifest",
                        path.name,
                    )
                except OSError:
                    pass
            else:
                get_logger().warning(
                    "garbage-collecting torn uncommitted checkpoint stage %s",
                    path.name,
                )
                path.unlink(missing_ok=True)
                sidecar_path(path).unlink(missing_ok=True)

    def synthesize_manifest(self, ckpt: str | Path) -> Path:
        """Write a commit manifest for an existing (verifying) payload —
        the backward-compat path for pre-manifest checkpoints, and the
        adoption path for complete-but-uncommitted stages."""
        ckpt = Path(ckpt)
        files = [_file_entry(ckpt)]
        side = sidecar_path(ckpt)
        if side.is_file():
            files.append(_file_entry(side))
        return self._publish_manifest(ckpt, files, None, synthesized=True)

    def verify(self, path: str | Path) -> bool:
        """True when ``path`` is a restorable checkpoint.

        With a sha-256 sidecar present the file digest must match; without
        one (pre-integrity checkpoints, or a crash between payload and
        sidecar rename) fall back to a deep parse — msgpack restore plus the
        required-key check. Results are cached by (path, size, mtime).
        """
        path = Path(path)
        try:
            stat = path.stat()
        except OSError:
            return False
        key = (str(path), stat.st_size, stat.st_mtime_ns)
        cached = self._verify_cache.get(key)
        if cached is not None:
            return cached
        ok = _verify_uncached(path)
        self._verify_cache[key] = ok
        return ok

    def verify_manifest(self, ckpt: str | Path) -> bool:
        """True when ``ckpt``'s commit manifest exists and every listed
        file is present with the recorded size and (for the payload) the
        recorded sha-256. Results are cached by the payload's
        (path, size, mtime) alongside the sidecar-based cache."""
        ckpt = Path(ckpt)
        manifest = read_manifest(ckpt)
        if manifest is None:
            return False
        try:
            stat = ckpt.stat()
        except OSError:
            return False
        key = (str(manifest_path(ckpt)), stat.st_size, stat.st_mtime_ns)
        cached = self._verify_cache.get(key)
        if cached is not None:
            return cached
        ok = _manifest_files_ok(self._dir, manifest)
        self._verify_cache[key] = ok
        return ok

    def all_manifests(self) -> list[Path]:
        """Committed steps' payload paths (manifest present), sorted by
        step, oldest first. The payload file itself may be missing or
        damaged — :meth:`verify_manifest` decides restorability."""
        if not self._dir.is_dir():
            return []
        found = []
        for path in self._dir.iterdir():
            m = _MANIFEST_RE.match(path.name)
            if m:
                step = int(m.group(1))
                found.append((step, self._dir / f"step_{step:06d}.ckpt"))
        return [p for _, p in sorted(found)]

    def latest_valid_checkpoint(self, *, before_step: int | None = None) -> Path | None:
        """Newest COMMITTED checkpoint whose manifest verifies, scanning
        backward past damaged steps (each skip logs a warning).

        Selection is manifest-driven: in a directory with commit manifests,
        a payload without one is an uncommitted stage — invisible here no
        matter how intact its bytes look, which is what makes the multi-file
        commit atomic. Directories with NO manifests at all are pre-manifest
        layouts: they fall back to per-file verification (sidecar digest or
        deep parse) and every file that verifies gets a manifest synthesized
        in place, so the dir is migrated by its first scan.

        ``before_step`` restricts the scan to checkpoints saved strictly
        before that step — the loss-spike rollback uses it so a periodic
        save that landed inside the spiking window (valid by integrity,
        poisoned by value) cannot become the restore point; with the
        restriction active, no fallback applies and None means "nothing
        restorable".

        Unrestricted scans where NO file verifies fall back to the plain
        newest so legacy layouts and hand-assembled dirs still resolve — a
        genuinely broken file then fails at ``load`` with a precise error.
        """

        def step_of(p: Path) -> int:
            return int(_STEP_RE.match(p.name).group(1))

        from ..utils.logging import get_logger

        manifests = self.all_manifests()
        if manifests:
            candidates = manifests
            if before_step is not None:
                candidates = [p for p in candidates if step_of(p) < before_step]
            for path in reversed(candidates):
                if self.verify_manifest(path):
                    return path
                get_logger().warning(
                    "checkpoint %s failed integrity verification against its "
                    "commit manifest; falling back to the previous one",
                    path,
                )
            if before_step is not None:
                return None
            # Every committed step is damaged: degrade to the legacy
            # per-file scan below rather than returning nothing for a dir
            # that may still hold a restorable unmanifested payload.
        ckpts = self.all_checkpoints()
        if before_step is not None:
            ckpts = [p for p in ckpts if step_of(p) < before_step]
        for path in reversed(ckpts):
            if self.verify(path):
                if read_manifest(path) is None:
                    # Backward compat: adopt the pre-manifest checkpoint so
                    # later scans (and the atomic-commit invariants) see a
                    # committed step. Best-effort — a read-only snapshot
                    # dir still resolves, it just stays unmigrated.
                    try:
                        self.synthesize_manifest(path)
                    except OSError:
                        pass
                return path
            get_logger().warning(
                "checkpoint %s failed integrity verification; "
                "falling back to the previous one",
                path,
            )
        if before_step is not None:
            return None
        return ckpts[-1] if ckpts else None

    def all_checkpoints(self) -> list[Path]:
        """Checkpoints sorted by parsed step number, oldest first."""
        if not self._dir.is_dir():
            return []
        found = []
        for path in self._dir.iterdir():
            m = _STEP_RE.match(path.name)
            if m:
                found.append((int(m.group(1)), path))
        return [p for _, p in sorted(found)]

    def latest_checkpoint(self) -> Path | None:
        ckpts = self.all_checkpoints()
        return ckpts[-1] if ckpts else None

    @staticmethod
    def load(path: str | Path) -> dict[str, Any]:
        """Read and validate a checkpoint payload (host numpy trees).

        When a sha-256 sidecar exists the file content is verified against
        it first, so a truncated or bit-flipped checkpoint fails with a
        precise integrity error instead of a deep msgpack traceback (or —
        worse — silently restoring garbage arrays).
        """
        path = Path(path)
        if not path.is_file():
            raise CheckpointError(f"Checkpoint file not found: {path}")
        blob = path.read_bytes()
        expected = _read_sidecar_digest(path)
        if expected is not None:
            actual = hashlib.sha256(blob).hexdigest()
            if actual != expected:
                raise CheckpointError(
                    f"Checkpoint {path} failed sha-256 integrity verification "
                    f"(expected {expected[:12]}…, got {actual[:12]}…): the file "
                    "is truncated or corrupt"
                )
        try:
            payload = serialization.msgpack_restore(blob)
        except Exception as exc:
            raise CheckpointError(
                f"Checkpoint {path} is not a parseable msgpack payload "
                f"(truncated or corrupt): {exc}"
            ) from exc
        if not isinstance(payload, dict):
            raise CheckpointError(
                f"Checkpoint {path} does not hold a payload mapping"
            )
        missing = _REQUIRED_KEYS - set(payload)
        if missing:
            raise CheckpointError(
                f"Checkpoint {path} is missing required keys: {sorted(missing)}"
            )
        return payload


def _file_entry(path: Path) -> tuple[str, int, str]:
    """(name, size, sha256) manifest entry for an existing file."""
    blob = path.read_bytes()
    return (path.name, len(blob), hashlib.sha256(blob).hexdigest())


def _manifest_files_ok(directory: Path, manifest: dict[str, Any]) -> bool:
    """Every file the manifest lists exists with the recorded size and
    digest. Malformed manifests (wrong shapes, non-numeric sizes, junk
    digest values) fail CLOSED — the backward scan must fall back to the
    previous step, never crash mid-resolution."""
    try:
        files = manifest.get("files")
        if not isinstance(files, list) or not files:
            return False
        for entry in files:
            if not isinstance(entry, dict):
                return False
            name = entry.get("name")
            if not isinstance(name, str) or "/" in name or name.startswith("."):
                return False
            path = directory / name
            try:
                blob = path.read_bytes()
            except OSError:
                return False
            size = entry.get("bytes")
            if size is not None and len(blob) != int(size):
                return False
            digest = entry.get("sha256")
            if (
                digest is not None
                and hashlib.sha256(blob).hexdigest() != str(digest).lower()
            ):
                return False
    except (TypeError, ValueError):
        return False
    return True


def _verify_uncached(path: Path) -> bool:
    """One verification pass: sidecar digest when present, deep parse
    (msgpack restore + required keys) otherwise."""
    try:
        blob = path.read_bytes()
    except OSError:
        return False
    expected = _read_sidecar_digest(path)
    if expected is not None:
        return hashlib.sha256(blob).hexdigest() == expected
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception:
        return False
    return isinstance(payload, dict) and not (_REQUIRED_KEYS - set(payload))


def load_inference_params(
    path: str | Path,
    abstract_params: Any,
    *,
    expected_config_yaml: str | None = None,
    device: bool = True,
) -> tuple[Any, int]:
    """Restore just the model params (no optimizer state) from a checkpoint.

    ``abstract_params`` is an unboxed ``jax.eval_shape`` tree of the model's
    parameters; it supplies the pytree structure that the flat state dict is
    mapped back onto. Returns ``(params_on_device, step)`` — the inference
    path for the ``generate`` CLI, which the reference only offers as eager
    notebook cells (reference notebooks/trained_vs_random_completion.ipynb).
    ``device=False`` keeps host numpy (host-side consumers like
    checkpoint averaging skip a full device round-trip per input).

    When ``expected_config_yaml`` is given and differs from the config stored
    in the checkpoint, a warning is logged — the same warn-and-continue
    contract as the resume path (reference trainer.py:315-318).
    """
    import jax.numpy as jnp

    payload = CheckpointManager.load(path)
    if expected_config_yaml is not None:
        warn_on_config_mismatch(payload, expected_config_yaml, path)
    host_params = serialization.from_state_dict(abstract_params, payload["params"])
    if not device:
        return host_params, int(payload["step"])
    params = jax.tree.map(jnp.asarray, host_params)
    return params, int(payload["step"])


def ema_from_payload(payload: dict[str, Any], abstract_target: Any) -> Any:
    """Dig the EMA shadow out of an already-loaded checkpoint payload and
    map it onto ``abstract_target`` (the params tree the shadow mirrors —
    the full model tree, or the factor subtree for LoRA runs). The
    shadow is stored in float32 (training/optimizer.py); extraction
    casts back to each target leaf's dtype. Raises ``ValueError`` when
    the payload holds no EMA state."""
    import jax.numpy as jnp

    from .optimizer import find_ema_tree

    raw = find_ema_tree(payload["opt_state"])
    if raw is None:
        raise ValueError(
            "checkpoint holds no EMA state — train with "
            "trainer.extra.ema_decay to track shadow weights"
        )
    # from_state_dict maps values onto the target STRUCTURE (dtypes come
    # from the stored f32 arrays); cast each leaf back to the dtype the
    # consumer's tree expects.
    host = serialization.from_state_dict(abstract_target, raw)
    return jax.tree.map(
        lambda t, v: jnp.asarray(v, t.dtype), abstract_target, host
    )


def load_ema_params(
    path: str | Path,
    abstract_target: Any,
    *,
    expected_config_yaml: str | None = None,
) -> tuple[Any, int]:
    """Path-based wrapper over :func:`ema_from_payload` — restore the
    Polyak shadow tracked by ``trainer.extra.ema_decay`` from a
    checkpoint file."""
    payload = CheckpointManager.load(path)
    if expected_config_yaml is not None:
        warn_on_config_mismatch(payload, expected_config_yaml, path)
    return ema_from_payload(payload, abstract_target), int(payload["step"])


def warn_on_config_mismatch(
    payload: dict[str, Any], current_config_yaml: str, path: str | Path
) -> None:
    """Warn-and-continue when a checkpoint's stored config differs from the
    current one (reference trainer.py:315-318) — shared by resume and the
    ``generate`` inference loader."""
    if payload["config_yaml"] != current_config_yaml:
        from ..utils.logging import get_logger

        get_logger().warning(
            "checkpoint config differs from current config; "
            "continuing with the CURRENT config (checkpoint: %s)",
            path,
        )


def resolve_resume_path(resume_spec: str, output_root: str | Path) -> Path:
    """Resolve a ``--resume`` spec (reference trainer.py:215-241).

    file → itself; dir → newest VALID inside (falling back to the dir's
    ``checkpoints/`` subdir, so a run DIRECTORY path works like its run
    id); bare ``*.ckpt``/``*.pt`` string → FileNotFoundError; anything
    else → treated as a run id under ``{output_root}/{run_id}/checkpoints``.

    Directory and run-id resolution go through ``latest_valid_checkpoint``:
    a run whose newest checkpoint was truncated by a mid-write eviction
    warns and resumes from the previous verified one instead of dying
    mid-restore — the auto-resume loop must never wedge on its own save.
    """
    candidate = Path(resume_spec)
    if candidate.is_file():
        return candidate
    if candidate.is_dir():
        latest = CheckpointManager(candidate).latest_valid_checkpoint()
        if latest is None and (candidate / "checkpoints").is_dir():
            # A run DIRECTORY (not just a run id): descend into its
            # checkpoints/ subdir, same shape as the run-id branch below.
            latest = CheckpointManager(
                candidate / "checkpoints"
            ).latest_valid_checkpoint()
        if latest is None:
            raise FileNotFoundError(f"No checkpoints found in directory: {candidate}")
        return latest
    if resume_spec.endswith((".ckpt", ".pt")):
        raise FileNotFoundError(f"Checkpoint file does not exist: {resume_spec}")
    run_ckpt_dir = Path(output_root) / resume_spec / "checkpoints"
    if not run_ckpt_dir.is_dir():
        raise FileNotFoundError(
            f"Resume spec {resume_spec!r} is neither a file, a directory, "
            f"nor a run id with checkpoints under {run_ckpt_dir}"
        )
    latest = CheckpointManager(run_ckpt_dir).latest_valid_checkpoint()
    if latest is None:
        raise FileNotFoundError(f"No checkpoints found for run id {resume_spec!r}")
    return latest
