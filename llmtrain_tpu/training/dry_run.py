"""Forward-only dry run.

Parity target: reference ``src/llmtrain/training/dry_run.py`` — build
adapter/module, run min(5, max_steps) forward-only batches, log per-step
loss + wall ms, return resolved plugin names and steps executed (:15-73).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import numpy as np

from ..config.schemas import RunConfig
from ..data.sampler import DeterministicSampler
from ..registry import get_data_module
from ..training.train_step import make_eval_step
from ..utils.logging import get_logger

DEFAULT_DRY_RUN_STEPS = 5

logger = get_logger()


@dataclass(frozen=True)
class DryRunResult:
    model_adapter: str
    data_module: str
    steps_executed: int


def run_dry_run(cfg: RunConfig) -> DryRunResult:
    """Run a few forward-only batches on the default device (no mesh)."""
    from ..models.lora import build_adapter

    # The same adapter factory the Trainer uses, so the dry run validates
    # the SAME program train will build (a bad LoRA targets list must
    # fail here, not five minutes into the real run).
    adapter = build_adapter(cfg)
    data_module = get_data_module(cfg.data.name)()

    tokenizer = None
    try:
        tokenizer = adapter.build_tokenizer(cfg)
    except Exception as exc:
        logger.warning("build_tokenizer failed (%s); continuing without one", exc)
    data_module.setup(cfg, tokenizer)
    model = adapter.build_model(cfg)
    params = adapter.init_params(model, cfg, jax.random.key(cfg.run.seed))

    from flax.linen import meta as nn_meta

    params = nn_meta.unbox(params)
    eval_step = jax.jit(make_eval_step(adapter, model))

    train_ds = data_module.train_dataset()
    steps = min(DEFAULT_DRY_RUN_STEPS, cfg.trainer.max_steps)
    batch_size = min(cfg.trainer.micro_batch_size, len(train_ds))
    sampler = DeterministicSampler(
        num_examples=len(train_ds),
        batch_size=batch_size,
        seed=cfg.run.seed,
        shuffle=not cfg.run.deterministic,
    )

    import jax.numpy as jnp

    for i in range(steps):
        start = time.perf_counter()
        host = train_ds.get_examples(sampler.batch_indices(i))
        batch = {k: jnp.asarray(v) for k, v in host.items()}
        loss_sum, tokens = eval_step(params, batch)
        loss = float(np.sum(jax.device_get(loss_sum)) / max(np.sum(jax.device_get(tokens)), 1.0))
        elapsed_ms = (time.perf_counter() - start) * 1000.0
        logger.info("dry_run step=%d/%d loss=%.4f time_ms=%.1f", i + 1, steps, loss, elapsed_ms)

    return DryRunResult(
        model_adapter=cfg.model.name,
        data_module=cfg.data.name,
        steps_executed=steps,
    )
