"""Training runtime: Trainer, jit train step, checkpoints, dry run."""

from .checkpoint import CheckpointError, CheckpointManager, resolve_resume_path
from .dry_run import DEFAULT_DRY_RUN_STEPS, DryRunResult, run_dry_run
from .optimizer import build_optimizer, lr_schedule
from .train_step import TrainState, create_train_state, make_eval_step, make_train_step
from .trainer import Trainer, TrainResult

__all__ = [
    "CheckpointError",
    "CheckpointManager",
    "DEFAULT_DRY_RUN_STEPS",
    "DryRunResult",
    "TrainResult",
    "TrainState",
    "Trainer",
    "build_optimizer",
    "create_train_state",
    "lr_schedule",
    "make_eval_step",
    "make_train_step",
    "resolve_resume_path",
    "run_dry_run",
]
