"""Step-based trainer over a jit-compiled train step on a device mesh.

Parity target: reference ``src/llmtrain/training/trainer.py`` — 1-indexed
step loop (:361), grad accumulation, interval metric accumulators with reset
after each log (:355-359, :493-497), per-rank + global metric naming
(:428-482), token-weighted eval (:243-289), rank-0-gated checkpointing at
``save_every`` and the final step (:402-413), resume with config-mismatch
warning (:315-318), ``TrainResult`` (:30-43).

TPU architecture: instead of a DDP-wrapped model + collectives sprinkled
through the loop, the Trainer builds ONE jit-compiled train step over a
named mesh (see train_step.py) and feeds it globally-sharded batches built
by ``jax.make_array_from_callback`` from the deterministic sampler. "Rank"
in metric names means *data shard* (devices), a superset of the reference's
process ranks. Host work per step is only: assemble batch indices, enqueue
the step, and (at log boundaries) pull small scalars off device.
"""

from __future__ import annotations

import math
import signal
import threading
import time
from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import yaml
from flax import linen as nn
from flax.linen import meta as nn_meta

from ..config.schemas import RunConfig
from ..data.prefetch import BatchPrefetcher
from ..data.sampler import DeterministicSampler
from ..distributed import DistState, build_mesh
from ..parallel.sharding import (
    DEFAULT_LOGICAL_AXIS_RULES,
    batch_sharding,
    data_parallel_degree,
    host_memory_kind,
    mesh_axis_sizes,
    opt_state_shardings,
    replicated,
    reshard_state,
    state_shardings,
    with_memory_kind,
)
from ..registry import get_data_module
from ..resilience import (
    FaultPlan,
    HangWatchdog,
    LossSpikeDetector,
    NonFiniteLossError,
    ProgressBeacon,
    RollbackBudgetExceededError,
    StragglerTracker,
    retry,
    retry_rng,
)
from ..resilience.elastic import (
    classify_topology_change,
    describe_topology,
    resume_batch_index,
)
from ..telemetry import Telemetry
from ..tracking.base import Tracker
from ..utils.hw import mfu as compute_mfu
from ..utils.hw import peak_flops_per_chip, transformer_flops_per_token
from ..utils.logging import get_logger
from .checkpoint import CheckpointManager, resolve_resume_path
from .optimizer import build_optimizer, lr_schedule
from .train_step import TrainState, make_eval_step, make_train_step

logger = get_logger()

# Abort/watchdog paths bound their drain of the in-flight async checkpoint
# write by this much before abandoning it (docs/robustness.md).
_ABORT_DRAIN_TIMEOUT_SEC = 30.0


@dataclass(frozen=True)
class TrainResult:
    """Final outcome of a training run (reference trainer.py:30-43)."""

    final_step: int
    final_loss: float
    final_val_loss: float | None
    total_time: float
    peak_memory: float
    val_metrics: dict[str, float] | None
    first_step_loss: float | None
    resumed_from_step: int | None
    parameter_count: int
    trainable_parameter_count: int
    total_tokens: int = 0
    # True when SIGTERM cut the run short: the last checkpoint is the
    # preemption save and final_step is where training actually stopped.
    preempted: bool = False
    # Loss-spike rollbacks performed (cumulative across resumes — the
    # counter round-trips through the checkpoint's resilience payload).
    rollbacks: int = 0


class Trainer:
    def __init__(
        self,
        cfg: RunConfig,
        run_dir: Path | None,
        tracker: Tracker,
        dist_state: DistState | None = None,
    ) -> None:
        self._cfg = cfg
        self._run_dir = run_dir
        self._tracker = tracker
        self._dist_state = dist_state

        # Unified telemetry (telemetry/, docs/observability.md): the
        # timeline + metrics registry + memory monitor every component of
        # this Trainer publishes through. All tracker traffic is routed
        # via the registry so backend failures degrade to warnings
        # instead of unwinding into the step loop.
        self._telemetry = Telemetry(
            cfg,
            run_dir,
            tracker,
            process_index=dist_state.process_index if dist_state else 0,
            is_main=dist_state is None or dist_state.is_main,
        )

        self._dataset_specs: dict[int, tuple[tuple[str, ...], int]] = {}
        from ..models.lora import build_adapter

        self._adapter = build_adapter(cfg)
        self._data_module = get_data_module(cfg.data.name)()

        # Fault-tolerance wiring (resilience/, docs/robustness.md): the
        # fault plan is inert unless the config injects something; rollback
        # bookkeeping lives on the instance so checkpoint saves can
        # round-trip it.
        self._resilience = cfg.resilience
        self._faults = FaultPlan.from_config(cfg.resilience.faults)
        self._rollback_count = 0
        self._data_offset = 0
        # Resumes survived so far (cumulative: round-trips through the
        # checkpoint's resilience payload like the rollback counter).
        self._resume_count = 0
        self._sampler: DeterministicSampler | None = None
        self._spike_detector: LossSpikeDetector | None = None
        self._last_restored_resilience: dict[str, Any] = {}
        self._last_restored_manifest: dict[str, Any] | None = None
        self._beacon: ProgressBeacon | None = None
        self._straggler: StragglerTracker | None = None
        # One persistent eval-data worker shared by every _evaluate call
        # of a fit (eval-heavy configs used to pay ThreadPoolExecutor
        # startup per eval interval). Lazily created; shut down when the
        # owning fit()/evaluate() returns so Trainer-per-run processes
        # don't accumulate idle non-daemon workers.
        self._eval_pool = None

        tokenizer = None
        try:
            tokenizer = self._adapter.build_tokenizer(cfg)
        except Exception as exc:  # offline environments: tokenizer optional
            logger.warning("build_tokenizer failed (%s); continuing without one", exc)
        # Dataset loading is the one init stage that touches network/disk
        # caches — transient failures (HF hub hiccup, NFS blip) get
        # full-jitter exponential-backoff retries instead of killing the
        # pod; the per-rank seeded RNG keeps a multi-host fleet's retries
        # decorrelated so a shared-dependency hiccup doesn't turn into a
        # synchronized thundering herd.
        retry(
            self._faults.flaky(
                "dataset_load", lambda: self._data_module.setup(cfg, tokenizer)
            ),
            attempts=cfg.resilience.retry_attempts,
            base_delay=cfg.resilience.retry_base_delay,
            description="dataset setup",
            rng=retry_rng(
                cfg.run.seed, dist_state.process_index if dist_state else 0
            ),
        )

        self._model = self._adapter.build_model(cfg)

        devices = jax.devices() if cfg.run.device == "tpu" else jax.devices("cpu")
        # Fail-fast plan validation (autotune/plan.py): axis tiling,
        # capability flags and divisibility rules all raise a named
        # MeshPlanError (config exit code 2) here, BEFORE any mesh or
        # params materialize — not as an opaque pjit/XLA error mid-setup.
        from ..autotune.plan import plan_from_config

        plan_from_config(cfg, len(devices), adapter=self._adapter)
        self._mesh = build_mesh(cfg.distributed.mesh, devices)
        from ..parallel.pipeline import pipeline_degree

        if pipeline_degree(self._mesh) > 1 and not getattr(
            self._adapter, "supports_pipeline", False
        ):
            raise ValueError(
                f"mesh axis 'pipeline' is {self._mesh.shape['pipeline']} but "
                f"model {cfg.model.name!r} does not stack its layers for "
                "pipeline stages; use a pipeline-capable model "
                "(e.g. 'gpt_pipeline') or set pipeline to 1"
            )
        # Adapter-specific mesh compatibility (e.g. GQA's n_kv_heads must
        # shard over the tensor axis) — fail with a clear message instead
        # of an opaque pjit sharding error at compile time.
        validate_mesh = getattr(self._adapter, "validate_mesh", None)
        if validate_mesh is not None:
            validate_mesh(cfg, self._mesh)
        self._rules = list(DEFAULT_LOGICAL_AXIS_RULES)
        self._dp = data_parallel_degree(self._mesh)
        self._global_micro = cfg.trainer.micro_batch_size * self._dp
        # Rows every applied batch must divide by (pipelined models:
        # data_shards × microbatches); eval pads up to lcm(dp, this).
        # getattr for duck-typed adapters, like validate_mesh above.
        divisor_fn = getattr(self._adapter, "batch_divisor", None)
        self._batch_divisor = (
            int(divisor_fn(cfg, self._mesh)) if divisor_fn is not None else 1
        )

        self._tx = build_optimizer(cfg.trainer)
        # Adapter-level optimizer wrapping (LoRA freezes the base tree by
        # masking moments to the factor leaves) — duck-typed like
        # validate_mesh above.
        wrap_tx = getattr(self._adapter, "wrap_optimizer", None)
        if wrap_tx is not None:
            self._tx = wrap_tx(self._tx)
        self._schedule = lr_schedule(cfg.trainer)

        self._ckpt_mgr: CheckpointManager | None = None
        if run_dir is not None:
            keep_last_k = int(cfg.trainer.extra.get("keep_last_k", 3))
            self._ckpt_mgr = CheckpointManager(
                Path(run_dir) / "checkpoints",
                keep_last_k=keep_last_k,
                # Commit observer runs on the async writer thread; the
                # registry/timeline are lock-protected, so the counter the
                # Prometheus endpoint exports as
                # llmtrain_checkpoint_commits_total stays exact.
                on_commit=self._on_checkpoint_commit,
            )

        with self._mesh, nn.logical_axis_rules(self._rules):
            self._state = self._init_state()

        # Metrics come out replicated (out_shardings) so every process can
        # read them: per-example arrays are otherwise batch-sharded and not
        # addressable across hosts. They are tiny; the all-gather is noise.
        use_dropout = cfg.model.dropout > 0.0
        step_fn = jax.jit(
            make_train_step(
                self._adapter,
                self._model,
                self._tx,
                grad_accum_steps=cfg.trainer.grad_accum_steps,
                use_dropout=use_dropout,
                nonfinite_guard=cfg.resilience.nonfinite_guard,
                inject_nan_window=self._faults.nan_window(),
                grad_shardings=self._grad_shardings,
            ),
            donate_argnums=(0,),
            out_shardings=(self._state_shardings, replicated(self._mesh)),
        )
        if self._zero_offload_mode == "roundtrip":
            # Explicit host round-trip (no pinned_host memory space on this
            # backend): the state's opt leaves live as host numpy between
            # steps; each step lands them on the mesh through a jit
            # identity (NOT device_put — on the CPU backend device_put
            # aliases host numpy zero-copy and the donating step would
            # then write into memory numpy still owns, see reshard_state)
            # and pulls the updated shards back to owned host copies.
            to_device = jax.jit(
                lambda t: t, out_shardings=self._state_shardings.opt_state
            )

            def step_with_host_opt(state, batch, run_key):
                state = state.replace(opt_state=to_device(state.opt_state))
                new_state, metrics = step_fn(state, batch, run_key)
                return (
                    new_state.replace(
                        opt_state=self._opt_state_to_host(new_state.opt_state)
                    ),
                    metrics,
                )

            self._train_step_fn = step_with_host_opt
        else:
            self._train_step_fn = step_fn
        # The raw jitted step (not the host-roundtrip wrapper): the cost
        # attribution hook lowers THIS to read XLA's cost_analysis —
        # lowering only traces, so the donation annotation never consumes
        # a live buffer (telemetry/profiling.py).
        self._jit_train_step = step_fn
        self._eval_step_fn = jax.jit(
            make_eval_step(self._adapter, self._model),
            out_shardings=replicated(self._mesh),
        )

        params = nn_meta.unbox(self._state.params)
        self._param_count = int(
            sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        )
        # Adapters that freeze parameters (LoRA) expose which leaves
        # train; the count feeds the summary AND the MFU FLOP model
        # (utils/hw.py: a frozen base skips its dW backward).
        mask_fn = getattr(self._adapter, "trainable_param_mask", None)
        if mask_fn is None:
            self._trainable_count = self._param_count
        else:
            mask = mask_fn(self._state.params)
            self._trainable_count = int(
                sum(
                    int(np.prod(x.shape))
                    for x, keep in zip(
                        jax.tree.leaves(params), jax.tree.leaves(mask), strict=True
                    )
                    if keep
                )
            )
        self._peak_flops = peak_flops_per_chip()
        self._train_seqlen = cfg.model.block_size  # refined from data in fit()
        # Cost-attribution inputs captured during fit (telemetry/profiling.py).
        self._batch_struct: Any | None = None
        self._train_batch_keys: tuple[str, ...] = ()
        self._tokens_per_step = 0

    # ------------------------------------------------------------------ setup

    def _init_state(self) -> TrainState:
        """Initialize the sharded TrainState on the mesh.

        Params keep their flax ``Partitioned`` metadata inside the state so
        optimizer moments inherit the same logical specs; shardings are
        computed from an ``eval_shape`` trace and applied via out_shardings.

        With ``trainer.zero.enabled`` the optimizer-state leaves swap their
        replicated fallback for the ZeRO partitioning over the combined
        data-parallel axes (parallel/sharding.py:opt_state_shardings) —
        the jitted step's in/out shardings then make XLA/GSPMD emit the
        sharded update + param all-gather, no step-code change. With
        ``host_offload`` the state additionally pins to the backend's
        ``pinned_host`` memory space when one exists; otherwise
        ``_zero_offload_mode`` records the explicit round-trip fallback
        the step wrapper applies.
        """
        cfg = self._cfg
        init_rng = jax.random.key(cfg.run.seed)

        def create(rng):
            params = self._adapter.init_params(self._model, cfg, rng)
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self._tx.init(params),
                # The guard's consecutive-skip counter rides in the state so
                # the hot loop never syncs on it; None keeps unguarded runs'
                # pytree structure identical to the pre-resilience layout.
                nonfinite_count=(
                    jnp.zeros((), jnp.int32)
                    if cfg.resilience.nonfinite_guard
                    else None
                ),
            )

        abstract = jax.eval_shape(create, init_rng)
        shardings = state_shardings(self._mesh, abstract, self._rules)
        self._grad_shardings = None
        self._zero_offload_mode: str | None = None
        zero = cfg.trainer.zero
        if zero.enabled:
            opt_sh = opt_state_shardings(self._mesh, abstract.opt_state, self._rules)
            # Stage 1 pins grads to the PARAM layout (the replicated path's
            # exact all-reduce, bitwise math); stage 2 pins them to the
            # ZeRO layout so GSPMD reduce-scatters instead.
            self._grad_shardings = (
                shardings.params
                if zero.stage == 1
                else opt_state_shardings(
                    self._mesh, abstract.params, self._rules, subject="gradient"
                )
            )
            offload_kind = None
            if zero.host_offload:
                offload_kind = host_memory_kind(self._mesh)
                if offload_kind is not None:
                    opt_sh = with_memory_kind(opt_sh, offload_kind)
                    self._zero_offload_mode = "memory_kind"
                else:
                    self._zero_offload_mode = "roundtrip"
                    logger.warning(
                        "trainer.zero.host_offload: this backend exposes no "
                        "pinned_host memory space; using the explicit host "
                        "round-trip (full opt-state H2D/D2H each step — "
                        "correct, but slower than memory-kind offload)"
                    )
            shardings = shardings.replace(opt_state=opt_sh)
            logger.info(
                "ZeRO optimizer-state sharding enabled: stage %d over %d-way "
                "data parallel%s",
                zero.stage,
                self._dp,
                (
                    f", host offload via {self._zero_offload_mode}"
                    if zero.host_offload
                    else ""
                ),
            )
        self._state_shardings = shardings
        state = jax.jit(create, out_shardings=shardings)(init_rng)
        if self._zero_offload_mode == "roundtrip":
            state = state.replace(
                opt_state=self._opt_state_to_host(state.opt_state)
            )
        return state

    @staticmethod
    def _opt_state_to_host(opt_state: Any) -> Any:
        """Owned host-numpy copies of every opt-state leaf (round-trip
        offload), flax boxes preserved so the state's pytree structure
        never changes mid-run. Shares the checkpoint module's
        owned-copy rule (zero-copy views of donated device buffers are
        the aliasing trap), DMA prestart (transfers pipeline instead of
        serializing leaf-by-leaf), and multi-host allgather for shards
        another process owns."""
        from .checkpoint import host_fetch, start_host_transfers

        start_host_transfers(opt_state)
        return jax.tree.map(host_fetch, opt_state)

    @property
    def _is_main(self) -> bool:
        return self._dist_state is None or self._dist_state.is_main

    @property
    def state(self) -> TrainState:
        return self._state

    @property
    def model(self):
        """The built (uninitialized) Flax module — for generation/eval."""
        return self._model

    @property
    def mesh(self):
        return self._mesh

    @property
    def parameter_count(self) -> int:
        return self._param_count

    # ------------------------------------------------------------------ data

    def _global_batch(self, sampler: DeterministicSampler, dataset, step: int) -> dict:
        """Assemble the (A, Bg, T) sharded global batch for optimizer step ``step``.

        ``_data_offset`` (normally 0) shifts the deterministic stream after a
        loss-spike rollback: the replayed steps consume the batches that
        FOLLOW the poisonous window instead of re-feeding it. The offset
        round-trips through the checkpoint so resume stays exact.
        """
        accum = self._cfg.trainer.grad_accum_steps
        base_index = (step - 1) * accum + self._data_offset
        keys, seqlen = self._dataset_spec(dataset)
        sharding = batch_sharding(self._mesh, with_accum_dim=True)

        # One dataset gather per (accum row, shard slice), shared across keys.
        gather_cache: dict[tuple, dict[str, np.ndarray]] = {}

        def fetch(key: str, index) -> np.ndarray:
            a_sl, b_sl, t_sl = index
            a_start = a_sl.start if a_sl.start is not None else 0
            a_stop = a_sl.stop if a_sl.stop is not None else accum
            rows = []
            for a in range(a_start, a_stop):
                cache_key = (a, b_sl.start, b_sl.stop)
                if cache_key not in gather_cache:
                    indices = sampler.batch_indices(base_index + a)[b_sl]
                    gather_cache[cache_key] = dataset.get_examples(indices)
                rows.append(gather_cache[cache_key][key][:, t_sl])
            return np.stack(rows)

        shape = (accum, self._global_micro, seqlen)
        return {
            key: jax.make_array_from_callback(shape, sharding, lambda i, k=key: fetch(k, i))
            for key in keys
        }

    def _eval_batch(self, dataset, indices: np.ndarray, *, n_pad: int = 0) -> dict:
        """Sharded (B, T) batch for the eval step from explicit example indices.

        Single assembly point for every forward-only batch (_evaluate and
        _restored_step_loss). Always includes an attention_mask —
        synthesized all-ones when the dataset doesn't produce one — and with
        ``n_pad`` > 0 the trailing rows are zero-masked so duplicated
        padding rows contribute 0 loss and 0 tokens to the token-weighted
        aggregation.
        """
        ds_keys, seqlen = self._dataset_spec(dataset)
        keys = set(ds_keys) | {"attention_mask"}
        bs = len(indices)
        sharding = batch_sharding(self._mesh, with_accum_dim=False)

        def fetch(key: str, index) -> np.ndarray:
            b_sl, t_sl = index
            examples = dataset.get_examples(indices[b_sl])
            if key == "attention_mask" and key not in examples:
                block = np.ones_like(examples["input_ids"][:, t_sl])
            else:
                block = examples[key][:, t_sl]
            if n_pad and key == "attention_mask":
                # Zero the mask of padded rows in this shard. Unsharded dims
                # arrive as slice(None) — default the bounds.
                start = b_sl.start if b_sl.start is not None else 0
                stop = b_sl.stop if b_sl.stop is not None else bs
                row_ids = np.arange(start, stop)[: block.shape[0]]
                block = block.copy()
                block[row_ids >= bs - n_pad] = 0
            return block

        return {
            key: jax.make_array_from_callback(
                (bs, seqlen), sharding, lambda i, k=key: fetch(k, i)
            )
            for key in keys
        }

    def _restored_step_loss(self, sampler: DeterministicSampler, dataset, step: int) -> float:
        """Token-weighted forward loss over the batch of training step ``step``.

        Used when resume lands at/past max_steps, so the summary reports a
        measured loss for the restored parameters instead of a 0.0
        placeholder. Runs the eval step over each accumulation micro-batch
        of the step the checkpoint was saved at.
        """
        accum = self._cfg.trainer.grad_accum_steps
        params = nn_meta.unbox(self._state.params)
        base = (step - 1) * accum
        total_loss = 0.0
        total_tok = 0.0
        for a in range(accum):
            batch = self._eval_batch(dataset, sampler.batch_indices(base + a))
            loss_sum, tokens = self._eval_step_fn(params, batch)
            total_loss += float(jnp.sum(jax.device_get(loss_sum)))
            total_tok += float(jnp.sum(jax.device_get(tokens)))
        return total_loss / max(total_tok, 1.0)

    def _dataset_spec(self, dataset) -> tuple[tuple[str, ...], int]:
        """Cached (batch keys, sequence length) of a dataset."""
        cached = self._dataset_specs.get(id(dataset))
        if cached is None:
            probe = dataset.get_examples(np.asarray([0]))
            cached = (tuple(probe), probe["input_ids"].shape[1])
            self._dataset_specs[id(dataset)] = cached
        return cached

    def evaluate(
        self,
        resume_from: str | None = None,
        *,
        use_ema: bool = False,
        quantize: str | None = None,
    ) -> dict[str, float] | None:
        """Eval-only pass: restore ``resume_from`` (if given) and run the
        full validation loop once, without training.

        New capability over the reference (eval there only happens inside
        the train loop, reference trainer.py:243-289). Returns
        ``{"val/loss": ...}`` (per-shard ``*_rank_{r}`` values go to the
        tracker, as in the train loop), or None when the data module has
        no validation split. The step reported in logs is the restored
        checkpoint's step (0 for a fresh init).

        ``use_ema=True`` evaluates the Polyak shadow tracked by
        ``trainer.extra.ema_decay`` — it already sits in the (restored)
        optimizer state, so this swaps the trainable tree in place, no
        extra checkpoint IO. For LoRA runs the shadow replaces the
        factors; the frozen base stays.

        ``quantize="int8"`` evaluates under weight-only int8
        (ops/quant.py) — the exact serving-path weights, so the reported
        ``val/loss`` IS the quality cost of quantized decode. Composes
        with ``use_ema`` (the shadow is quantized). Like the EMA path it
        is an override: ``self._state`` keeps the full-precision weights.
        """
        if quantize not in (None, "int8"):
            raise ValueError(f"unsupported quantize mode: {quantize!r}")
        step = 0
        if resume_from is not None:
            step = self._restore(resume_from)
        params_override = None
        if use_ema:
            from .optimizer import find_ema_tree

            shadow = find_ema_tree(self._state.opt_state)
            if shadow is None:
                raise ValueError(
                    "no EMA state in the optimizer — train with "
                    "trainer.extra.ema_decay to track shadow weights"
                )
            shadow = nn_meta.unbox(shadow)
            params = nn_meta.unbox(self._state.params)
            is_lora = isinstance(params, dict) and "lora" in params
            target = params["lora"] if is_lora else params
            # Shadow accumulates in f32 (optimizer.py); cast back to the
            # param dtypes the eval forward expects. Passed as an
            # override — self._state stays untouched, so a later fit()
            # or raw evaluate() on this Trainer sees the real weights.
            cast = jax.tree.map(
                lambda p, e: jnp.asarray(e, p.dtype), target, shadow
            )
            params_override = {**params, "lora": cast} if is_lora else cast
        if quantize == "int8":
            from ..ops.quant import quantize_tree

            base = (
                params_override
                if params_override is not None
                else nn_meta.unbox(self._state.params)
            )
            if isinstance(base, dict) and "base" in base and "lora" in base:
                # Serving quantizes the MERGED weights (generate
                # --quantize merges first, models/lora.py). Mirror that
                # exactly: quantize(W + sBA) as the base, factors zeroed
                # so the training model's in-step merge adds nothing —
                # quantize(W) + sBA would measure a different model.
                from ..models.lora import to_inference_params

                merged = nn_meta.unbox(
                    to_inference_params(self._adapter, base)
                )
                params_override = {
                    "base": quantize_tree(merged),
                    "lora": jax.tree.map(jnp.zeros_like, base["lora"]),
                }
            else:
                params_override = quantize_tree(base)
        try:
            with self._mesh, nn.logical_axis_rules(self._rules):
                return self._evaluate(step, step, params_override)
        finally:
            self._close_eval_pool()
            self._telemetry.close()

    # ------------------------------------------------------------------ fit

    def fit(
        self, max_steps_override: int | None = None, resume_from: str | None = None
    ) -> TrainResult:
        cfg = self._cfg
        max_steps = max_steps_override or cfg.trainer.max_steps
        accum = cfg.trainer.grad_accum_steps
        log_every = cfg.trainer.log_every_steps
        eval_every = cfg.trainer.eval_every_steps
        save_every = cfg.trainer.save_every_steps

        train_ds = self._data_module.train_dataset()
        sampler = DeterministicSampler(
            num_examples=len(train_ds),
            batch_size=self._global_micro,
            seed=cfg.run.seed,
            shuffle=not cfg.run.deterministic,
        )
        # Checkpoint manifests record the sampler's progress block
        # (_manifest_extra) so elastic resume can recompute offsets.
        self._sampler = sampler

        res_cfg = self._resilience
        multi_process = (
            self._dist_state is not None and self._dist_state.num_processes > 1
        )
        self._spike_detector = (
            LossSpikeDetector(
                factor=res_cfg.spike_factor,
                beta=res_cfg.spike_ewma_beta,
                min_history=res_cfg.spike_min_history,
            )
            if res_cfg.spike_detection
            else None
        )
        if self._spike_detector is not None and multi_process:
            # Rollback restores the SAME checkpoint file on every rank via
            # a consensus all-gather (see _maybe_rollback); a rank that
            # cannot even resolve the checkpoint dir would desync the
            # collective the moment a spike fires. The CLI hands every rank
            # the shared run-dir path (reads only; writes stay rank-0
            # gated) — direct embedders must do the same. The missing-
            # manager flag is itself all-gathered so EVERY rank raises
            # together: a local-only raise would leave the other ranks
            # wedged in their first collective until the distributed
            # timeout — the exact opaque hang this check exists to avoid.
            from ..distributed import allgather_any

            if allgather_any(self._ckpt_mgr is None):
                raise ValueError(
                    "multi-process spike rollback requires every rank to "
                    "see the shared run directory (checkpoints volume); "
                    "construct the Trainer with the run-dir path on all "
                    "ranks or disable resilience.spike_detection"
                )
        self._rollback_count = 0
        self._data_offset = 0
        self._resume_count = 0

        # Hang watchdog + heartbeat + straggler telemetry (resilience/
        # watchdog.py, docs/robustness.md). The beacon records progress at
        # each dispatched step; the watchdog hard-exits with the retryable
        # EXIT_HANG_DETECTED when nothing lands within the stall timeout.
        wd_cfg = res_cfg.watchdog
        self._beacon = None
        watchdog: HangWatchdog | None = None
        if wd_cfg.enabled:
            hb_path = wd_cfg.heartbeat_path
            if hb_path is None and self._run_dir is not None:
                # Default lands in the run dir — which multi-process runs
                # SHARE, so non-main ranks get a per-rank suffix: one file
                # for all ranks would let a healthy rank's touches mask a
                # hung one from any external freshness check. An explicit
                # heartbeat_path is honored verbatim (the k8s probes stat
                # a container-LOCAL path, so sharing cannot happen there).
                name = "heartbeat"
                if multi_process and not self._is_main:
                    name = f"heartbeat.r{self._dist_state.process_index}"
                hb_path = str(Path(self._run_dir) / name)
            self._beacon = ProgressBeacon(
                hb_path, heartbeat_interval_sec=wd_cfg.heartbeat_interval_sec
            )
            import tempfile

            report_dir = (
                Path(self._run_dir)
                if self._run_dir is not None
                else Path(tempfile.gettempdir())
            )
            watchdog = HangWatchdog(
                self._beacon,
                stall_timeout_sec=wd_cfg.stall_timeout_sec,
                poll_interval_sec=wd_cfg.poll_interval_sec,
                report_dir=report_dir,
                process_index=(
                    self._dist_state.process_index if self._dist_state else 0
                ),
                # Before the hard exit: stamp the hang on the timeline
                # (flushed so the JSONL survives os._exit), then drain-or-
                # abandon the in-flight async checkpoint write with a
                # bounded wait — never block the watchdog behind a write
                # wedged on the same dead storage that caused the hang.
                on_hang=self._on_watchdog_hang,
                # Direct last-ditch flush on the exit-76 path itself: the
                # on_hang hook above can be abandoned with the bounded
                # worker when the checkpoint drain wedges, and the goodput
                # ledger needs the buffered events to attribute the hang.
                timeline=self._telemetry.timeline,
            )
        self._straggler = (
            StragglerTracker(
                skew_factor=wd_cfg.straggler_skew_factor,
                patience=wd_cfg.straggler_patience,
            )
            if multi_process and wd_cfg.straggler_telemetry
            else None
        )

        resumed_from_step: int | None = None
        if resume_from is not None:
            # validate_topology: the fit path owns the identical-trajectory
            # contract, so a topology change is checked against the
            # checkpoint's manifest here — elastic (batch axes) re-shards,
            # incompatible (tensor/pipeline/global-batch) aborts with
            # TopologyMismatchError -> exit 2.
            resumed_from_step = self._restore(resume_from, validate_topology=True)
            # Rollback/sampler bookkeeping and the spike detector's trend
            # continue exactly where the checkpointed run left them.
            resil = self._last_restored_resilience
            self._rollback_count = int(resil.get("rollback_count", 0))
            manifest_data = (self._last_restored_manifest or {}).get("data") or {}
            if "consumed_micro_batches" in manifest_data:
                # The manifest's recorded global-batch progress is the
                # authoritative stream position — elastic resume re-derives
                # sampler offsets from it on ANY world size (the saving run
                # wrote consumed = step·accum + data_offset, so this agrees
                # with the payload bookkeeping when both exist).
                self._data_offset = resume_batch_index(
                    manifest_data, step=resumed_from_step, grad_accum_steps=accum
                ) - resumed_from_step * accum
            else:
                # Synthesized/pre-manifest commit: no progress record, fall
                # back to the payload's rollback-advanced offset (0 for
                # pre-resilience checkpoints — pure step math).
                self._data_offset = int(resil.get("data_offset", 0))
            self._resume_count = int(resil.get("resume_count", 0)) + 1
            self._telemetry.metrics.inc("resilience/resumes")
            self._telemetry.metrics.publish(
                {"resilience/resume_count": float(self._resume_count)},
                step=resumed_from_step,
            )
            if self._spike_detector is not None:
                self._spike_detector.load_state(resil)
        start_step = (resumed_from_step or 0) + 1
        if start_step > max_steps:
            logger.warning(
                "resume step %d >= max_steps %d; no training steps will run",
                start_step - 1,
                max_steps,
            )

        base_run_key = jax.random.key(cfg.run.seed)
        run_key = self._active_run_key(base_run_key)
        # Per-step host throttle (trainer.extra.step_delay_sec): an
        # emulation/testing knob that stretches wall-clock without touching
        # the math — fleet preemption drills use it so externally delivered
        # evictions reliably land while a tiny smoke model is mid-run.
        step_delay = float(cfg.trainer.extra.get("step_delay_sec", 0.0) or 0.0)
        self._train_seqlen = self._probe_seqlen(train_ds)
        tokens_per_step = accum * self._global_micro * self._train_seqlen
        # Cost-attribution inputs (telemetry/profiling.py): the hook at
        # end of fit lowers the jitted step against these abstract shapes.
        self._train_batch_keys = self._dataset_spec(train_ds)[0]
        self._tokens_per_step = tokens_per_step
        profiler = _StepProfiler(
            cfg,
            self._run_dir,
            process_index=(
                self._dist_state.process_index if self._dist_state else 0
            ),
            num_processes=(
                self._dist_state.num_processes if self._dist_state else 1
            ),
            timeline=self._telemetry.timeline,
        )
        # Fired fault injections land on the event timeline so chaos
        # drills are auditable from the trace alone.
        tl = self._telemetry.timeline
        self._faults.observer = lambda kind, at_step: (
            tl.instant(f"fault_{kind}", cat="fault", step=at_step),
            self._telemetry.metrics.inc("faults/injected"),
        )
        self._telemetry.start()
        # Optimizer-state footprint (docs/perf.md "Sharded optimizer
        # state"): static for the whole fit, recorded once so the ZeRO
        # memory win is a measured number in report.json/metrics, not a
        # claim. Recorded after a resume's reshard too (fit restores
        # above), so the bytes describe the state actually training.
        opt_mem = self._opt_state_memory()
        self._telemetry.record_opt_state_bytes(opt_mem)
        logger.info(
            "optimizer state: %.1f MiB total, %.1f MiB on device 0, "
            "%.1f MiB host-resident",
            opt_mem["opt_state_bytes"] / 2**20,
            opt_mem["opt_state_bytes_per_device"] / 2**20,
            opt_mem["opt_state_bytes_host"] / 2**20,
        )
        # Activation footprint under the activation-tier ladder: like the
        # opt-state block, static for the whole fit — the analytic number
        # `llmtrain plan` feasibility-checks against, recorded so the
        # tiering/offload win is visible in report.json and as mem/*
        # gauges (docs/perf.md "Activation tiers and host offload").
        act_mem = self._activation_memory()
        if act_mem is not None:
            self._telemetry.record_activation_bytes(act_mem)
            logger.info(
                "activations (analytic): %.1f MiB on-device, %.1f MiB "
                "host-offloaded per device",
                act_mem["activation_bytes"] / 2**20,
                act_mem["activation_bytes_offloaded"] / 2**20,
            )

        self._telemetry.metrics.safe_log_params(cfg.model_dump())

        first_step_loss: float | None = None
        final_val_loss: float | None = None
        final_val_metrics: dict[str, float] | None = None
        step_loss_dev = None
        total_tokens = (start_step - 1) * tokens_per_step

        interval_losses: list[jax.Array] = []
        interval_shard: list[tuple[jax.Array, jax.Array]] = []
        interval_tokens = 0
        # Input-pipeline health (docs/perf.md): time the consumer spent
        # blocked waiting for a batch, and host time spent inside the
        # dispatch call. With a healthy prefetch pipeline data_wait ~ 0
        # and dispatch is the only host cost left on the critical path.
        interval_data_wait = 0.0
        interval_dispatch = 0.0
        interval_start = time.perf_counter()
        start_time = time.perf_counter()

        # Async input pipeline (data/prefetch.py): a daemon thread runs the
        # deterministic index math ahead of the loop and keeps up to
        # prefetch_depth fully-formed global device batches queued, so host
        # assembly + H2D overlap the previous step's compute. depth 0 keeps
        # the synchronous path (identical batches either way — the
        # prefetcher changes when they are built, never what is built).
        prefetcher: BatchPrefetcher | None = None
        if cfg.trainer.prefetch_depth > 0 and start_step <= max_steps:
            prefetcher = BatchPrefetcher(
                lambda s: self._global_batch(sampler, train_ds, s),
                depth=cfg.trainer.prefetch_depth,
                start_step=start_step,
                before_assemble=(
                    lambda s: self._faults.maybe_hang(s, site="prefetcher")
                ),
                timeline=self._telemetry.timeline,
            )

        # Preemption-safe checkpointing (the k8s spot/maintenance story,
        # docs/k8s.md): SIGTERM sets a flag; the loop saves a durable
        # checkpoint and returns cleanly (exit 0) inside the pod's
        # termination grace period, so `train --resume`/`--auto-resume`
        # continues exactly where the evicted pod stopped. Single-process
        # runs honor the flag at every step. Multi-process runs decide at
        # the log-interval boundary via an ALL-GATHER of the local flags:
        # OS signal delivery gives no cross-rank timing guarantee, so
        # without the consensus a rank whose signal landed just before
        # its boundary check would break into the collective host-gather
        # while another rank ran step N+1's collectives — a deadlock the
        # grace period would turn into a SIGKILL with no checkpoint. The
        # boundary already syncs on the interval's last loss, so the
        # one-byte collective costs nothing extra.
        preempted = False
        # Distinct sentinel, not `old_term is None`: signal.signal()
        # legitimately returns None when the previous handler was
        # installed by C code, and that handler must be restored too.
        handler_installed = False
        old_term = None

        def _on_sigterm(signum, frame):  # pragma: no cover - exercised via kill
            nonlocal preempted
            preempted = True

        if threading.current_thread() is threading.main_thread():
            old_term = signal.signal(signal.SIGTERM, _on_sigterm)
            handler_installed = True
        else:
            # signal.signal only works on the main thread. Embedding the
            # trainer in a worker thread therefore silently loses the
            # checkpoint-on-eviction path — make that loudly visible
            # instead of discovering it at the first preemption.
            logger.warning(
                "Trainer.fit is running off the main thread: SIGTERM "
                "preemption handling is DISABLED for this run (no "
                "checkpoint-on-eviction; the process default handler "
                "applies)"
            )

        past_end_loss: float | None = None
        final_step_override: int | None = None
        loop_completed = False
        try:
            with self._mesh, nn.logical_axis_rules(self._rules):
                if start_step > max_steps and resumed_from_step:
                    # Resume landed at/past max_steps: the loop body never
                    # runs, so measure a real loss for the restored state
                    # instead of reporting 0.0.
                    past_end_loss = self._restored_step_loss(
                        sampler, train_ds, resumed_from_step
                    )
                nonfinite_dev = None
                step = start_step - 1
                while step < max_steps:
                    step += 1
                    profiler.maybe_start(step)
                    # data_wait: consumer blocked on the queue (prefetch) or
                    # the full synchronous assembly (depth 0) — either way,
                    # host time the device queue could not hide. The SAME
                    # three clock reads feed the interval accumulators and
                    # the timeline (tl.record), so the span record and the
                    # train/data_wait_ms family can never drift apart; the
                    # StepTraceAnnotation aligns the dispatch with xprof.
                    t_fetch = time.perf_counter()
                    if prefetcher is not None:
                        batch = prefetcher.get(step)
                    else:
                        batch = self._global_batch(sampler, train_ds, step)
                    t_dispatch = time.perf_counter()
                    if self._batch_struct is None:
                        # Abstract shapes of the real global batch, captured
                        # once: the cost-attribution hook re-lowers the
                        # jitted step against exactly these at end of fit.
                        self._batch_struct = jax.tree.map(
                            lambda x: jax.ShapeDtypeStruct(
                                x.shape,
                                x.dtype,
                                sharding=getattr(x, "sharding", None),
                            ),
                            batch,
                        )
                    with self._telemetry.step_annotation(step):
                        self._state, metrics = self._train_step_fn(
                            self._state, batch, run_key
                        )
                    t_done = time.perf_counter()
                    interval_data_wait += t_dispatch - t_fetch
                    interval_dispatch += t_done - t_dispatch
                    tl.record(
                        "data_wait", cat="data", step=step, t0=t_fetch, t1=t_dispatch
                    )
                    tl.record("host_dispatch", step=step, t0=t_dispatch, t1=t_done)
                    profiler.maybe_stop(step, sync=metrics["loss"])
                    if self._beacon is not None:
                        # Progress = the step DISPATCHED. A hung device
                        # backpressures the host within a step or two (the
                        # dispatch queue is bounded and log boundaries
                        # block on device_get), so host-side dispatch time
                        # is a faithful liveness signal for both host and
                        # device stalls. The watchdog arms at the FIRST
                        # dispatched step, so the (minutes-long on a pod
                        # slice) first-step compile never counts against
                        # the stall timeout — init-time wedges belong to
                        # the rendezvous timeout and the k8s probe, not to
                        # the step-progress watchdog.
                        self._beacon.touch(step)
                        if watchdog is not None:
                            watchdog.arm()  # no-op once armed
                    # Injected preemption goes through the real OS signal
                    # path, so everything below sees a genuine SIGTERM.
                    self._faults.maybe_sigterm(step)
                    # Injected crash: SIGKILL, nothing below ever runs —
                    # recovery is entirely the atomic commit protocol's
                    # problem (chaos harness territory).
                    self._faults.maybe_kill(step)
                    # Injected hang BLOCKS here for real — the beacon is
                    # stranded at this step and the watchdog must end the
                    # process (tests/test_watchdog.py, end to end).
                    self._faults.maybe_hang(step)
                    if step_delay > 0.0:
                        time.sleep(step_delay)

                    step_loss_dev = metrics["loss"]
                    nonfinite_dev = metrics.get("nonfinite_count")
                    interval_losses.append(metrics["loss"])
                    interval_shard.append(
                        (metrics["per_example_loss_sum"], metrics["per_example_tokens"])
                    )
                    interval_tokens += tokens_per_step
                    total_tokens += tokens_per_step

                    if step == 1:
                        first_step_loss = float(jax.device_get(metrics["loss"]))

                    if multi_process and step % log_every == 0:
                        from ..distributed import allgather_any

                        stop_now = allgather_any(preempted)
                    else:
                        stop_now = preempted and not multi_process
                    # A signal during the very last step changes nothing:
                    # the run is completing anyway — let the normal
                    # save/log/eval tail report an un-preempted result.
                    stop_now = stop_now and step < max_steps
                    if step % save_every == 0 or step == max_steps or stop_now:
                        self._save_checkpoint(step)
                        # Injection on the WRITING rank only: non-main ranks
                        # now hold read-side managers over the same shared
                        # dir, and two ranks XOR-garbling the same bytes
                        # would un-corrupt the file (and their wait_pending
                        # is a no-op against rank 0's in-flight write).
                        self._faults.maybe_corrupt_checkpoint(
                            step, self._ckpt_mgr if self._is_main else None
                        )

                    if stop_now:
                        tl.instant(
                            "preempted",
                            cat="resilience",
                            step=step,
                            checkpointed=self._ckpt_mgr is not None,
                        )
                        # Flush NOW, not at the unwind: the pod's grace
                        # period can expire (SIGKILL) anywhere between here
                        # and the finally block, and the preemption instant
                        # plus the interval's buffered step spans are what
                        # the goodput ledger attributes the eviction from.
                        tl.flush()
                        if self._ckpt_mgr is not None and self._is_main:
                            logger.warning(
                                "SIGTERM received: preemption checkpoint "
                                "saved at step %d; stopping cleanly (resume "
                                "with --resume)",
                                step,
                            )
                        elif self._ckpt_mgr is not None:
                            # Non-main rank with a (read-side) manager: the
                            # save happened on the main rank only.
                            logger.warning(
                                "SIGTERM received: stopping cleanly at step "
                                "%d (preemption checkpoint written by the "
                                "main rank)",
                                step,
                            )
                        else:
                            logger.warning(
                                "SIGTERM received: stopping cleanly at step "
                                "%d WITHOUT a checkpoint (no run dir / "
                                "checkpoint manager on this process)",
                                step,
                            )
                        final_step_override = step
                        break

                    if step % log_every == 0 or step == max_steps:
                        # Steps dispatch asynchronously; sync on the
                        # interval's last loss BEFORE stamping the end time
                        # so queued execution is charged to this interval.
                        # Without this, step_time measures dispatch only and
                        # tokens_per_sec/mfu are nonsense. (device_get, not
                        # block_until_ready: on remote-tunnel platforms the
                        # latter can return before execution finishes.)
                        with tl.span("interval_sync", step=step):
                            losses_host = np.asarray(
                                jax.device_get(jnp.stack(interval_losses))
                            )
                        first_interval_step = step - len(interval_losses) + 1
                        losses_host = self._faults.poison_host_losses(
                            losses_host, first_interval_step
                        )
                        self._check_nonfinite_guard(nonfinite_dev, losses_host, step)
                        rolled_back_to = self._maybe_rollback(
                            losses_host, first_interval_step, step
                        )
                        if rolled_back_to is not None:
                            # Timeline bookkeeping BEFORE the interval state
                            # resets: events of the replayed window are
                            # tagged rolled_back (not dropped — the
                            # post-mortem needs to see what the poisoned
                            # window did) and the rollback itself is an
                            # instant event. Both land ahead of the next
                            # flush, so the JSONL carries the tags.
                            tl.tag_rollback(rolled_back_to + 1, step)
                            tl.instant(
                                "rollback",
                                cat="resilience",
                                step=step,
                                restored_step=rolled_back_to,
                                rollback_count=self._rollback_count,
                            )
                            self._telemetry.metrics.inc("resilience/rollbacks")
                            # Replay from the restored step with the sampler
                            # advanced past the bad window and a fresh
                            # rollback-folded RNG stream. Rewind the token
                            # odometer so it stays consistent with what a
                            # resume from the restored step would report.
                            total_tokens -= (step - rolled_back_to) * tokens_per_step
                            run_key = self._active_run_key(base_run_key)
                            interval_losses = []
                            interval_shard = []
                            interval_tokens = 0
                            interval_data_wait = 0.0
                            interval_dispatch = 0.0
                            interval_start = time.perf_counter()
                            step_loss_dev = None
                            nonfinite_dev = None
                            step = rolled_back_to
                            if prefetcher is not None:
                                # Everything queued (or mid-assembly) was
                                # built under the pre-rollback data offset:
                                # invalidate it and restart the producer at
                                # the first replayed step, which now reads
                                # the advanced offset — the replay consumes
                                # the batches FOLLOWING the bad window,
                                # exactly as the synchronous path would.
                                prefetcher.reseek(step + 1)
                            continue
                        interval_time = time.perf_counter() - interval_start
                        if prefetcher is not None:
                            # Pipeline health gauge: a persistently empty
                            # queue under nonzero data_wait means assembly
                            # cannot keep up with the device.
                            self._telemetry.metrics.publish(
                                {
                                    "data/prefetch_queue_depth": float(
                                        prefetcher.queue_depth
                                    )
                                },
                                step,
                            )
                        self._log_train_interval(
                            step=step,
                            max_steps=max_steps,
                            losses_host=losses_host,
                            interval_shard=interval_shard,
                            interval_tokens=interval_tokens,
                            interval_time=interval_time,
                            total_tokens=total_tokens,
                            interval_data_wait=interval_data_wait,
                            interval_dispatch=interval_dispatch,
                        )
                        interval_losses = []
                        interval_shard = []
                        interval_tokens = 0
                        interval_data_wait = 0.0
                        interval_dispatch = 0.0
                        interval_start = time.perf_counter()

                    if step % eval_every == 0 or step == max_steps:
                        with tl.span("eval", cat="eval", step=step):
                            val_metrics = self._evaluate(step, max_steps)
                        if val_metrics:
                            final_val_metrics = val_metrics
                            final_val_loss = val_metrics.get("val/loss", final_val_loss)
            loop_completed = True
        finally:
            if prefetcher is not None:
                # Poisoned-shutdown path: SIGTERM preemption or an unwinding
                # exception can leave the queue full and the producer blocked
                # in put (or wedged inside a hung fetch). close() drains the
                # queue so a healthy producer unblocks and exits, and
                # abandons a wedged one after a bounded join — the same
                # never-deadlock-the-exit stance as the checkpoint drain.
                prefetcher.close()
            # The interval evals' shared worker is fit-scoped: release it
            # so repeated Trainer constructions don't accumulate idle
            # non-daemon threads.
            self._close_eval_pool()
            self._faults.observer = None
            # Transport teardown only (endpoint + a timeline flush so crash
            # evidence persists); the report/trace finalize runs after the
            # result is known, below.
            self._telemetry.close()
            if watchdog is not None:
                watchdog.disarm()
            if handler_installed:
                # old_term None = the previous handler was installed by C
                # code; Python cannot re-install it, but SIG_DFL at least
                # keeps SIGTERM lethal instead of latched into our dead
                # closure.
                signal.signal(
                    signal.SIGTERM,
                    old_term if old_term is not None else signal.SIG_DFL,
                )
            profiler.close(sync=step_loss_dev)
            if self._ckpt_mgr is not None:
                # Final save must be durable. When an exception is unwinding
                # out of the loop, log a write failure instead of masking it.
                # (An explicit flag, not sys.exc_info(): the latter also sees
                # exceptions being handled further up the call stack.)
                if loop_completed:
                    self._ckpt_mgr.close()
                else:
                    try:
                        # Bounded drain on the abort path: a write wedged on
                        # dead storage must not deadlock the exit that is
                        # already unwinding an exception (the timeout
                        # abandons it with an error log).
                        self._ckpt_mgr.close(timeout=_ABORT_DRAIN_TIMEOUT_SEC)
                    except Exception as ckpt_exc:  # noqa: BLE001
                        logger.error(
                            "async checkpoint write failed during unwind: %s", ckpt_exc
                        )
        total_time = time.perf_counter() - start_time
        final_loss = float(jax.device_get(step_loss_dev)) if step_loss_dev is not None else 0.0
        final_step = final_step_override or max_steps
        if start_step > max_steps:
            # No steps ran: report the restored step and its measured loss
            # rather than pretending training reached max_steps.
            final_step = resumed_from_step or 0
            if past_end_loss is not None:
                final_loss = past_end_loss

        result = TrainResult(
            final_step=final_step,
            final_loss=final_loss,
            final_val_loss=final_val_loss,
            total_time=total_time,
            peak_memory=self._peak_memory_bytes(),
            val_metrics=final_val_metrics,
            first_step_loss=first_step_loss,
            resumed_from_step=resumed_from_step,
            parameter_count=self._param_count,
            trainable_parameter_count=self._trainable_count,
            total_tokens=total_tokens,
            preempted=final_step_override is not None,
            rollbacks=self._rollback_count,
        )
        # End-of-run telemetry: report.json/report.md + Perfetto trace in
        # the run dir, then register them (plus profiler traces and any
        # hang reports) as tracker artifacts. Best-effort by construction;
        # the guard here is only against surprises in the result dict.
        try:
            perf_attribution = self._build_perf_attribution(
                run_key, steps=max(0, final_step - start_step + 1)
            )
            self._telemetry.finalize(
                train_result=asdict(result),
                run_id=self._run_dir.name if self._run_dir is not None else None,
                perf_attribution=perf_attribution,
                precision=self._precision_block(),
            )
            self._telemetry.register_artifacts()
        except Exception as exc:  # noqa: BLE001 — reporting must not fail the run
            logger.warning("telemetry finalize failed: %s", exc)
        return result

    def _precision_block(self) -> dict[str, Any]:
        """Numerics provenance for report.json: the EFFECTIVE values the
        model compiled with (post auto-selection / capability fallback),
        read off the built module — not the raw config keys."""
        return {
            "dtype": str(self._cfg.model.dtype),
            "param_dtype": str(self._cfg.model.param_dtype),
            "loss_impl": getattr(self._model, "loss_impl", "dense"),
            "matmul_precision": getattr(self._model, "matmul_precision", "f32"),
        }

    def _probe_seqlen(self, dataset) -> int:
        return self._dataset_spec(dataset)[1]

    def _build_perf_attribution(
        self, run_key: jax.Array, *, steps: int
    ) -> dict[str, Any] | None:
        """Cost-attribution block for report.json (telemetry/profiling.py).

        Re-lowers the raw jitted step (trace only — NO XLA compile, nothing
        executes, donated buffers stay live) against the batch shapes the
        fit actually dispatched, reads XLA's cost_analysis, and classifies
        the step on the device roofline. Publishes the ``perf/*`` gauges
        as a side effect. Returns None when gated off, when no step ran,
        or on any backend failure — attribution is optional, the run is
        not.
        """
        tcfg = self._cfg.telemetry
        if not (tcfg.enabled and tcfg.report and tcfg.perf_attribution):
            return None
        # Attribution exists for the report; without a run dir no
        # report.json is written, so the extra trace+lower buys nothing.
        if self._run_dir is None:
            return None
        if self._batch_struct is None or steps <= 0:
            return None
        try:
            from ..telemetry import profiling

            cost = profiling.lower_cost_profile(
                self._jit_train_step,
                (self._state, self._batch_struct, run_key),
                name="train_step",
                n_chips=int(self._mesh.devices.size),
            )
            if cost is None:
                return None
            peaks = profiling.resolve_peaks(None, tcfg.device_peaks)
            # Gradient-sync estimate: ring all-reduce of the trainable
            # grads (f32 accumulation) over the combined data-parallel
            # degree. An estimate, labeled as such in the docs — XLA's
            # cost_analysis does not expose collective bytes at this tier.
            collective = profiling.gradient_collective_bytes(
                mesh_axis_sizes(self._mesh), float(self._trainable_count) * 4.0
            )
            latest = {k: v[0] for k, v in self._telemetry.metrics.latest().items()}
            step_time_sec = latest.get("train/step_time_sec") or 0.0
            palm = transformer_flops_per_token(
                n_params=self._param_count,
                n_layers=self._cfg.model.n_layers,
                seq_len=self._train_seqlen,
                d_model=self._cfg.model.d_model,
                n_trainable_params=self._trainable_count,
            )
            block = profiling.build_perf_attribution(
                executables=[cost],
                peaks=peaks,
                n_chips=int(self._mesh.devices.size),
                step_time_ms=step_time_sec * 1e3 if step_time_sec > 0 else None,
                tokens_per_step=float(self._tokens_per_step) or None,
                palm_flops_per_token=palm,
                measured_mfu=latest.get("train/mfu"),
                collective_bytes=collective,
                span_totals=self._telemetry.timeline.span_totals(),
                steps=steps,
            )
            self._telemetry.metrics.publish(profiling.attribution_gauges(block))
            return block
        except Exception as exc:  # noqa: BLE001 — attribution must not fail the run
            logger.warning("perf attribution skipped: %s", exc)
            return None

    def _close_eval_pool(self) -> None:
        """Release the shared eval-data executor (idle at call time: every
        submitted build was consumed by the eval loop that submitted it)."""
        if self._eval_pool is not None:
            self._eval_pool.shutdown(wait=True)
            self._eval_pool = None

    # ------------------------------------------------------------ resilience

    def _active_run_key(self, base_run_key: jax.Array) -> jax.Array:
        """The RNG key the train step folds per-step keys from.

        With zero rollbacks this is exactly the seed key (bit-compatible
        with pre-resilience runs); each rollback folds the rollback count in
        so replayed steps draw fresh dropout streams alongside their fresh
        batches."""
        if self._rollback_count == 0:
            return base_run_key
        return jax.random.fold_in(base_run_key, self._rollback_count)

    def _check_nonfinite_guard(
        self, nonfinite_dev, losses_host: np.ndarray, step: int
    ) -> None:
        """Boundary-cadence guard bookkeeping: warn about skipped updates in
        the interval, abort once the consecutive-skip cap is crossed.

        Runs where the losses already synced to host, so it adds no device
        round-trips beyond the scalar counter."""
        if nonfinite_dev is None:
            return
        consecutive = int(jax.device_get(nonfinite_dev))
        # Non-finite host losses catch mid-interval skips; the device
        # counter catches the finite-loss/non-finite-grads case (bf16
        # backward overflow) the loss vector cannot see.
        skipped = max(
            int(np.count_nonzero(~np.isfinite(losses_host))),
            min(consecutive, len(losses_host)),
        )
        if skipped:
            logger.warning(
                "non-finite loss/grads: %d optimizer update(s) skipped by the "
                "guard in the last %d step(s)",
                skipped,
                len(losses_host),
            )
            self._telemetry.metrics.inc("resilience/nonfinite_skips", skipped)
            self._telemetry.timeline.instant(
                "nonfinite_skip", cat="resilience", step=step, skipped=skipped
            )
        cap = self._resilience.max_consecutive_nonfinite
        if consecutive >= cap:
            raise NonFiniteLossError(
                f"aborting at step {step}: {consecutive} consecutive optimizer "
                f"updates were non-finite (cap {cap}) — the run has diverged; "
                "params/opt_state are untouched since the last finite step and "
                "the newest checkpoint remains restorable"
            )

    def _maybe_rollback(
        self, losses_host: np.ndarray, first_interval_step: int, step: int
    ) -> int | None:
        """Feed the interval's losses to the spike detector; on a spike,
        restore the newest verified checkpoint saved BEFORE the spiking step
        and advance the data stream past the consumed window.

        Returns the restored step (the loop replays from there), or None.
        """
        detector = self._spike_detector
        if detector is None:
            return None
        spike_step = None
        spike_loss = trend = None
        for i, value in enumerate(np.asarray(losses_host)):
            if detector.observe(float(value)):
                spike_step = first_interval_step + i
                spike_loss, trend = float(value), detector.trend
                break
        multi_process = (
            self._dist_state is not None and self._dist_state.num_processes > 1
        )
        if multi_process:
            # Consensus: ANY rank's spike rolls back EVERY rank. Losses are
            # replicated (out_shardings), so ranks normally agree already —
            # the all-gather removes the numeric edge cases where they
            # don't, which would otherwise desync the next collective. The
            # earliest flagged step wins so the restore point predates all
            # local views of the spike. This collective runs at every log
            # boundary the detector is active for, on every rank — the
            # boundary already syncs on host losses, so it's noise.
            from ..distributed import allgather_scalar

            views = allgather_scalar(
                float(spike_step) if spike_step is not None else -1.0
            )
            flagged = [int(v) for v in views if v >= 0]
            consensus_step = min(flagged) if flagged else None
            if consensus_step is not None and spike_step is None:
                logger.warning(
                    "loss spike at step %d flagged by another rank; joining "
                    "the consensus rollback",
                    consensus_step,
                )
            spike_step = consensus_step
        if spike_step is None:
            return None
        if spike_loss is None:
            # Consensus-joined rank: the spiking loss was another rank's
            # observation; log NaN rather than faking a local value.
            spike_loss = float("nan")
            if trend is None:
                trend = (
                    detector.trend if detector.trend is not None else float("nan")
                )
        if multi_process and self._ckpt_mgr is None:
            # fit() validates this up front; reaching it means the manager
            # vanished mid-run — desyncing the consensus would hang every
            # rank, so fail loudly instead.
            raise RuntimeError(
                "consensus spike rollback needs a checkpoint manager on "
                "every rank but this rank has none"
            )
        if self._ckpt_mgr is None:
            logger.error(
                "loss spike at step %d (%.4f vs trend %.4f) but no checkpoint "
                "manager on this process; spike rollback disabled for the "
                "rest of the run",
                spike_step,
                spike_loss,
                trend or 0.0,
            )
            self._spike_detector = None
            return None
        if self._rollback_count >= self._resilience.max_rollbacks:
            raise RollbackBudgetExceededError(
                f"loss spike at step {spike_step} ({spike_loss:.4f} vs trend "
                f"{trend:.4f}) after exhausting the rollback budget "
                f"({self._resilience.max_rollbacks}) — the run diverges "
                "deterministically; change the config instead of retrying"
            )
        # The rollback target must PREDATE the spike: a periodic save can
        # land inside a spiking interval, and that checkpoint — valid by
        # integrity, poisoned by value — must not become the restore point.
        with self._telemetry.timeline.span("checkpoint_wait", cat="ckpt", step=step):
            self._ckpt_mgr.wait_pending()
        if multi_process:
            # Rank 0 owns the target decision (its manager did the writes);
            # broadcasting the STEP — not each rank scanning the shared dir
            # independently — removes any filesystem-visibility race from
            # the agreement. Every rank then restores the same file.
            from ..distributed import broadcast_int_from_main

            target_step = -1
            if self._is_main:
                picked = self._ckpt_mgr.latest_valid_checkpoint(
                    before_step=spike_step
                )
                if picked is not None:
                    target_step = int(picked.stem.split("_")[1])
            target_step = broadcast_int_from_main(target_step)
            target = (
                self._ckpt_mgr.directory / f"step_{target_step:06d}.ckpt"
                if target_step >= 0
                else None
            )
            if target is not None and not self._is_main:
                # The broadcast removes the AGREEMENT race, not the READ
                # race: rank 0 verified the file in its own filesystem
                # view, but a shared-FS attribute cache (NFS acdirmax) can
                # lag on other ranks. Poll briefly before restoring —
                # crashing here would strand every other rank in the
                # restore collective until the distributed timeout.
                deadline = time.monotonic() + 60.0
                while not target.is_file() and time.monotonic() < deadline:
                    time.sleep(0.5)
                if not target.is_file():
                    raise RuntimeError(
                        f"rollback target {target} (broadcast by rank 0) "
                        "never became visible on this rank's filesystem "
                        "view — shared runs volume misconfigured?"
                    )
        else:
            target = self._ckpt_mgr.latest_valid_checkpoint(before_step=spike_step)
        if target is None:
            # Early spike, before the first periodic save: nothing to
            # restore, so train through it (same stance as the
            # no-checkpoint-manager path above — a missing restore point
            # must not kill a run that would otherwise continue).
            logger.warning(
                "loss spike at step %d (%.4f vs trend %.4f) but no verified "
                "checkpoint predates it; continuing without rollback",
                spike_step,
                spike_loss,
                trend or 0.0,
            )
            return None
        with self._telemetry.timeline.span(
            "rollback_restore", cat="resilience", step=step
        ):
            restored_step = self._restore(str(target))
        accum = self._cfg.trainer.grad_accum_steps
        # Accumulate onto the LIVE offset, not the checkpoint's stored one:
        # a second rollback landing on a checkpoint that predates the first
        # must keep advancing the stream, not rewind onto the
        # already-consumed window.
        self._data_offset += (step - restored_step) * accum
        self._rollback_count += 1
        logger.warning(
            "loss spike at step %d (%.4f vs trend %.4f): rolled back to "
            "checkpoint step %d (rollback %d/%d); sampler advanced %d "
            "micro-batches past the bad window",
            spike_step,
            spike_loss,
            trend or 0.0,
            restored_step,
            self._rollback_count,
            self._resilience.max_rollbacks,
            (step - restored_step) * accum,
        )
        return restored_step

    def _drain_checkpoints_for_abort(self) -> None:
        """Bounded drain of the in-flight async checkpoint write for the
        watchdog's pre-exit hook: give a healthy write a chance to land,
        abandon a wedged one instead of deadlocking the hard exit."""
        if self._ckpt_mgr is not None:
            self._ckpt_mgr.close(timeout=_ABORT_DRAIN_TIMEOUT_SEC)

    def _on_watchdog_hang(self) -> None:
        """Watchdog pre-exit hook: persist the hang on the timeline, then
        drain the checkpoint write. Every part is best-effort — the
        watchdog's bounded fire window outranks completeness."""
        try:
            self._telemetry.timeline.instant("hang_detected", cat="resilience")
            self._telemetry.timeline.flush()
        except Exception:  # noqa: BLE001 — the exit must proceed
            pass
        self._drain_checkpoints_for_abort()

    def _resilience_payload(self) -> dict[str, Any] | None:
        """Small scalar dict saved alongside the state so guard counter,
        rollback bookkeeping, and the spike detector's trend survive
        preemption + resume."""
        out: dict[str, Any] = {}
        if self._state.nonfinite_count is not None:
            out["nonfinite_count"] = int(jax.device_get(self._state.nonfinite_count))
        if self._rollback_count:
            out["rollback_count"] = self._rollback_count
        if self._data_offset:
            out["data_offset"] = self._data_offset
        if self._resume_count:
            out["resume_count"] = self._resume_count
        if self._spike_detector is not None:
            out.update(self._spike_detector.state())
        return out or None

    def _on_checkpoint_commit(self, step: int, manifest: Path) -> None:
        """Commit observer (writer thread): one counter tick + timeline
        instant per PUBLISHED manifest — saves that died mid-write never
        count, which is exactly what makes the metric trustworthy."""
        self._telemetry.metrics.inc("checkpoint/commits")
        self._telemetry.timeline.instant("checkpoint_commit", cat="ckpt", step=step)

    def _current_topology(self) -> dict[str, Any]:
        """This run's topology block — recorded in every manifest, and the
        comparison target when resuming someone else's (elastic.py)."""
        return describe_topology(
            mesh_axis_sizes(self._mesh),
            data_parallel=self._dp,
            global_micro_batch=self._global_micro,
            micro_batch_size=self._cfg.trainer.micro_batch_size,
            grad_accum_steps=self._cfg.trainer.grad_accum_steps,
            num_processes=(
                self._dist_state.num_processes if self._dist_state else 1
            ),
        )

    def _manifest_extra(self, step: int) -> dict[str, Any]:
        """Topology + sampler/prefetch progress for the step-``step``
        manifest: everything resume needs to validate (or elastically
        re-shard) WITHOUT deserializing the multi-GB payload."""
        accum = self._cfg.trainer.grad_accum_steps
        # The save runs at the END of step `step`: the next global
        # micro-batch the stream will consume is step·accum plus the
        # rollback-advanced offset.
        consumed = step * accum + self._data_offset
        if self._sampler is not None:
            sampler_state = self._sampler.progress(consumed)
        else:
            sampler_state = {
                "seed": int(self._cfg.run.seed),
                "global_micro_batch": int(self._global_micro),
                "consumed_micro_batches": int(consumed),
            }
        data = {
            **sampler_state,
            "data_offset": int(self._data_offset),
            # Prefetch generation state: depth is a pure performance knob
            # (the prefetcher never changes WHAT is built) and the
            # generation counter equals the rollback count — recorded so a
            # resume under any prefetch_depth provably replays the same
            # stream (tests/test_prefetch.py pins bitwise equality).
            "prefetch_depth": int(self._cfg.trainer.prefetch_depth),
            "prefetch_generation": int(self._rollback_count),
        }
        # Segment provenance for the goodput ledger (telemetry/goodput.py):
        # which process lifetime committed this step and when it started —
        # mtime-free ordering for post-hoc recomputed-work derivation. The
        # id comes from the timeline's durable header count, so manifests
        # and timeline segments agree by construction.
        resilience = {
            "segment_id": int(self._telemetry.timeline.segment_id),
            "process_start_unix_time": round(
                self._telemetry.timeline.origin_unix_time, 3
            ),
            "saved_unix_time": round(time.time(), 3),
        }
        return {
            "topology": self._current_topology(),
            "data": data,
            "resilience": resilience,
        }

    def _save_checkpoint(self, step: int) -> None:
        """Host-gather on every process (collective for multi-host sharded
        params), write on the main process only (reference trainer.py:402-406)."""
        multi_process = (
            self._dist_state is not None and self._dist_state.num_processes > 1
        )
        if self._ckpt_mgr is None and not multi_process:
            return
        from .checkpoint import state_to_host

        # The synchronous cost of a save is the device→host gather; the
        # msgpack+IO tail is async. The span measures what the step loop
        # actually pays (telemetry timeline: checkpoint_save).
        with self._telemetry.timeline.span("checkpoint_save", cat="ckpt", step=step):
            host_state = state_to_host(self._state)
            if self._ckpt_mgr is not None and self._is_main:
                # Async: msgpack + disk IO overlap the next steps (the
                # collective device→host gather above already completed
                # synchronously). The manifest extras (topology + sampler
                # progress) make the commit self-describing for elastic
                # resume; inject_kill aims the chaos harness's SIGKILL
                # inside this very write.
                self._ckpt_mgr.save_host_async(
                    step,
                    host_state,
                    self._cfg.model_dump(),
                    resilience=self._resilience_payload(),
                    manifest_extra=self._manifest_extra(step),
                    inject_kill=self._faults.take_checkpoint_kill(step),
                )
                # Counter on the WRITING rank only: a non-main pod's
                # /metrics must not report saves it never performed.
                self._telemetry.metrics.inc("ckpt/saves")

    # ------------------------------------------------------------------ metrics

    def _shard_means(
        self, shard_stats: list[tuple[jax.Array, jax.Array]]
    ) -> np.ndarray:
        """Per-data-shard interval losses: mean over steps+accum of shard means."""
        per_step = []
        for loss_sum, tokens in shard_stats:
            ls = np.asarray(jax.device_get(loss_sum))  # (A, Bg)
            tc = np.asarray(jax.device_get(tokens))
            a, bg = ls.shape
            per = bg // self._dp
            ls = ls.reshape(a, self._dp, per).sum(axis=2)
            tc = tc.reshape(a, self._dp, per).sum(axis=2)
            per_step.append((ls / np.maximum(tc, 1.0)).mean(axis=0))  # (dp,)
        return np.mean(per_step, axis=0)

    def _log_train_interval(
        self,
        *,
        step: int,
        max_steps: int,
        losses_host: np.ndarray,
        interval_shard: list[tuple[jax.Array, jax.Array]],
        interval_tokens: int,
        interval_time: float,
        total_tokens: int,
        interval_data_wait: float = 0.0,
        interval_dispatch: float = 0.0,
    ) -> None:
        if self._ckpt_mgr is not None:
            # Surface a failed async checkpoint write within one log
            # interval instead of at the next save or at close().
            self._ckpt_mgr.poll()
        losses = losses_host
        avg_loss = float(losses.mean())
        steps_in_interval = len(losses)
        avg_step_time = interval_time / steps_in_interval if steps_in_interval else 0.0
        tokens_per_sec = interval_tokens / interval_time if interval_time > 0 else 0.0
        # Host-overlap telemetry (docs/perf.md): per-step mean time the
        # consumer blocked waiting on the input pipeline, and host time
        # inside the dispatch call. Steady-state data_wait near zero means
        # batch assembly + H2D are fully hidden behind device compute.
        data_wait_ms = (
            interval_data_wait / steps_in_interval * 1e3 if steps_in_interval else 0.0
        )
        host_dispatch_ms = (
            interval_dispatch / steps_in_interval * 1e3 if steps_in_interval else 0.0
        )
        current_lr = float(jax.device_get(self._schedule(step - 1)))
        # MFU from per-chip throughput — new observability over the reference,
        # which only tracks tokens_per_sec (SURVEY §5/§6).
        # Straggler telemetry (multi-process only): all-gather every host's
        # mean step time for this interval and reduce to max/median skew. A
        # persistently slowest host is the canonical precursor of a full
        # stall — surface it while the job is still making progress. Rides
        # the boundary the ranks already synchronize at: no extra syncs.
        step_time_skew: float | None = None
        if self._straggler is not None:
            from ..distributed import allgather_scalar

            per_host = np.asarray(allgather_scalar(avg_step_time))
            straggle = self._straggler.observe(per_host)
            step_time_skew = straggle["skew"]
            logger.info(
                "stragglers: step_time max=%.4fs median=%.4fs skew=%.2fx "
                "(slowest host %d)",
                straggle["max_sec"],
                straggle["median_sec"],
                straggle["skew"],
                straggle["slowest_host"],
            )
            if straggle["persistent"]:
                logger.warning(
                    "persistent straggler: host %d has been the slowest "
                    "with >=%.1fx skew for %d consecutive intervals — "
                    "check that host before it stalls the job",
                    straggle["slowest_host"],
                    self._resilience.watchdog.straggler_skew_factor,
                    straggle["streak"],
                )
                self._telemetry.metrics.inc("resilience/straggler_warnings")
                self._telemetry.timeline.instant(
                    "straggler_persistent",
                    cat="resilience",
                    step=step,
                    slowest_host=straggle["slowest_host"],
                    skew=round(straggle["skew"], 3),
                    streak=straggle["streak"],
                )
        n_chips = self._mesh.devices.size
        interval_mfu = compute_mfu(
            tokens_per_sec / n_chips,
            n_params=self._param_count,
            n_layers=self._cfg.model.n_layers,
            seq_len=self._train_seqlen,  # actual trained length, not block_size
            d_model=self._cfg.model.d_model,
            peak_flops=self._peak_flops,
            n_trainable_params=self._trainable_count,
        )

        if self._is_main:
            # All metrics go through the telemetry registry: buffered here,
            # pushed to the tracker by the single flush below (backend
            # failures degrade to warnings — a dead mlflow server must not
            # kill the step loop), and kept live for the Prometheus
            # endpoint and the end-of-run report.
            registry = self._telemetry.metrics
            if self._dp > 1:
                shard_losses = self._shard_means(interval_shard)
                for r in range(self._dp):
                    registry.publish(
                        {
                            f"train/loss_rank_{r}": float(shard_losses[r]),
                            f"train/lr_rank_{r}": current_lr,
                            f"train/tokens_per_sec_rank_{r}": tokens_per_sec / self._dp,
                            f"train/step_time_sec_rank_{r}": avg_step_time,
                            f"train/tokens_total_rank_{r}": float(total_tokens / self._dp),
                        },
                        step=step,
                    )
            global_metrics = {
                "train/loss": avg_loss,
                "train/lr": current_lr,
                "train/tokens_per_sec": tokens_per_sec,
                "train/step_time_sec": avg_step_time,
                "train/tokens_total": float(total_tokens),
                "train/mfu": interval_mfu,
                "train/data_wait_ms": data_wait_ms,
                "train/host_dispatch_ms": host_dispatch_ms,
            }
            if step_time_skew is not None:
                global_metrics["train/step_time_skew"] = step_time_skew
            registry.publish(global_metrics, step=step)
        # The one flush point per log interval: samples memory (mem/*),
        # pushes the pending sample to the tracker, persists the timeline,
        # refreshes the Prometheus textfile. Runs on every rank (non-main
        # ranks flush to a NullTracker and skip file writes).
        self._telemetry.flush(step)

        logger.info(
            "step=%d/%d  loss=%.4f  lr=%.6e  tokens_per_sec=%.1f  step_time=%.4fs  "
            "mfu=%.4f  data_wait=%.2fms  host_dispatch=%.2fms",
            step,
            max_steps,
            avg_loss,
            current_lr,
            tokens_per_sec,
            avg_step_time,
            interval_mfu,
            data_wait_ms,
            host_dispatch_ms,
        )

    # ------------------------------------------------------------------ eval

    def _evaluate(
        self, step: int, max_steps: int, params_override: Any | None = None
    ) -> dict[str, float] | None:
        val_ds = self._data_module.val_dataset()
        if val_ds is None:
            return None
        n = len(val_ds)

        # Pad the last batch up to a multiple of the data-parallel degree —
        # and of the model's batch divisor (pipelined models need
        # data_shards × microbatches; models/base.py batch_divisor) — with
        # zero-masked rows: token-weighted aggregation makes padding exact
        # (padded rows contribute 0 loss and 0 tokens).
        mult = math.lcm(self._dp, self._batch_divisor)
        eval_bs = min(
            max(self._global_micro // mult, 1) * mult,
            -(-n // mult) * mult,
        )
        num_batches = -(-n // eval_bs)

        # Pipelined eval: a worker thread assembles batch b+1 (host-side
        # dataset gathers + make_array_from_callback) while the device runs
        # batch b; eval-step dispatch is async, so the host never blocks on
        # device results inside the loop — there is ONE device sync for the
        # whole eval pass, at the device_get below (VERDICT r1 weak #6).
        # The single-worker executor persists across eval calls: eval-heavy
        # configs (small eval_every_steps) otherwise pay thread startup at
        # every interval.
        if self._eval_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._eval_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="eval-data"
            )
        pool = self._eval_pool

        params = (
            params_override
            if params_override is not None
            else nn_meta.unbox(self._state.params)
        )

        def build(b: int) -> dict:
            real = np.arange(b * eval_bs, min((b + 1) * eval_bs, n))
            pad = eval_bs - len(real)
            indices = np.concatenate([real, np.zeros(pad, dtype=np.int64)])
            return self._eval_batch(val_ds, indices, n_pad=pad)

        loss_sums = []
        token_sums = []
        pending = pool.submit(build, 0)
        for b in range(num_batches):
            batch = pending.result()
            if b + 1 < num_batches:
                pending = pool.submit(build, b + 1)
            loss_sum, tokens = self._eval_step_fn(params, batch)
            loss_sums.append(loss_sum)
            token_sums.append(tokens)

        host_loss, host_tok = jax.device_get((loss_sums, token_sums))
        total_loss = float(sum(x.sum() for x in host_loss))
        total_tok = float(sum(x.sum() for x in host_tok))
        val_loss = total_loss / max(total_tok, 1.0)
        metrics = {"val/loss": val_loss}
        shard_stats = [
            (np.asarray(ls)[None], np.asarray(tc)[None])
            for ls, tc in zip(host_loss, host_tok)
        ]

        if self._is_main:
            registry = self._telemetry.metrics
            if self._dp > 1:
                shard_losses = self._shard_means(shard_stats)
                for r in range(self._dp):
                    registry.publish(
                        {f"val/loss_rank_{r}": float(shard_losses[r])}, step=step
                    )
            registry.publish(metrics, step=step)
        self._telemetry.flush(step)

        parts = "  ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items()))
        logger.info("val_step=%d/%d  %s", step, max_steps, parts)
        return metrics

    # ------------------------------------------------------------------ resume

    def _restore(self, resume_spec: str, *, validate_topology: bool = False) -> int:
        """Load a checkpoint into the live state; returns the restored step.

        ``validate_topology`` (the fit/resume path) checks the commit
        manifest's recorded topology against this run's: batch-axis-only
        changes log an elastic reshard (params/opt state land on the new
        mesh via ``reshard_state``), incompatible changes raise
        ``TopologyMismatchError``. Eval-only restores skip the check —
        they make no trajectory claim."""
        from flax import serialization

        from .checkpoint import read_manifest, warn_on_config_mismatch

        path = resolve_resume_path(resume_spec, self._cfg.output.root_dir)
        manifest = read_manifest(path)
        self._last_restored_manifest = manifest
        if validate_topology:
            saved_topo = (manifest or {}).get("topology")
            verdict = classify_topology_change(saved_topo, self._current_topology())
            if manifest is None or manifest.get("synthesized"):
                # WARNING, not info: an adopted orphan (kill between staged
                # files and manifest publish) or pre-manifest checkpoint
                # cannot be validated — if the operator ALSO changed the
                # topology/global batch, the stream would silently re-deal.
                # The committed-manifest path aborts that case with exit 2;
                # here the best available signal is a loud skip.
                logger.warning(
                    "checkpoint %s carries no saved topology (pre-manifest "
                    "checkpoint or synthesized manifest): elastic/topology "
                    "validation SKIPPED — if the mesh, micro_batch_size, or "
                    "grad_accum_steps changed since it was saved, the resumed "
                    "trajectory will not continue the saved run's",
                    path.name,
                )
            if verdict["elastic"]:
                changes = ", ".join(verdict["changes"])
                logger.warning(
                    "elastic resume: topology changed (%s) with the global "
                    "micro-batch preserved — re-sharding params/optimizer "
                    "state onto the new mesh; the loss trajectory continues "
                    "the saved run's at matching global steps",
                    changes,
                )
                self._telemetry.timeline.instant(
                    "elastic_reshard", cat="resilience", changes=changes
                )
                self._telemetry.metrics.inc("resilience/elastic_reshard")
        payload = CheckpointManager.load(path)
        warn_on_config_mismatch(
            payload, yaml.safe_dump(self._cfg.model_dump(), sort_keys=False), path
        )

        step = int(payload["step"])
        host_params = serialization.from_state_dict(
            nn_meta.unbox(self._state.params), payload["params"]
        )
        host_opt = serialization.from_state_dict(
            nn_meta.unbox(self._state.opt_state), payload["opt_state"]
        )
        boxed_params = _rebox_like(self._state.params, host_params)
        # Resilience scalars (guard counter, rollback/data-offset, spike
        # trend) ride in an optional payload key; absent in pre-resilience
        # checkpoints, which restore with zeroed guard state.
        resil = payload.get("resilience") or {}
        self._last_restored_resilience = {k: v for k, v in resil.items()}
        nonfinite_count = None
        if self._resilience.nonfinite_guard:
            nonfinite_count = jnp.asarray(
                int(resil.get("nonfinite_count", 0)), jnp.int32
            )
        # Placement onto THIS run's mesh (parallel/sharding.py): the
        # checkpoint holds full host arrays, so restoring onto a different
        # data-parallel/fsdp degree is the same device_put as restoring
        # onto the saving one — this line IS the elastic reshard. With
        # trainer.zero the sharding tree carries the ZeRO partition specs,
        # so the SAME jit-identity lands the full host arrays as per-
        # replica state shards (zero on/off and any dp size compose
        # freely across a resume: the payload is always full arrays).
        if self._zero_offload_mode == "roundtrip":
            # Round-trip offload keeps opt state as host numpy between
            # steps — and the checkpoint ALREADY holds full host arrays,
            # so landing them on the mesh just to gather them straight
            # back would be two wasted full-state transfers per restore.
            # Reshard only the on-device fields; re-box the opt tree as
            # owned host copies directly.
            placed = reshard_state(
                {"step": jnp.asarray(step, jnp.int32), "params": boxed_params,
                 "nonfinite_count": nonfinite_count},
                {"step": self._state_shardings.step,
                 "params": self._state_shardings.params,
                 "nonfinite_count": self._state_shardings.nonfinite_count},
            )
            self._state = TrainState(
                step=placed["step"],
                params=placed["params"],
                opt_state=_rebox_like(
                    self._state.opt_state, host_opt, device=False
                ),
                nonfinite_count=placed["nonfinite_count"],
            )
        else:
            restored = TrainState(
                step=jnp.asarray(step, jnp.int32),
                params=boxed_params,
                opt_state=_rebox_like(self._state.opt_state, host_opt),
                nonfinite_count=nonfinite_count,
            )
            self._state = reshard_state(restored, self._state_shardings)
        logger.info("resumed from %s at step %d", path, step)
        return step

    # ------------------------------------------------------------------ misc

    def _peak_memory_bytes(self) -> float:
        from ..utils.hw import peak_memory_bytes

        return peak_memory_bytes()

    def _opt_state_memory(self) -> dict[str, int]:
        """Optimizer-state footprint: logical total, bytes resident on the
        first mesh device, and bytes held off-device (host offload). With
        ZeRO off, per-device == total (every replica holds a full copy);
        with ZeRO on it drops to ~total/N_dp — the measured number behind
        report.json ``memory.opt_state_bytes`` (docs/perf.md)."""
        device0 = self._mesh.devices.flat[0]
        try:
            default_kind = device0.default_memory().kind
        except Exception:  # noqa: BLE001 — memories API is backend-optional
            default_kind = None
        total = per_device = on_host = 0
        for leaf in jax.tree.leaves(nn_meta.unbox(self._state.opt_state)):
            nbytes = int(getattr(leaf, "nbytes", 0) or 0)
            total += nbytes
            if isinstance(leaf, jax.Array):
                kind = getattr(leaf.sharding, "memory_kind", None)
                if (
                    kind is not None
                    and default_kind is not None
                    and kind != default_kind
                ):
                    # memory-kind offload: resident in the host space, not
                    # in the device's default (HBM) space.
                    on_host += nbytes
                    continue
                for shard in leaf.addressable_shards:
                    if shard.device == device0:
                        per_device += int(shard.data.nbytes)
            else:
                # Round-trip offload keeps host numpy between steps.
                on_host += nbytes
        return {
            "opt_state_bytes": total,
            "opt_state_bytes_per_device": per_device,
            "opt_state_bytes_host": on_host,
        }

    def _activation_memory(self) -> dict[str, float] | None:
        """Analytic per-device activation footprint under the run's
        activation-tier ladder (autotune/plan.py predict_hbm_bytes — the
        same model `llmtrain plan` feasibility-checks): device-resident
        bytes plus the host-RAM bytes the offload tier stages. None when
        the plan cannot be resolved (never kills the fit it measures)."""
        from ..autotune.plan import (
            config_loss_impl,
            plan_from_config,
            predict_hbm_bytes,
        )

        cfg = self._cfg
        try:
            plan = plan_from_config(
                cfg, self._mesh.devices.size, adapter=self._adapter
            )
            loss_impl, ce_chunk = config_loss_impl(cfg)
            hbm = predict_hbm_bytes(
                plan,
                n_params=int(self._param_count),
                d_model=cfg.model.d_model,
                n_layers=cfg.model.n_layers,
                vocab_size=int(cfg.model.vocab_size or 50257),
                block_size=cfg.model.block_size,
                dtype_bytes=2 if cfg.model.dtype == "bfloat16" else 4,
                param_dtype_bytes=2 if cfg.model.param_dtype == "bfloat16" else 4,
                loss_impl=loss_impl,
                ce_chunk=ce_chunk,
            )
        except Exception as exc:  # noqa: BLE001 — accounting must not kill runs
            logger.debug("activation memory accounting skipped: %s", exc)
            return None
        return {
            "activation_bytes": float(hbm["activation_bytes"]),
            "activation_bytes_offloaded": float(hbm["activation_host_bytes"]),
        }


class _StepProfiler:
    """Optional ``jax.profiler`` trace over a window of training steps.

    New capability over the reference (SURVEY §5: profiling absent there).
    Enabled via the ``trainer.extra`` escape hatch — the same mechanism the
    reference uses for ``keep_last_k`` (reference trainer.py:101):

        trainer:
          extra:
            profile_start_step: 10     # 0/absent = disabled
            profile_num_steps: 3
            profile_all_hosts: false   # multi-host: trace every process

    The trace (XPlane protos viewable in TensorBoard / xprof / Perfetto)
    lands in ``{run_dir}/logs/profile``. Every start/stop is guarded — a
    profiler failure must never kill or wedge training — and multi-host
    runs CANNOT clobber each other's traces: by default only the main
    process collects; with ``profile_all_hosts`` every process writes into
    its own ``host_{i}`` subdirectory of the shared run dir. The produced
    trace files are registered as tracker artifacts at end of fit
    (telemetry.register_artifacts). Framework-side, the window edges are
    stamped on the event timeline so the XPlane trace aligns with the
    run's own span record.
    """

    def __init__(
        self,
        cfg: RunConfig,
        run_dir: Path | None,
        *,
        process_index: int = 0,
        num_processes: int = 1,
        timeline: Any | None = None,
    ) -> None:
        self._start_step = int(cfg.trainer.extra.get("profile_start_step", 0))
        self._num_steps = max(1, int(cfg.trainer.extra.get("profile_num_steps", 3)))
        all_hosts = bool(cfg.trainer.extra.get("profile_all_hosts", False))
        self._timeline = timeline
        self._dir: Path | None = None
        if run_dir is not None:
            base = Path(run_dir) / "logs" / "profile"
            if num_processes <= 1:
                self._dir = base
            elif all_hosts:
                # Per-host subdirs: the run dir is SHARED on multi-host
                # jobs, and two processes tracing into one directory write
                # interleaved XPlane files that tooling cannot separate.
                self._dir = base / f"host_{process_index}"
            elif process_index == 0:
                self._dir = base
            # non-main without profile_all_hosts: trace collection stays
            # restricted to the main process (self._dir stays None).
        self._active = False
        self._begun_at: int | None = None

    @property
    def enabled(self) -> bool:
        return self._start_step > 0 and self._dir is not None

    def maybe_start(self, step: int) -> None:
        # ``>=`` not ``==``: a resumed run whose first step is already past
        # the window start still traces (from its first step).
        if (
            not self.enabled
            or self._active
            or self._begun_at is not None
            or step < self._start_step
        ):
            return
        try:
            self._dir.mkdir(parents=True, exist_ok=True)
            jax.profiler.start_trace(str(self._dir))
            self._active = True
            self._begun_at = step
            logger.info("profiler trace started at step %d -> %s", step, self._dir)
            if self._timeline is not None:
                self._timeline.instant(
                    "profiler_start", cat="profile", step=step, dir=str(self._dir)
                )
        except Exception as exc:  # profiling must never kill training
            logger.warning("profiler start failed (%s); continuing without trace", exc)

    def maybe_stop(self, step: int, sync: Any = None) -> None:
        if not self._active or step < self._begun_at + self._num_steps - 1:
            return
        self.close(sync=sync)

    def close(self, sync: Any = None) -> None:
        if not self._active:
            return
        try:
            if sync is not None:
                jax.block_until_ready(sync)  # capture the full async dispatch
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", self._dir)
            if self._timeline is not None:
                self._timeline.instant(
                    "profiler_stop", cat="profile", dir=str(self._dir)
                )
        except Exception as exc:
            logger.warning("profiler stop failed (%s)", exc)
        finally:
            self._active = False


def _rebox_like(boxed_template: Any, values: Any, *, device: bool = True) -> Any:
    """Re-attach Partitioned metadata from ``boxed_template`` onto ``values``.

    ``device=False`` keeps the leaves as OWNED host numpy (round-trip
    offload restore: the opt state lives on host between steps, so the
    usual jnp.asarray device placement would be an immediate waste)."""
    from .checkpoint import owned_host_copy

    convert = jnp.asarray if device else owned_host_copy

    def rebox(template_leaf, value):
        if isinstance(template_leaf, nn_meta.Partitioned):
            return template_leaf.replace_boxed(convert(value))
        return convert(value)

    return jax.tree.map(
        rebox, boxed_template, values, is_leaf=lambda x: isinstance(x, nn_meta.Partitioned)
    )
