"""Optimizer + LR schedule via optax.

Parity target: reference trainer.py:93-121 — AdamW(lr, weight_decay) with
grad clipping (trainer.py:390-393) and a LambdaLR doing linear warmup to
``warmup_steps`` then cosine decay to 0 at ``max_steps``. The reference steps
the scheduler *after* the optimizer, so optimizer step N (1-indexed) uses
multiplier ``lr_lambda(N-1)`` — optax's 0-indexed update count reproduces
this exactly.
"""

from __future__ import annotations

import math

import optax

from ..config.schemas import TrainerConfig


def lr_schedule(cfg: TrainerConfig) -> optax.Schedule:
    """Linear warmup → cosine decay to 0, as a function of update count."""
    warmup = cfg.warmup_steps
    max_steps = cfg.max_steps
    base_lr = cfg.lr

    def schedule(count):
        import jax.numpy as jnp

        count = jnp.asarray(count, dtype=jnp.float32)
        warm = count / warmup if warmup > 0 else jnp.ones_like(count)
        if max_steps <= warmup:
            decay = jnp.ones_like(count)
        else:
            progress = (count - warmup) / (max_steps - warmup)
            decay = 0.5 * (1.0 + jnp.cos(math.pi * jnp.clip(progress, 0.0, 1.0)))
        mult = jnp.where(count < warmup, warm, decay)
        return base_lr * mult

    return schedule


def build_optimizer(cfg: TrainerConfig) -> optax.GradientTransformation:
    """clip-by-global-norm → {AdamW | Adafactor} with the warmup-cosine
    schedule.

    ``trainer.extra.optimizer`` selects the update rule:

    * ``"adamw"`` (default) — hyperparams match torch defaults (betas
      0.9/0.999, eps 1e-8) so the optimizer trajectory is comparable to
      the reference (tests/test_torch_parity.py pins it).
    * ``"adafactor"`` — the TPU-classic memory-efficient optimizer: the
      second moment is stored FACTORED (row+column running averages,
      O(n+m) per (n, m) matrix instead of O(n·m)) and first-moment
      momentum is off, cutting optimizer state from 2x params (AdamW) to
      ~per-row/column vectors. The right trade when params (not
      activations) bound HBM — e.g. large-vocab embeddings under FSDP.
      ``weight_decay`` keeps AdamW's decoupled semantics — the decay is
      scaled by the CURRENT scheduled lr (optax.adafactor's own
      ``weight_decay_rate`` would apply ``wd*param`` per step unscaled:
      the schema default 0.1 would shrink params 10%/step and destroy
      training). ``max_grad_norm`` still applies (outer clip).
    * ``"lion"`` — sign-momentum (Chen et al. 2023): HALF the optimizer
      state of AdamW (one moment, no second), updates are ±lr·sign —
      bf16-friendly magnitudes. Published recipe: ~3-10x lower lr and
      ~3-10x higher weight_decay than AdamW for the same effective
      decay strength (wd is lr-scaled here, same decoupled semantics).
    """
    name = str(cfg.extra.get("optimizer", "adamw"))
    ema_decay = cfg.extra.get("ema_decay")
    if ema_decay is not None:
        ema_decay = float(ema_decay)
        if not 0.0 < ema_decay < 1.0:
            raise ValueError(
                f"trainer.extra.ema_decay must be in (0, 1), got {ema_decay}"
            )
    schedule = lr_schedule(cfg)
    if name == "adamw":
        opt = optax.adamw(
            learning_rate=schedule,
            b1=0.9,
            b2=0.999,
            eps=1e-8,
            weight_decay=cfg.weight_decay,
        )
    elif name == "adafactor":
        opt = optax.adafactor(
            learning_rate=schedule,
            multiply_by_parameter_scale=False,
            clipping_threshold=1.0,
            weight_decay_rate=None,
        )
        if cfg.weight_decay:
            opt = optax.chain(
                opt, _scheduled_decoupled_decay(cfg.weight_decay, schedule)
            )
    elif name == "lion":
        opt = optax.lion(
            learning_rate=schedule,
            b1=0.9,
            b2=0.99,
            weight_decay=cfg.weight_decay,
        )
    else:
        raise ValueError(
            f"trainer.extra.optimizer {name!r} unknown; expected 'adamw', "
            "'adafactor', or 'lion'"
        )
    parts = [optax.clip_by_global_norm(cfg.max_grad_norm), opt]
    if ema_decay is not None:
        parts.append(_param_ema(ema_decay))
    return optax.chain(*parts)


# Sentinel key marking the EMA shadow tree inside a serialized opt_state,
# so checkpoint consumers (training/checkpoint.py:load_ema_params) can
# find it without knowing the optimizer chain's exact shape.
EMA_STATE_KEY = "__param_ema__"


def _param_ema(decay: float) -> optax.GradientTransformation:
    """Track a Polyak/EMA shadow of the parameters INSIDE the optimizer.

    ``trainer.extra.ema_decay`` — classic trick: evaluating/serving the
    exponential moving average ``ema ← d·ema + (1-d)·params`` usually
    beats the raw final step. Chained LAST so it sees the final updates;
    the state rides opt_state, which means checkpointing, exact resume,
    and sharding (the shadow keeps the params' flax metadata boxes) all
    come for free — no TrainState or train-step changes. Extract with
    ``generate --ema`` / ``eval --ema`` / ``export-checkpoint --ema``.

    The shadow accumulates in float32 regardless of the param dtype: a
    (1-d) ≈ 0.1% per-step increment is below bf16's ~0.4% relative
    resolution, so a bf16 shadow would round every update away and
    freeze near its init. Extraction casts back to the param dtype.
    """
    import jax
    import jax.numpy as jnp

    def _f32(p):
        if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
            return jnp.asarray(p, jnp.float32)
        return p

    def init(params):
        return {EMA_STATE_KEY: jax.tree.map(_f32, params)}

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("param EMA needs params in the update call")

        def one(e, p, u):
            post = p + u
            if not jnp.issubdtype(jnp.asarray(post).dtype, jnp.floating):
                return post
            return decay * e + (1.0 - decay) * jnp.asarray(post, jnp.float32)

        new = jax.tree.map(one, state[EMA_STATE_KEY], params, updates)
        return updates, {EMA_STATE_KEY: new}

    return optax.GradientTransformation(init, update)


def find_ema_tree(opt_state: "object") -> "object | None":
    """Locate the EMA shadow inside a LIVE optimizer state (chained
    namedtuples/tuples/dicts) or a serialized payload (index-keyed
    dicts). None when the optimizer tracks no EMA."""
    if isinstance(opt_state, dict):
        if EMA_STATE_KEY in opt_state:
            return opt_state[EMA_STATE_KEY]
        children = opt_state.values()
    elif isinstance(opt_state, (tuple, list)):
        children = opt_state
    else:
        return None
    for child in children:
        hit = find_ema_tree(child)
        if hit is not None:
            return hit
    return None


def _scheduled_decoupled_decay(
    weight_decay: float, schedule: optax.Schedule
) -> optax.GradientTransformation:
    """AdamW-style decoupled weight decay: ``-lr(t) * wd * param`` added
    to the (already lr-scaled) updates — matching how optax.adamw scales
    its decay by the schedule, so the trainer's ``weight_decay`` value
    means the same thing under both optimizers."""
    import jax
    import jax.numpy as jnp

    def init(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError("weight decay needs params in the update call")
        lr = schedule(state.count)
        updates = jax.tree.map(
            lambda u, p: u - lr * weight_decay * p, updates, params
        )
        return updates, optax.ScaleByScheduleState(
            count=optax.safe_int32_increment(state.count)
        )

    return optax.GradientTransformation(init, update)
