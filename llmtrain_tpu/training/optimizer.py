"""Optimizer + LR schedule via optax.

Parity target: reference trainer.py:93-121 — AdamW(lr, weight_decay) with
grad clipping (trainer.py:390-393) and a LambdaLR doing linear warmup to
``warmup_steps`` then cosine decay to 0 at ``max_steps``. The reference steps
the scheduler *after* the optimizer, so optimizer step N (1-indexed) uses
multiplier ``lr_lambda(N-1)`` — optax's 0-indexed update count reproduces
this exactly.
"""

from __future__ import annotations

import math

import optax

from ..config.schemas import TrainerConfig


def lr_schedule(cfg: TrainerConfig) -> optax.Schedule:
    """Linear warmup → cosine decay to 0, as a function of update count."""
    warmup = cfg.warmup_steps
    max_steps = cfg.max_steps
    base_lr = cfg.lr

    def schedule(count):
        import jax.numpy as jnp

        count = jnp.asarray(count, dtype=jnp.float32)
        warm = count / warmup if warmup > 0 else jnp.ones_like(count)
        if max_steps <= warmup:
            decay = jnp.ones_like(count)
        else:
            progress = (count - warmup) / (max_steps - warmup)
            decay = 0.5 * (1.0 + jnp.cos(math.pi * jnp.clip(progress, 0.0, 1.0)))
        mult = jnp.where(count < warmup, warm, decay)
        return base_lr * mult

    return schedule


def build_optimizer(cfg: TrainerConfig) -> optax.GradientTransformation:
    """clip-by-global-norm → AdamW with the warmup-cosine schedule.

    AdamW hyperparams match torch defaults (betas 0.9/0.999, eps 1e-8) so the
    optimizer trajectory is comparable to the reference.
    """
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(
            learning_rate=lr_schedule(cfg),
            b1=0.9,
            b2=0.999,
            eps=1e-8,
            weight_decay=cfg.weight_decay,
        ),
    )
