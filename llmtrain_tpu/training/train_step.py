"""The jit-compiled training and eval steps.

This replaces the reference's Python hot loop (reference trainer.py:361-534):
forward, backward, gradient accumulation, clipping, AdamW, LR schedule and
gradient synchronization are ONE traced XLA program per optimizer step.

* Gradient accumulation is a ``lax.scan`` over the leading micro-batch axis —
  the analogue of the reference's ``no_sync()`` trick (trainer.py:376-384):
  gradients accumulate in sharded registers and the cross-replica reduction
  XLA inserts happens once per optimizer step, not per micro-batch.
* Dropout RNG is ``fold_in(run_key, step, micro_idx)`` — stateless, so resume
  reproduces the exact RNG stream without checkpointing generator state
  (the reference must capture python/numpy/torch RNG states,
  reference checkpoint.py:53-59).
* Per-data-shard metrics come back as small (accum, B) arrays; the host
  derives the reference's ``*_rank_{r}`` metric values from them.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..models.base import ModelAdapter


@struct.dataclass
class TrainState:
    """Pytree holding everything the step updates. ``step`` counts completed
    optimizer steps (0 = fresh init); training step N uses LR multiplier
    schedule(N-1), matching the reference's post-step LambdaLR.

    ``nonfinite_count`` is the non-finite guard's consecutive-skip counter
    (resilience/guard.py) — an int32 scalar when the guard is enabled, None
    otherwise so unguarded runs keep the exact seed pytree structure."""

    step: jax.Array
    params: Any
    opt_state: Any
    nonfinite_count: Any = None


def create_train_state(params: Any, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))


def make_loss_fn(
    adapter: ModelAdapter, model: Any, *, use_dropout: bool
) -> Callable:
    """Per-micro-batch loss: (params, batch, rng) -> (loss, (loss_sum_B, tokens_B))."""

    def loss_fn(params, micro_batch, rng):
        rngs = {"dropout": rng} if use_dropout else None
        comps = adapter.compute_loss_components(
            model, params, micro_batch, rngs=rngs, deterministic=not use_dropout
        )
        if comps is None:
            loss, _ = adapter.compute_loss(
                model, params, micro_batch, rngs=rngs, deterministic=not use_dropout
            )
            mask = micro_batch.get("attention_mask")
            if mask is None:
                tokens = jnp.full(
                    (micro_batch["input_ids"].shape[0],),
                    micro_batch["input_ids"].shape[1],
                    jnp.float32,
                )
            else:
                tokens = (mask != 0).astype(jnp.float32).sum(axis=-1)
            # Fallback: distribute the scalar loss uniformly per token.
            return loss, (loss * tokens, tokens)
        loss_sum, tokens = comps
        loss = jnp.sum(loss_sum) / jnp.maximum(jnp.sum(tokens), 1.0)
        return loss, (loss_sum, tokens)

    return loss_fn


def make_train_step(
    adapter: ModelAdapter,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    grad_accum_steps: int,
    use_dropout: bool,
    nonfinite_guard: bool = False,
    inject_nan_window: tuple[int, int] | None = None,
    grad_shardings: Any | None = None,
) -> Callable:
    """Build the pure train step: (state, batch(A,B,T), run_key) -> (state, metrics).

    ``nonfinite_guard`` masks the optimizer update behind ``lax.cond`` on an
    all-finite flag over loss and grads (resilience/guard.py): a non-finite
    step leaves params/opt_state untouched, advances ``step`` (so the
    deterministic sampler moves past the bad batch), and bumps the
    consecutive-skip counter the trainer aborts on.

    ``inject_nan_window=(start, n)`` is the fault-injection hook
    (resilience/faults.py): loss and grads are poisoned with NaN for
    optimizer steps ``start .. start+n-1``, compiled into the step so the
    guard's recovery is exercised inside the real XLA program.

    ``grad_shardings`` (ZeRO, trainer.zero — a NamedSharding pytree over
    the param structure) pins the accumulated gradients' layout with
    ``with_sharding_constraint`` so GSPMD emits the intended gradient
    collective. Stage 1 passes the PARAM shardings: the grad sync stays
    the all-reduce of the replicated path (bitwise-identical math — the
    global-norm clip sees the exact same layout), while the optimizer
    update downstream is sharded by the state's in/out shardings and the
    new params all-gather. Stage 2 passes the ZeRO-sharded layout: the
    sync becomes a reduce-scatter and the full grad tree never
    materializes replicated after accumulation (the norm clip then
    reduces shard partials — ~1e-6 float reassociation vs zero-off).
    None (zero off) adds no constraint: the pre-zero program, bit-exact.
    """
    loss_fn = make_loss_fn(adapter, model, use_dropout=use_dropout)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def train_step(state: TrainState, batch: dict, run_key: jax.Array):
        step_key = jax.random.fold_in(run_key, state.step)

        def micro(grads_acc, xs):
            micro_batch, idx = xs
            rng = jax.random.fold_in(step_key, idx)
            (loss, (loss_sum, tokens)), grads = grad_fn(state.params, micro_batch, rng)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            return grads_acc, (loss, loss_sum, tokens)

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        idxs = jnp.arange(grad_accum_steps)
        grads_sum, (losses, loss_sums, token_counts) = jax.lax.scan(
            micro, zeros, (batch, idxs)
        )
        grads = jax.tree.map(lambda g: g / grad_accum_steps, grads_sum)
        if grad_shardings is not None:
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)

        if inject_nan_window is not None:
            first, length = inject_nan_window
            current = state.step + 1  # 1-based optimizer step being taken
            in_window = (current >= first) & (current < first + length)
            poison = jnp.where(in_window, jnp.float32(jnp.nan), jnp.float32(1.0))
            grads = jax.tree.map(lambda g: g * poison.astype(g.dtype), grads)
            losses = losses * poison.astype(losses.dtype)

        metrics = {
            # mean over accum steps of per-micro-batch token-weighted means,
            # matching reference step_loss (trainer.py:389).
            "loss": jnp.mean(losses),
            "per_example_loss_sum": loss_sums,  # (A, B)
            "per_example_tokens": token_counts,  # (A, B)
        }

        if nonfinite_guard:
            from ..resilience.guard import tree_all_finite

            all_finite = tree_all_finite(grads) & jnp.isfinite(losses).all()

            def _apply(operand):
                g, opt_state, params = operand
                updates, new_opt = tx.update(g, opt_state, params)
                return optax.apply_updates(params, updates), new_opt

            def _skip(operand):
                _, opt_state, params = operand
                return params, opt_state

            # lax.cond, not a per-leaf where-select: the skip branch must
            # not evaluate tx.update at all — optax transforms divide by
            # grad moments and a NaN would infect the selected-away branch
            # under value-level masking.
            new_params, new_opt_state = jax.lax.cond(
                all_finite, _apply, _skip, (grads, state.opt_state, state.params)
            )
            prev = state.nonfinite_count
            if prev is None:
                prev = jnp.zeros((), jnp.int32)
            new_count = jnp.where(all_finite, 0, prev + 1).astype(jnp.int32)
            # grad_norm of NaN grads is NaN — honest, and only read at log
            # boundaries.
            metrics["grad_norm"] = optax.global_norm(grads)
            metrics["nonfinite_count"] = new_count
        else:
            updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            new_count = state.nonfinite_count
            metrics["grad_norm"] = optax.global_norm(grads)

        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            nonfinite_count=new_count,
        )
        return new_state, metrics

    return train_step


def make_eval_step(adapter: ModelAdapter, model: Any) -> Callable:
    """Forward-only: (params, batch(B,T)) -> (loss_sum_B, tokens_B)."""
    loss_fn = make_loss_fn(adapter, model, use_dropout=False)

    def eval_step(params, batch):
        _, (loss_sum, tokens) = loss_fn(params, batch, jax.random.key(0))
        return loss_sum, tokens

    return eval_step
