"""The jit-compiled training and eval steps.

This replaces the reference's Python hot loop (reference trainer.py:361-534):
forward, backward, gradient accumulation, clipping, AdamW, LR schedule and
gradient synchronization are ONE traced XLA program per optimizer step.

* Gradient accumulation is a ``lax.scan`` over the leading micro-batch axis —
  the analogue of the reference's ``no_sync()`` trick (trainer.py:376-384):
  gradients accumulate in sharded registers and the cross-replica reduction
  XLA inserts happens once per optimizer step, not per micro-batch.
* Dropout RNG is ``fold_in(run_key, step, micro_idx)`` — stateless, so resume
  reproduces the exact RNG stream without checkpointing generator state
  (the reference must capture python/numpy/torch RNG states,
  reference checkpoint.py:53-59).
* Per-data-shard metrics come back as small (accum, B) arrays; the host
  derives the reference's ``*_rank_{r}`` metric values from them.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..models.base import ModelAdapter


@struct.dataclass
class TrainState:
    """Pytree holding everything the step updates. ``step`` counts completed
    optimizer steps (0 = fresh init); training step N uses LR multiplier
    schedule(N-1), matching the reference's post-step LambdaLR."""

    step: jax.Array
    params: Any
    opt_state: Any


def create_train_state(params: Any, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))


def make_loss_fn(
    adapter: ModelAdapter, model: Any, *, use_dropout: bool
) -> Callable:
    """Per-micro-batch loss: (params, batch, rng) -> (loss, (loss_sum_B, tokens_B))."""

    def loss_fn(params, micro_batch, rng):
        rngs = {"dropout": rng} if use_dropout else None
        comps = adapter.compute_loss_components(
            model, params, micro_batch, rngs=rngs, deterministic=not use_dropout
        )
        if comps is None:
            loss, _ = adapter.compute_loss(
                model, params, micro_batch, rngs=rngs, deterministic=not use_dropout
            )
            mask = micro_batch.get("attention_mask")
            if mask is None:
                tokens = jnp.full(
                    (micro_batch["input_ids"].shape[0],),
                    micro_batch["input_ids"].shape[1],
                    jnp.float32,
                )
            else:
                tokens = (mask != 0).astype(jnp.float32).sum(axis=-1)
            # Fallback: distribute the scalar loss uniformly per token.
            return loss, (loss * tokens, tokens)
        loss_sum, tokens = comps
        loss = jnp.sum(loss_sum) / jnp.maximum(jnp.sum(tokens), 1.0)
        return loss, (loss_sum, tokens)

    return loss_fn


def make_train_step(
    adapter: ModelAdapter,
    model: Any,
    tx: optax.GradientTransformation,
    *,
    grad_accum_steps: int,
    use_dropout: bool,
) -> Callable:
    """Build the pure train step: (state, batch(A,B,T), run_key) -> (state, metrics)."""
    loss_fn = make_loss_fn(adapter, model, use_dropout=use_dropout)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def train_step(state: TrainState, batch: dict, run_key: jax.Array):
        step_key = jax.random.fold_in(run_key, state.step)

        def micro(grads_acc, xs):
            micro_batch, idx = xs
            rng = jax.random.fold_in(step_key, idx)
            (loss, (loss_sum, tokens)), grads = grad_fn(state.params, micro_batch, rng)
            grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
            return grads_acc, (loss, loss_sum, tokens)

        zeros = jax.tree.map(jnp.zeros_like, state.params)
        idxs = jnp.arange(grad_accum_steps)
        grads_sum, (losses, loss_sums, token_counts) = jax.lax.scan(
            micro, zeros, (batch, idxs)
        )
        grads = jax.tree.map(lambda g: g / grad_accum_steps, grads_sum)

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt_state
        )
        metrics = {
            # mean over accum steps of per-micro-batch token-weighted means,
            # matching reference step_loss (trainer.py:389).
            "loss": jnp.mean(losses),
            "grad_norm": optax.global_norm(grads),
            "per_example_loss_sum": loss_sums,  # (A, B)
            "per_example_tokens": token_counts,  # (A, B)
        }
        return new_state, metrics

    return train_step


def make_eval_step(adapter: ModelAdapter, model: Any) -> Callable:
    """Forward-only: (params, batch(B,T)) -> (loss_sum_B, tokens_B)."""
    loss_fn = make_loss_fn(adapter, model, use_dropout=False)

    def eval_step(params, batch):
        _, (loss_sum, tokens) = loss_fn(params, batch, jax.random.key(0))
        return loss_sum, tokens

    return eval_step
