"""Llama parameter conversion to/from the HF-transformers state dict.

The Llama family's checkpoint lingua franca is HF ``LlamaForCausalLM``
(the analogue of the reference torch GPT format that
``interop/torch_interop.py`` speaks for the GPT family). The exported
dict uses HF's module names, so

    LlamaForCausalLM(config).load_state_dict(torch.load(path))

works strict=True; import accepts the same naming, so weights from any
HF Llama/Mistral-class checkpoint load into ``models/llama.py``.

    model.embed_tokens.weight
    model.layers.{i}.input_layernorm.weight
    model.layers.{i}.self_attn.{q,k,v,o}_proj.weight
    model.layers.{i}.post_attention_layernorm.weight
    model.layers.{i}.mlp.{gate,up,down}_proj.weight
    model.norm.weight
    lm_head.weight            (tied models: the shared tensor; HF
                               safetensors may omit it — tolerated on
                               import into a tied template)

Layout transforms are the ones proven numerically in
tests/test_llama.py's HF parity tests (logits atol 2e-4 against torch
LlamaForCausalLM on full forward AND cache prefill): flax kernels are
(in, out) vs torch Linear (out, in); head-major DenseGeneral kernels
(D, H, dh) flatten C-order to torch's (H·dh, D) rows. Both the fused-MHA
tree (``qkv_proj``, n_kv_heads == n_heads) and the split GQA tree
(``q_proj``/``kv_proj``) are handled — HF always stores q/k/v separately.

Conversion is pure numpy; torch is only needed by callers that
``torch.save``/``torch.load`` the result. Float tensors export as f32.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

Params = Any  # nested dict pytree of arrays

# Older transformers versions persisted per-layer rotary inv_freq buffers;
# they are deterministic functions of (head_dim, rope_theta) — ignored.
_ROTARY_BUFFER_RE = re.compile(r"(^|\.)rotary_emb\.inv_freq$")


def is_llama_tree(params: Params) -> bool:
    """True for a models/llama.py param tree.

    Keys off ``attn_norm`` — the RMSNorm marker only llama blocks carry
    (GPT blocks use ``ln_1``/``ln_2``) — so dense AND MoE (llama_moe)
    trees both dispatch here; the converter then raises its own accurate
    error for the MoE layout it cannot express in HF-Llama naming.
    """
    blk = params.get("block_0") if hasattr(params, "get") else None
    return blk is not None and "attn_norm" in blk


def _np(a) -> np.ndarray:
    return np.array(a, dtype=np.float32)


def llama_params_to_hf_state_dict(params: Params) -> dict[str, np.ndarray]:
    """Flax Llama params (models/llama.py tree) → HF Llama state dict."""
    for required in ("token_embedding", "norm_f"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; only the models/llama.py "
                "tree is supported (model.name 'llama')"
            )
    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _np(params["token_embedding"]["embedding"]),
        "model.norm.weight": _np(params["norm_f"]["scale"]),
    }
    d = sd["model.embed_tokens.weight"].shape[1]
    i = 0
    while f"block_{i}" in params:
        p = params[f"block_{i}"]
        if "moe_mlp" in p:
            raise ValueError(
                "Mixture-of-Experts checkpoints (model.name llama_moe) "
                "have no counterpart in the HF LlamaForCausalLM state-dict "
                "layout — export is only supported for dense llama models"
            )
        if "mlp_gate" not in p:
            raise ValueError(
                f"block_{i} has no mlp_gate; not a models/llama.py tree"
            )
        att = p["attn"]
        pre = f"model.layers.{i}."
        if "qkv_proj" in att:
            # Fused MHA tree (n_kv_heads == n_heads): HF stores q/k/v
            # separately, so split the (D, 3, H, dh) kernel.
            kern = _np(att["qkv_proj"]["kernel"])
            q, k, v = kern[:, 0], kern[:, 1], kern[:, 2]
            biases = (
                tuple(_np(att["qkv_proj"]["bias"])[j] for j in range(3))
                if "bias" in att["qkv_proj"]
                else None
            )
        else:
            q = _np(att["q_proj"]["kernel"])
            kv = _np(att["kv_proj"]["kernel"])
            k, v = kv[:, 0], kv[:, 1]
            if "bias" in att["q_proj"]:
                kvb = _np(att["kv_proj"]["bias"])
                biases = (_np(att["q_proj"]["bias"]), kvb[0], kvb[1])
            else:
                biases = None
        sd[pre + "self_attn.q_proj.weight"] = q.reshape(d, -1).T
        sd[pre + "self_attn.k_proj.weight"] = k.reshape(d, -1).T
        sd[pre + "self_attn.v_proj.weight"] = v.reshape(d, -1).T
        if biases is not None:
            # Qwen2 convention (models/qwen2.py): 1-D torch biases,
            # head-major flatten matching the kernel rows.
            for name, b in zip(("q", "k", "v"), biases):
                sd[pre + f"self_attn.{name}_proj.bias"] = b.reshape(-1)
        sd[pre + "self_attn.o_proj.weight"] = (
            _np(att["out_proj"]["kernel"]).reshape(-1, d).T
        )
        sd[pre + "input_layernorm.weight"] = _np(p["attn_norm"]["scale"])
        sd[pre + "post_attention_layernorm.weight"] = _np(p["mlp_norm"]["scale"])
        sd[pre + "mlp.gate_proj.weight"] = _np(p["mlp_gate"]["kernel"]).T
        sd[pre + "mlp.up_proj.weight"] = _np(p["mlp_up"]["kernel"]).T
        sd[pre + "mlp.down_proj.weight"] = _np(p["mlp_down"]["kernel"]).T
        i += 1
    if i == 0:
        raise ValueError("params contain no block_0; not a models/llama.py tree")
    if "lm_head" in params:
        sd["lm_head.weight"] = _np(params["lm_head"]["kernel"]).T
    else:
        # Tied model: HF materializes the shared tensor under
        # lm_head.weight in .bin state dicts (tie_word_embeddings=True).
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    return sd


def llama_params_from_hf_state_dict(sd: dict[str, Any], template: Params) -> Params:
    """HF Llama state dict → flax params shaped like ``template``.

    ``template`` (a fresh ``adapter.init_params`` tree) supplies
    structure, dtypes, and shapes; missing/mismatched/unconsumed keys
    raise (silently dropping weights would "import" a different model).
    Rotary inv_freq buffers are ignored; a tied template tolerates a
    missing ``lm_head.weight`` (HF safetensors drops shared tensors) and
    rejects one that differs from the embedding.
    """
    import jax.numpy as jnp

    consumed: set[str] = set()

    def put(key: str, like, transform=lambda a: a) -> Any:
        if key not in sd:
            raise ValueError(f"state dict is missing {key!r}")
        consumed.add(key)
        a = transform(np.asarray(sd[key], dtype=np.float32))
        want = tuple(np.shape(like))
        if tuple(a.shape) != want:
            raise ValueError(
                f"{key!r}: converted shape {tuple(a.shape)} != expected {want}"
            )
        return jnp.asarray(a, dtype=like.dtype)

    def take_proj(key: str, shape: tuple) -> np.ndarray:
        """Torch (out, in) Linear weight → transposed + head-major reshape."""
        if key not in sd:
            raise ValueError(f"state dict is missing {key!r}")
        consumed.add(key)
        a = np.asarray(sd[key], dtype=np.float32).T
        if a.size != int(np.prod(shape)):
            raise ValueError(
                f"{key!r}: shape {a.shape} cannot reshape to {shape}"
            )
        return a.reshape(shape)

    d = np.shape(template["token_embedding"]["embedding"])[1]
    out: dict[str, Any] = {
        "token_embedding": {
            "embedding": put(
                "model.embed_tokens.weight",
                template["token_embedding"]["embedding"],
            )
        },
        "norm_f": {"scale": put("model.norm.weight", template["norm_f"]["scale"])},
    }
    i = 0
    while f"block_{i}" in template:
        t = template[f"block_{i}"]
        if "moe_mlp" in t:
            raise ValueError(
                "Mixture-of-Experts configs (model.name llama_moe) have no "
                "counterpart in the HF LlamaForCausalLM state-dict layout — "
                "import is only supported for dense llama models"
            )
        att_t = t["attn"]
        pre = f"model.layers.{i}."
        if "qkv_proj" in att_t:
            like = att_t["qkv_proj"]["kernel"]
            h, hd = np.shape(like)[2:4]
            qkv = np.stack(
                [
                    take_proj(pre + f"self_attn.{n}_proj.weight", (d, h, hd))
                    for n in ("q", "k", "v")
                ],
                axis=1,
            )
            attn = {"qkv_proj": {"kernel": jnp.asarray(qkv, dtype=like.dtype)}}
            if "bias" in att_t["qkv_proj"]:
                # Qwen2 tree: (3, H, dh) fused bias from the 1-D torch ones.
                bl = att_t["qkv_proj"]["bias"]
                attn["qkv_proj"]["bias"] = jnp.asarray(
                    np.stack(
                        [
                            take_proj(pre + f"self_attn.{n}_proj.bias", (h, hd))
                            for n in ("q", "k", "v")
                        ],
                        axis=0,
                    ),
                    dtype=bl.dtype,
                )
        else:
            h, hd = np.shape(att_t["q_proj"]["kernel"])[1:3]
            like = att_t["kv_proj"]["kernel"]
            hkv = np.shape(like)[2]
            kv = np.stack(
                [
                    take_proj(pre + f"self_attn.{n}_proj.weight", (d, hkv, hd))
                    for n in ("k", "v")
                ],
                axis=1,
            )
            attn = {
                "q_proj": {
                    "kernel": put(
                        pre + "self_attn.q_proj.weight",
                        att_t["q_proj"]["kernel"],
                        lambda a: a.T.reshape(d, h, hd),
                    )
                },
                "kv_proj": {"kernel": jnp.asarray(kv, dtype=like.dtype)},
            }
            if "bias" in att_t["q_proj"]:
                attn["q_proj"]["bias"] = jnp.asarray(
                    take_proj(pre + "self_attn.q_proj.bias", (h, hd)),
                    dtype=att_t["q_proj"]["bias"].dtype,
                )
                attn["kv_proj"]["bias"] = jnp.asarray(
                    np.stack(
                        [
                            take_proj(pre + f"self_attn.{n}_proj.bias", (hkv, hd))
                            for n in ("k", "v")
                        ],
                        axis=0,
                    ),
                    dtype=att_t["kv_proj"]["bias"].dtype,
                )
        attn["out_proj"] = {
            "kernel": put(
                pre + "self_attn.o_proj.weight",
                att_t["out_proj"]["kernel"],
                lambda a: a.T.reshape(-1, np.shape(att_t["out_proj"]["kernel"])[1], d),
            )
        }
        out[f"block_{i}"] = {
            "attn_norm": {
                "scale": put(pre + "input_layernorm.weight", t["attn_norm"]["scale"])
            },
            "mlp_norm": {
                "scale": put(
                    pre + "post_attention_layernorm.weight", t["mlp_norm"]["scale"]
                )
            },
            "attn": attn,
            "mlp_gate": {
                "kernel": put(
                    pre + "mlp.gate_proj.weight", t["mlp_gate"]["kernel"],
                    lambda a: a.T,
                )
            },
            "mlp_up": {
                "kernel": put(
                    pre + "mlp.up_proj.weight", t["mlp_up"]["kernel"], lambda a: a.T
                )
            },
            "mlp_down": {
                "kernel": put(
                    pre + "mlp.down_proj.weight", t["mlp_down"]["kernel"],
                    lambda a: a.T,
                )
            },
        }
        i += 1
    if "lm_head" in template:
        out["lm_head"] = {
            "kernel": put("lm_head.weight", template["lm_head"]["kernel"], lambda a: a.T)
        }
    elif "lm_head.weight" in sd:
        head = np.asarray(sd["lm_head.weight"], dtype=np.float32)
        tok = np.asarray(sd["model.embed_tokens.weight"], dtype=np.float32)
        if head.shape != tok.shape or not np.array_equal(head, tok):
            raise ValueError(
                "state dict's lm_head.weight differs from "
                "model.embed_tokens.weight: the source model was untied, "
                "but the target config has model.tie_embeddings=true"
            )
        consumed.add("lm_head.weight")
    consumed.update(k for k in sd if _ROTARY_BUFFER_RE.search(k))
    extra = set(template) - set(out)
    if extra:
        raise ValueError(
            f"template has params the converter does not map: {sorted(extra)} "
            "(only the models/llama.py tree is supported)"
        )
    unconsumed = set(sd) - consumed
    if unconsumed:
        raise ValueError(
            f"state dict has weights the template cannot hold: "
            f"{sorted(unconsumed)[:8]}{'...' if len(unconsumed) > 8 else ''} "
            "(layer count / head count / weight tying mismatch?)"
        )
    return out


__all__ = [
    "is_llama_tree",
    "llama_params_to_hf_state_dict",
    "llama_params_from_hf_state_dict",
]
