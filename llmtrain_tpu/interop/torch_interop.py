"""GPT parameter conversion to/from the reference torch GPT's state dict.

The exported dict uses the reference model's ACTUAL module names
(reference src/llmtrain/models/gpt.py:27-146), so
``GPT.from_config(cfg); model.load_state_dict(torch.load(path))`` works
strict=True on the reference implementation:

    token_embedding.weight, position_embedding.weight,
    blocks.{i}.ln_1.{weight,bias},
    blocks.{i}.attn.qkv_proj.{weight,bias},
    blocks.{i}.attn.out_proj.{weight,bias},
    blocks.{i}.attn.causal_mask          (persistent bool buffer,
                                          reference gpt.py:32-33),
    blocks.{i}.ln_2.{weight,bias},
    blocks.{i}.mlp_fc.{weight,bias}, blocks.{i}.mlp_proj.{weight,bias},
    ln_f.{weight,bias},
    lm_head.weight                       (ALWAYS — tied models share the
                                          tensor with token_embedding,
                                          reference gpt.py:143-146)

The layout transforms are the ones proven numerically equivalent in
tests/test_torch_parity.py (logits 2e-5, gradients 1e-4, optimizer
trajectory 3e-5 vs the reference-spec torch mirror): flax Dense kernels
are (in, out) vs torch Linear (out, in); the fused qkv DenseGeneral
kernel (D, 3, H, hd) flattens C-order so torch's row-chunk(3) recovers
q/k/v; out_proj (H, hd, D) contracts in the same C-order as torch's
post-attention reshape.

Import accepts the same naming, tolerates the tied ``lm_head.weight``
duplicate, ignores the deterministic ``causal_mask`` buffers, and maps
the first-generation export names (``tok.weight``/``blocks.{i}.qkv.*``)
so pre-alignment .pt files stay importable.
Conversion is pure numpy — torch is only needed by callers that
``torch.save``/``torch.load`` the result (the export-checkpoint CLI).
All float tensors are exported in float32.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

Params = Any  # nested dict pytree of arrays

_CAUSAL_MASK_RE = re.compile(r"^blocks\.\d+\.attn\.causal_mask$")

# The first export format (pre reference-name alignment) used short
# names and left attention projections unscoped. Files saved by it are
# mapped on import rather than failing with a generic missing-key error.
_LEGACY_RENAMES = {
    "tok.weight": "token_embedding.weight",
    "pos.weight": "position_embedding.weight",
}
_LEGACY_BLOCK_RE = re.compile(r"^(blocks\.\d+)\.(qkv|out_proj)\.(weight|bias)$")


def _normalize_legacy_keys(sd: dict[str, Any]) -> dict[str, Any]:
    """Rename a legacy-format state dict to the current reference names.

    Legacy marker: ``tok.weight`` (the current format always has
    ``token_embedding.weight`` instead). Tied legacy exports carried no
    ``lm_head.weight`` duplicate and no ``causal_mask`` buffers; both
    absences are already tolerated downstream.
    """
    if "tok.weight" not in sd:
        return sd
    out: dict[str, Any] = {}
    for k, v in sd.items():
        m = _LEGACY_BLOCK_RE.match(k)
        if m:
            proj = "qkv_proj" if m.group(2) == "qkv" else "out_proj"
            k = f"{m.group(1)}.attn.{proj}.{m.group(3)}"
        out[_LEGACY_RENAMES.get(k, k)] = v
    return out


def _np(a) -> np.ndarray:
    return np.array(a, dtype=np.float32)


def params_to_torch_state_dict(params: Params) -> dict[str, np.ndarray]:
    """Flax GPT params (models/gpt.py tree) → reference torch state dict."""
    for required in ("token_embedding", "position_embedding", "ln_f"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; only the models/gpt.py dense "
                "GPT tree is supported (model.name 'gpt')"
            )
    sd: dict[str, np.ndarray] = {
        "token_embedding.weight": _np(params["token_embedding"]["embedding"]),
        "position_embedding.weight": _np(params["position_embedding"]["embedding"]),
        "ln_f.weight": _np(params["ln_f"]["scale"]),
        "ln_f.bias": _np(params["ln_f"]["bias"]),
    }
    block_size, d = sd["position_embedding.weight"].shape
    # The reference registers the causal mask as a persistent buffer
    # (gpt.py:32-33), so strict load_state_dict expects it per block.
    causal_mask = np.triu(
        np.ones((block_size, block_size), dtype=bool), k=1
    ).reshape(1, 1, block_size, block_size)
    i = 0
    while f"block_{i}" in params:
        p = params[f"block_{i}"]
        att = p["attn"]
        if "q_proj" in att or "kv_proj" in att:
            raise ValueError(
                "GQA/MQA checkpoints (model.extra.n_kv_heads) split the "
                "attention projection into q_proj/kv_proj, which has no "
                "counterpart in the reference torch GPT's fused qkv_proj — "
                "export is only supported for full multi-head attention"
            )
        if "qkv_proj" not in att:
            raise ValueError(
                f"block_{i}.attn has no qkv_proj; not a models/gpt.py GPT tree"
            )
        if "moe_mlp" in p:
            raise ValueError(
                "Mixture-of-Experts checkpoints (model.extra.n_experts) "
                "have no counterpart in the reference torch GPT's dense "
                "MLP — export is only supported for dense models"
            )
        pre = f"blocks.{i}"
        sd[f"{pre}.ln_1.weight"] = _np(p["ln_1"]["scale"])
        sd[f"{pre}.ln_1.bias"] = _np(p["ln_1"]["bias"])
        sd[f"{pre}.ln_2.weight"] = _np(p["ln_2"]["scale"])
        sd[f"{pre}.ln_2.bias"] = _np(p["ln_2"]["bias"])
        sd[f"{pre}.attn.qkv_proj.weight"] = _np(att["qkv_proj"]["kernel"]).reshape(d, 3 * d).T
        sd[f"{pre}.attn.qkv_proj.bias"] = _np(att["qkv_proj"]["bias"]).reshape(3 * d)
        sd[f"{pre}.attn.out_proj.weight"] = _np(att["out_proj"]["kernel"]).reshape(d, d).T
        sd[f"{pre}.attn.out_proj.bias"] = _np(att["out_proj"]["bias"])
        sd[f"{pre}.attn.causal_mask"] = causal_mask
        sd[f"{pre}.mlp_fc.weight"] = _np(p["mlp_fc"]["kernel"]).T
        sd[f"{pre}.mlp_fc.bias"] = _np(p["mlp_fc"]["bias"])
        sd[f"{pre}.mlp_proj.weight"] = _np(p["mlp_proj"]["kernel"]).T
        sd[f"{pre}.mlp_proj.bias"] = _np(p["mlp_proj"]["bias"])
        i += 1
    if i == 0:
        raise ValueError("params contain no block_0; not a models/gpt.py GPT tree")
    if "lm_head" in params:
        sd["lm_head.weight"] = _np(params["lm_head"]["kernel"]).T
    else:
        # Tied model: the reference still materializes lm_head.weight in
        # its state dict (the tensor is shared, gpt.py:145-146).
        sd["lm_head.weight"] = sd["token_embedding.weight"]
    return sd


def params_from_torch_state_dict(
    sd: dict[str, Any], template: Params
) -> Params:
    """Reference torch state dict → flax GPT params shaped like ``template``.

    ``template`` (e.g. a fresh ``adapter.init_params`` tree) supplies the
    tree structure, dtypes, and expected shapes; every template leaf must
    be present in ``sd`` (missing/mismatched keys raise). The reference's
    ``causal_mask`` buffers are ignored, and for tied templates the
    mandatory ``lm_head.weight`` duplicate is accepted iff it matches
    ``token_embedding.weight`` (a differing head means the source model
    was untied and cannot load into a tied template).
    """
    import jax.numpy as jnp

    sd = _normalize_legacy_keys(sd)
    consumed: set[str] = set()

    def put(key: str, like, transform=lambda a: a) -> Any:
        if key not in sd:
            raise ValueError(f"state dict is missing {key!r}")
        consumed.add(key)
        a = transform(np.asarray(sd[key], dtype=np.float32))
        want = tuple(np.shape(like))
        if tuple(a.shape) != want:
            raise ValueError(
                f"{key!r}: converted shape {tuple(a.shape)} != expected {want}"
            )
        return jnp.asarray(a, dtype=like.dtype)

    d = np.shape(template["token_embedding"]["embedding"])[1]
    out: dict[str, Any] = {
        "token_embedding": {
            "embedding": put("token_embedding.weight", template["token_embedding"]["embedding"])
        },
        "position_embedding": {
            "embedding": put(
                "position_embedding.weight", template["position_embedding"]["embedding"]
            )
        },
        "ln_f": {
            "scale": put("ln_f.weight", template["ln_f"]["scale"]),
            "bias": put("ln_f.bias", template["ln_f"]["bias"]),
        },
    }
    i = 0
    while f"block_{i}" in template:
        t = template[f"block_{i}"]
        pre = f"blocks.{i}"
        att_t = t["attn"]
        if "qkv_proj" not in att_t:
            raise ValueError(
                "template uses split q_proj/kv_proj attention (GQA/MQA, "
                "model.extra.n_kv_heads) — the reference torch GPT has no "
                "such checkpoint format; import requires full multi-head "
                "attention"
            )
        h, hd = np.shape(att_t["qkv_proj"]["kernel"])[2:4]
        out[f"block_{i}"] = {
            "ln_1": {
                "scale": put(f"{pre}.ln_1.weight", t["ln_1"]["scale"]),
                "bias": put(f"{pre}.ln_1.bias", t["ln_1"]["bias"]),
            },
            "ln_2": {
                "scale": put(f"{pre}.ln_2.weight", t["ln_2"]["scale"]),
                "bias": put(f"{pre}.ln_2.bias", t["ln_2"]["bias"]),
            },
            "attn": {
                "qkv_proj": {
                    "kernel": put(
                        f"{pre}.attn.qkv_proj.weight",
                        att_t["qkv_proj"]["kernel"],
                        lambda a: a.T.reshape(d, 3, h, hd),
                    ),
                    "bias": put(
                        f"{pre}.attn.qkv_proj.bias",
                        att_t["qkv_proj"]["bias"],
                        lambda a: a.reshape(3, h, hd),
                    ),
                },
                "out_proj": {
                    "kernel": put(
                        f"{pre}.attn.out_proj.weight",
                        att_t["out_proj"]["kernel"],
                        lambda a: a.T.reshape(h, hd, d),
                    ),
                    "bias": put(f"{pre}.attn.out_proj.bias", att_t["out_proj"]["bias"]),
                },
            },
            "mlp_fc": {
                "kernel": put(f"{pre}.mlp_fc.weight", t["mlp_fc"]["kernel"], lambda a: a.T),
                "bias": put(f"{pre}.mlp_fc.bias", t["mlp_fc"]["bias"]),
            },
            "mlp_proj": {
                "kernel": put(f"{pre}.mlp_proj.weight", t["mlp_proj"]["kernel"], lambda a: a.T),
                "bias": put(f"{pre}.mlp_proj.bias", t["mlp_proj"]["bias"]),
            },
        }
        i += 1
    if "lm_head" in template:
        out["lm_head"] = {
            "kernel": put("lm_head.weight", template["lm_head"]["kernel"], lambda a: a.T)
        }
    elif "lm_head.weight" in sd:
        # Tied template: the reference always emits the shared tensor
        # under lm_head.weight too. Accept it only if it really is the
        # tied duplicate.
        head = np.asarray(sd["lm_head.weight"], dtype=np.float32)
        # Compare against the RAW sd value, not the template-dtype-cast
        # tree — a bf16 param_dtype would otherwise fail equality for a
        # genuinely tied f32 checkpoint.
        tok = np.asarray(sd["token_embedding.weight"], dtype=np.float32)
        if head.shape != tok.shape or not np.array_equal(head, tok):
            raise ValueError(
                "state dict's lm_head.weight differs from "
                "token_embedding.weight: the source model was untied, but "
                "the target config has model.tie_embeddings=true"
            )
        consumed.add("lm_head.weight")
    # The causal-mask buffers are deterministic functions of block_size;
    # nothing to import.
    consumed.update(k for k in sd if _CAUSAL_MASK_RE.match(k))
    extra = set(template) - set(out)
    if extra:
        raise ValueError(
            f"template has params the converter does not map: {sorted(extra)} "
            "(only the models/gpt.py dense GPT tree is supported)"
        )
    unconsumed = set(sd) - consumed
    if unconsumed:
        # Silently dropping weights (deeper torch model, ...) would import
        # "successfully" and then produce different logits than the source.
        raise ValueError(
            f"state dict has weights the template cannot hold: "
            f"{sorted(unconsumed)[:8]}{'...' if len(unconsumed) > 8 else ''} "
            "(layer count / weight tying mismatch?)"
        )
    return out


__all__ = ["params_to_torch_state_dict", "params_from_torch_state_dict"]
