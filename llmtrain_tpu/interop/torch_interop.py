"""GPT parameter conversion to/from a torch-layout state dict.

The torch mirror architecture and the exact layout transforms are the
ones proven numerically equivalent in tests/test_torch_parity.py (logits
2e-5, gradients 1e-4, optimizer trajectory 3e-5 vs the reference-spec
torch GPT): flax Dense kernels are (in, out) vs torch Linear (out, in);
the fused qkv DenseGeneral kernel (D, 3, H, hd) flattens C-order so
torch's row-chunk(3) recovers q/k/v; out_proj (H, hd, D) contracts in
the same C-order as torch's post-attention reshape.

State-dict naming (the mirror's):

    tok.weight, pos.weight,
    blocks.{i}.ln_1.{weight,bias}, blocks.{i}.qkv.{weight,bias},
    blocks.{i}.out_proj.{weight,bias}, blocks.{i}.ln_2.{weight,bias},
    blocks.{i}.mlp_fc.{weight,bias}, blocks.{i}.mlp_proj.{weight,bias},
    ln_f.{weight,bias}, lm_head.weight (untied models only)

Conversion is pure numpy — torch is only needed by callers that
``torch.save``/``torch.load`` the result (the export-checkpoint CLI).
All tensors are exported in float32.
"""

from __future__ import annotations

from typing import Any

import numpy as np

Params = Any  # nested dict pytree of arrays


def _np(a) -> np.ndarray:
    return np.array(a, dtype=np.float32)


def params_to_torch_state_dict(params: Params) -> dict[str, np.ndarray]:
    """Flax GPT params (models/gpt.py tree) → torch-layout state dict."""
    for required in ("token_embedding", "position_embedding", "ln_f"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; only the models/gpt.py dense "
                "GPT tree is supported (model.name 'gpt')"
            )
    sd: dict[str, np.ndarray] = {
        "tok.weight": _np(params["token_embedding"]["embedding"]),
        "pos.weight": _np(params["position_embedding"]["embedding"]),
        "ln_f.weight": _np(params["ln_f"]["scale"]),
        "ln_f.bias": _np(params["ln_f"]["bias"]),
    }
    d = sd["tok.weight"].shape[1]
    i = 0
    while f"block_{i}" in params:
        p = params[f"block_{i}"]
        att = p["attn"]
        pre = f"blocks.{i}"
        sd[f"{pre}.ln_1.weight"] = _np(p["ln_1"]["scale"])
        sd[f"{pre}.ln_1.bias"] = _np(p["ln_1"]["bias"])
        sd[f"{pre}.ln_2.weight"] = _np(p["ln_2"]["scale"])
        sd[f"{pre}.ln_2.bias"] = _np(p["ln_2"]["bias"])
        sd[f"{pre}.qkv.weight"] = _np(att["qkv_proj"]["kernel"]).reshape(d, 3 * d).T
        sd[f"{pre}.qkv.bias"] = _np(att["qkv_proj"]["bias"]).reshape(3 * d)
        sd[f"{pre}.out_proj.weight"] = _np(att["out_proj"]["kernel"]).reshape(d, d).T
        sd[f"{pre}.out_proj.bias"] = _np(att["out_proj"]["bias"])
        sd[f"{pre}.mlp_fc.weight"] = _np(p["mlp_fc"]["kernel"]).T
        sd[f"{pre}.mlp_fc.bias"] = _np(p["mlp_fc"]["bias"])
        sd[f"{pre}.mlp_proj.weight"] = _np(p["mlp_proj"]["kernel"]).T
        sd[f"{pre}.mlp_proj.bias"] = _np(p["mlp_proj"]["bias"])
        i += 1
    if i == 0:
        raise ValueError("params contain no block_0; not a models/gpt.py GPT tree")
    if "lm_head" in params:
        sd["lm_head.weight"] = _np(params["lm_head"]["kernel"]).T
    return sd


def params_from_torch_state_dict(
    sd: dict[str, Any], template: Params
) -> Params:
    """torch-layout state dict → flax GPT params shaped like ``template``.

    ``template`` (e.g. a fresh ``adapter.init_params`` tree) supplies the
    tree structure, dtypes, and expected shapes; every template leaf must
    be present in ``sd`` (missing/mismatched keys raise).
    """
    import jax.numpy as jnp

    consumed: set[str] = set()

    def put(key: str, like, transform=lambda a: a) -> Any:
        if key not in sd:
            raise ValueError(f"state dict is missing {key!r}")
        consumed.add(key)
        a = transform(np.asarray(sd[key], dtype=np.float32))
        want = tuple(np.shape(like))
        if tuple(a.shape) != want:
            raise ValueError(
                f"{key!r}: converted shape {tuple(a.shape)} != expected {want}"
            )
        return jnp.asarray(a, dtype=like.dtype)

    d = np.shape(template["token_embedding"]["embedding"])[1]
    out: dict[str, Any] = {
        "token_embedding": {"embedding": put("tok.weight", template["token_embedding"]["embedding"])},
        "position_embedding": {"embedding": put("pos.weight", template["position_embedding"]["embedding"])},
        "ln_f": {
            "scale": put("ln_f.weight", template["ln_f"]["scale"]),
            "bias": put("ln_f.bias", template["ln_f"]["bias"]),
        },
    }
    i = 0
    while f"block_{i}" in template:
        t = template[f"block_{i}"]
        pre = f"blocks.{i}"
        att_t = t["attn"]
        h, hd = np.shape(att_t["qkv_proj"]["kernel"])[2:4]
        out[f"block_{i}"] = {
            "ln_1": {
                "scale": put(f"{pre}.ln_1.weight", t["ln_1"]["scale"]),
                "bias": put(f"{pre}.ln_1.bias", t["ln_1"]["bias"]),
            },
            "ln_2": {
                "scale": put(f"{pre}.ln_2.weight", t["ln_2"]["scale"]),
                "bias": put(f"{pre}.ln_2.bias", t["ln_2"]["bias"]),
            },
            "attn": {
                "qkv_proj": {
                    "kernel": put(
                        f"{pre}.qkv.weight",
                        att_t["qkv_proj"]["kernel"],
                        lambda a: a.T.reshape(d, 3, h, hd),
                    ),
                    "bias": put(
                        f"{pre}.qkv.bias",
                        att_t["qkv_proj"]["bias"],
                        lambda a: a.reshape(3, h, hd),
                    ),
                },
                "out_proj": {
                    "kernel": put(
                        f"{pre}.out_proj.weight",
                        att_t["out_proj"]["kernel"],
                        lambda a: a.T.reshape(h, hd, d),
                    ),
                    "bias": put(f"{pre}.out_proj.bias", att_t["out_proj"]["bias"]),
                },
            },
            "mlp_fc": {
                "kernel": put(f"{pre}.mlp_fc.weight", t["mlp_fc"]["kernel"], lambda a: a.T),
                "bias": put(f"{pre}.mlp_fc.bias", t["mlp_fc"]["bias"]),
            },
            "mlp_proj": {
                "kernel": put(f"{pre}.mlp_proj.weight", t["mlp_proj"]["kernel"], lambda a: a.T),
                "bias": put(f"{pre}.mlp_proj.bias", t["mlp_proj"]["bias"]),
            },
        }
        i += 1
    if "lm_head" in template:
        out["lm_head"] = {
            "kernel": put("lm_head.weight", template["lm_head"]["kernel"], lambda a: a.T)
        }
    extra = set(template) - set(out)
    if extra:
        raise ValueError(
            f"template has params the converter does not map: {sorted(extra)} "
            "(only the models/gpt.py dense GPT tree is supported)"
        )
    unconsumed = set(sd) - consumed
    if unconsumed:
        # Silently dropping weights (deeper torch model, untied head into a
        # tied template, ...) would import "successfully" and then produce
        # different logits than the source model.
        raise ValueError(
            f"state dict has weights the template cannot hold: "
            f"{sorted(unconsumed)[:8]}{'...' if len(unconsumed) > 8 else ''} "
            "(layer count / weight tying mismatch?)"
        )
    return out


__all__ = ["params_to_torch_state_dict", "params_from_torch_state_dict"]
