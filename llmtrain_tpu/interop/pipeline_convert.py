"""gpt_pipeline ↔ gpt parameter-tree conversion.

The pipeline model stacks every block parameter with a LEADING layer dim
so stages can shard it over the ``pipeline`` mesh axis
(models/gpt_pipeline.py ``_stacked``); the plain GPT keeps per-layer
``block_{i}`` subtrees (models/gpt.py). The math is identical (same
pre-norm blocks, GELU MLP, LN eps 1e-6, tied lm_head), so converting is
pure re-indexing — no numerics.

This unlocks the rest of the toolchain for pipeline-trained runs:
``export-checkpoint`` (reference torch format, via
interop/torch_interop.py), ``import-checkpoint``, KV-cache ``generate``,
and torch-parity evaluation all operate on the ``gpt`` tree. The CLI
applies the conversion automatically when ``model.name: gpt_pipeline``
(cli.py export/import handlers).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = Any

# stacked leaf name -> (gpt block subtree path); the attention entries
# depend on the layout — fused qkv (MHA) vs split q/kv (GQA, both models
# use the same per-layer shapes).
_COMMON_MAP: dict[str, tuple[str, ...]] = {
    "ln1_scale": ("ln_1", "scale"),
    "ln1_bias": ("ln_1", "bias"),
    "out_kernel": ("attn", "out_proj", "kernel"),
    "out_bias": ("attn", "out_proj", "bias"),
    "ln2_scale": ("ln_2", "scale"),
    "ln2_bias": ("ln_2", "bias"),
    "fc_kernel": ("mlp_fc", "kernel"),
    "fc_bias": ("mlp_fc", "bias"),
    "proj_kernel": ("mlp_proj", "kernel"),
    "proj_bias": ("mlp_proj", "bias"),
}
_MHA_MAP: dict[str, tuple[str, ...]] = {
    **_COMMON_MAP,
    "qkv_kernel": ("attn", "qkv_proj", "kernel"),
    "qkv_bias": ("attn", "qkv_proj", "bias"),
}
_GQA_MAP: dict[str, tuple[str, ...]] = {
    **_COMMON_MAP,
    "q_kernel": ("attn", "q_proj", "kernel"),
    "q_bias": ("attn", "q_proj", "bias"),
    "kv_kernel": ("attn", "kv_proj", "kernel"),
    "kv_bias": ("attn", "kv_proj", "bias"),
}


def _set_path(tree: dict, path: tuple[str, ...], value) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def _get_path(tree: dict, path: tuple[str, ...]):
    node = tree
    for key in path:
        node = node[key]
    return node


def _layer_slice(leaf, i: int):
    """Layer ``i`` of a stacked leaf; abstract (ShapeDtypeStruct) leaves
    slice symbolically, so the conversion also maps checkpoint templates
    (the import-checkpoint path converts shapes before any data exists)."""
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return jax.ShapeDtypeStruct(leaf.shape[1:], leaf.dtype)
    return leaf[i]


def _block_map(fused: bool) -> dict[str, tuple[str, ...]]:
    return _MHA_MAP if fused else _GQA_MAP


def pipeline_params_to_gpt(params: Params) -> Params:
    """Stacked gpt_pipeline tree → per-layer models/gpt.py tree.

    Works on real arrays AND abstract ShapeDtypeStruct trees (templates);
    both the fused-qkv (MHA) and split q/kv (GQA) layouts convert.
    """
    for required in ("token_embedding", "position_embedding"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; not a models/gpt_pipeline.py tree"
            )
    fused = "qkv_kernel" in params
    if not fused and "q_kernel" not in params:
        raise ValueError(
            "params have neither qkv_kernel nor q_kernel; not a "
            "models/gpt_pipeline.py tree"
        )
    block_map = _block_map(fused)
    n_layers = params["qkv_kernel" if fused else "q_kernel"].shape[0]
    out: dict[str, Any] = {
        "token_embedding": dict(params["token_embedding"]),
        "position_embedding": dict(params["position_embedding"]),
        "ln_f": {"scale": params["ln_f_scale"], "bias": params["ln_f_bias"]},
    }
    if "lm_head" in params:
        out["lm_head"] = dict(params["lm_head"])
    for i in range(n_layers):
        block: dict[str, Any] = {}
        for name, path in block_map.items():
            _set_path(block, path, _layer_slice(params[name], i))
        out[f"block_{i}"] = block
    return out


def gpt_params_to_pipeline(params: Params) -> Params:
    """Per-layer models/gpt.py tree → stacked gpt_pipeline tree.

    Handles both the fused-qkv (MHA) and split q/kv (GQA) layouts — the
    pipeline model stacks the matching projection shapes.
    """
    for required in ("token_embedding", "position_embedding", "block_0"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; not a models/gpt.py tree"
            )
    fused = "qkv_proj" in params["block_0"]["attn"]
    block_map = _block_map(fused)
    n_layers = 0
    while f"block_{n_layers}" in params:
        n_layers += 1
    out: dict[str, Any] = {
        "token_embedding": dict(params["token_embedding"]),
        "position_embedding": dict(params["position_embedding"]),
        "ln_f_scale": params["ln_f"]["scale"],
        "ln_f_bias": params["ln_f"]["bias"],
    }
    if "lm_head" in params:
        out["lm_head"] = dict(params["lm_head"])
    for name, path in block_map.items():
        out[name] = jnp.stack(
            [_get_path(params[f"block_{i}"], path) for i in range(n_layers)]
        )
    return out


def is_pipeline_tree(params: Params) -> bool:
    return (
        "qkv_kernel" in params or "q_kernel" in params
    ) and "block_0" not in params


__all__ = [
    "pipeline_params_to_gpt",
    "gpt_params_to_pipeline",
    "is_pipeline_tree",
]
