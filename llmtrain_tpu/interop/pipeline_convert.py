"""gpt_pipeline ↔ gpt parameter-tree conversion.

The pipeline model stacks every block parameter with a LEADING layer dim
so stages can shard it over the ``pipeline`` mesh axis
(models/gpt_pipeline.py ``_stacked``); the plain GPT keeps per-layer
``block_{i}`` subtrees (models/gpt.py). The math is identical (same
pre-norm blocks, GELU MLP, LN eps 1e-6, tied lm_head), so converting is
pure re-indexing — no numerics.

This unlocks the rest of the toolchain for pipeline-trained runs:
``export-checkpoint`` (reference torch format, via
interop/torch_interop.py), ``import-checkpoint``, KV-cache ``generate``,
and torch-parity evaluation all operate on the ``gpt`` tree. The CLI
applies the conversion automatically when ``model.name: gpt_pipeline``
(cli.py export/import handlers).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = Any

# stacked leaf name -> (gpt block subtree path)
_BLOCK_MAP: dict[str, tuple[str, ...]] = {
    "ln1_scale": ("ln_1", "scale"),
    "ln1_bias": ("ln_1", "bias"),
    "qkv_kernel": ("attn", "qkv_proj", "kernel"),
    "qkv_bias": ("attn", "qkv_proj", "bias"),
    "out_kernel": ("attn", "out_proj", "kernel"),
    "out_bias": ("attn", "out_proj", "bias"),
    "ln2_scale": ("ln_2", "scale"),
    "ln2_bias": ("ln_2", "bias"),
    "fc_kernel": ("mlp_fc", "kernel"),
    "fc_bias": ("mlp_fc", "bias"),
    "proj_kernel": ("mlp_proj", "kernel"),
    "proj_bias": ("mlp_proj", "bias"),
}


def _set_path(tree: dict, path: tuple[str, ...], value) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def _get_path(tree: dict, path: tuple[str, ...]):
    node = tree
    for key in path:
        node = node[key]
    return node


def _layer_slice(leaf, i: int):
    """Layer ``i`` of a stacked leaf; abstract (ShapeDtypeStruct) leaves
    slice symbolically, so the conversion also maps checkpoint templates
    (the import-checkpoint path converts shapes before any data exists)."""
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return jax.ShapeDtypeStruct(leaf.shape[1:], leaf.dtype)
    return leaf[i]


def pipeline_params_to_gpt(params: Params) -> Params:
    """Stacked gpt_pipeline tree → per-layer models/gpt.py tree.

    Works on real arrays AND abstract ShapeDtypeStruct trees (templates).
    """
    for required in ("token_embedding", "position_embedding", "qkv_kernel"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; not a models/gpt_pipeline.py tree"
            )
    n_layers = params["qkv_kernel"].shape[0]
    out: dict[str, Any] = {
        "token_embedding": dict(params["token_embedding"]),
        "position_embedding": dict(params["position_embedding"]),
        "ln_f": {"scale": params["ln_f_scale"], "bias": params["ln_f_bias"]},
    }
    if "lm_head" in params:
        out["lm_head"] = dict(params["lm_head"])
    for i in range(n_layers):
        block: dict[str, Any] = {}
        for name, path in _BLOCK_MAP.items():
            _set_path(block, path, _layer_slice(params[name], i))
        out[f"block_{i}"] = block
    return out


def gpt_params_to_pipeline(params: Params) -> Params:
    """Per-layer models/gpt.py tree → stacked gpt_pipeline tree.

    Requires the fused-qkv (MHA) tree — GQA's split q_proj/kv_proj has no
    pipeline counterpart.
    """
    for required in ("token_embedding", "position_embedding", "block_0"):
        if required not in params:
            raise ValueError(
                f"params have no {required!r}; not a models/gpt.py tree"
            )
    if "qkv_proj" not in params["block_0"]["attn"]:
        raise ValueError(
            "GQA/MQA trees (split q_proj/kv_proj, model.extra.n_kv_heads) "
            "cannot convert to the pipeline layout, which stacks a fused "
            "qkv kernel"
        )
    n_layers = 0
    while f"block_{n_layers}" in params:
        n_layers += 1
    out: dict[str, Any] = {
        "token_embedding": dict(params["token_embedding"]),
        "position_embedding": dict(params["position_embedding"]),
        "ln_f_scale": params["ln_f"]["scale"],
        "ln_f_bias": params["ln_f"]["bias"],
    }
    if "lm_head" in params:
        out["lm_head"] = dict(params["lm_head"])
    for name, path in _BLOCK_MAP.items():
        out[name] = jnp.stack(
            [_get_path(params[f"block_{i}"], path) for i in range(n_layers)]
        )
    return out


def is_pipeline_tree(params: Params) -> bool:
    return "qkv_kernel" in params and "block_0" not in params


__all__ = [
    "pipeline_params_to_gpt",
    "gpt_params_to_pipeline",
    "is_pipeline_tree",
]
