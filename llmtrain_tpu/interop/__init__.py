"""Cross-framework interop (torch / HF-Llama checkpoint export/import)
and pipeline↔gpt parameter-tree conversion."""

from .llama_hf import (
    is_llama_tree,
    llama_params_from_hf_state_dict,
    llama_params_to_hf_state_dict,
)
from .pipeline_convert import (
    gpt_params_to_pipeline,
    is_pipeline_tree,
    pipeline_params_to_gpt,
)
from .torch_interop import (
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

__all__ = [
    "params_to_torch_state_dict",
    "params_from_torch_state_dict",
    "pipeline_params_to_gpt",
    "gpt_params_to_pipeline",
    "is_pipeline_tree",
    "is_llama_tree",
    "llama_params_to_hf_state_dict",
    "llama_params_from_hf_state_dict",
]
