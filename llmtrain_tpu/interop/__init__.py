"""Cross-framework interop (torch checkpoint export/import) and
pipeline↔gpt parameter-tree conversion."""

from .pipeline_convert import (
    gpt_params_to_pipeline,
    is_pipeline_tree,
    pipeline_params_to_gpt,
)
from .torch_interop import (
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

__all__ = [
    "params_to_torch_state_dict",
    "params_from_torch_state_dict",
    "pipeline_params_to_gpt",
    "gpt_params_to_pipeline",
    "is_pipeline_tree",
]
