"""Cross-framework interop (torch checkpoint export/import)."""

from .torch_interop import (
    params_from_torch_state_dict,
    params_to_torch_state_dict,
)

__all__ = ["params_to_torch_state_dict", "params_from_torch_state_dict"]
