"""llmtrain_tpu — a TPU-native (JAX/XLA/pjit/Pallas) LLM training framework.

Brand-new framework with the capabilities of the reference ``llmtrain``
(LeGabriel/local-llm-training-k8s): strict YAML→Pydantic configs, a plugin
registry of model adapters and data modules, a step-based trainer whose entire
optimizer step (grad accumulation + clipping + AdamW + LR schedule + gradient
sync) is one jit-compiled XLA program over a ``jax.sharding.Mesh``,
checkpoint/resume with exact loss parity, rank-0 MLflow tracking, and
Kubernetes IndexedJob orchestration (incl. a GKE TPU pod-slice variant).

The compute path is JAX/Flax/Pallas; parallelism is expressed as shardings
over a named device mesh (data/fsdp/tensor/sequence axes) with XLA
collectives over ICI/DCN — not a DDP wrapper.
"""

__version__ = "0.1.0"
