"""Deterministic preemption-aware scheduling policy over shared capacity.

Pure functions only — no clocks, no processes, no randomness — so every
quota/priority/shrink-before-suspend decision is table-testable (tier-1)
and two supervisors looking at the same fleet state always compute the
same plan. The supervisor (fleet/supervisor.py) owns the messy parts
(signals, subprocesses, backoff); this module owns WHO gets HOW MANY
devices.

The policy (MinT's scheduling argument, PAPERS.md: preemption is a
scheduling decision, not a disaster):

1. **Admit by priority.** Runnable tenants sorted by (-priority, name)
   each receive their smallest feasible world size while capacity lasts;
   a tenant whose minimum no longer fits is SUSPENDED (allocation 0) —
   degraded, never crashed. Shrinking a low-priority tenant to its
   minimum to admit a high-priority one falls out of the same pass: the
   high-priority tenant is granted first, so the low one only keeps what
   is left.
2. **Grow round-robin.** Remaining capacity is handed out one feasibility
   step at a time in priority order, so a spare device goes to the
   highest-priority tenant below its quota, and nobody exceeds
   ``max_devices``.

Feasibility: a tenant's world size must divide its global micro-batch —
that is exactly the elastic-resume contract (``micro_batch_size × dp``
constant, resilience/elastic.py), so every resize the policy can emit is
a resize the trainer can resume through with a preserved trajectory.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class TenantDemand:
    """What the scheduler needs to know about one tenant."""

    name: str
    priority: int
    # Ascending feasible world sizes (candidate_world_sizes); the first
    # entry is the tenant's minimum footprint, the last its quota.
    candidate_sizes: tuple[int, ...]
    runnable: bool = True

    def __post_init__(self) -> None:
        if not self.candidate_sizes:
            raise ValueError(f"tenant {self.name!r} has no feasible world size")
        if list(self.candidate_sizes) != sorted(set(self.candidate_sizes)):
            raise ValueError(
                f"tenant {self.name!r}: candidate_sizes must be strictly "
                f"ascending, got {self.candidate_sizes}"
            )


@dataclass
class AllocationPlan:
    """The policy's output: device grant per tenant (0 = suspended)."""

    allocations: dict[str, int] = field(default_factory=dict)
    free_devices: int = 0
    suspended: tuple[str, ...] = ()


def candidate_world_sizes(
    global_micro_batch: int, min_devices: int, max_devices: int
) -> tuple[int, ...]:
    """Feasible world sizes for a tenant: every device count in
    [min_devices, max_devices] that divides the tenant's global
    micro-batch — the allocations elastic resume can re-shard across with
    an unchanged trajectory. Raises when the window contains none (a
    config error: the tenant could never be scheduled legally)."""
    sizes = tuple(
        d
        for d in range(min_devices, max_devices + 1)
        if global_micro_batch % d == 0
    )
    if not sizes:
        raise ValueError(
            f"no device count in [{min_devices}, {max_devices}] divides the "
            f"global micro-batch {global_micro_batch}; elastic resume "
            "requires micro_batch_size x world size to stay constant — "
            "adjust trainer.micro_batch_size or the tenant's device bounds"
        )
    return sizes


def priority_order(demands: list[TenantDemand]) -> list[TenantDemand]:
    """Deterministic scheduling order: priority desc, then name — ties
    never depend on dict/iteration order."""
    return sorted(demands, key=lambda d: (-d.priority, d.name))


def plan_allocations(pool_devices: int, demands: list[TenantDemand]) -> AllocationPlan:
    """Compute the target world size for every tenant (see module doc).

    Non-runnable tenants (completed/failed) are carried in the result with
    allocation 0 so callers can reconcile over one dict.
    """
    if pool_devices < 0:
        raise ValueError(f"pool_devices must be >= 0, got {pool_devices}")
    alloc = {d.name: 0 for d in demands}
    order = priority_order([d for d in demands if d.runnable])
    free = pool_devices

    # Pass 1: minimum footprints by priority; what does not fit suspends.
    for d in order:
        need = d.candidate_sizes[0]
        if need <= free:
            alloc[d.name] = need
            free -= need

    # Pass 2: round-robin growth, one feasibility step per turn, priority
    # first — a single spare device goes to the most important tenant
    # below quota, and repeated rounds spread the rest fairly.
    grew = True
    while grew and free > 0:
        grew = False
        for d in order:
            cur = alloc[d.name]
            if cur == 0:
                continue  # suspended tenants do not grow past admission
            bigger = next((c for c in d.candidate_sizes if c > cur), None)
            if bigger is not None and bigger - cur <= free:
                free -= bigger - cur
                alloc[d.name] = bigger
                grew = True

    suspended = tuple(
        d.name for d in order if d.runnable and alloc[d.name] == 0
    )
    return AllocationPlan(allocations=alloc, free_devices=free, suspended=suspended)


def within_bounds(allocation: int, demand: TenantDemand) -> bool:
    """Bounds invariant the storm drill asserts on every launch: a tenant
    runs with one of its feasible sizes, or not at all."""
    return allocation == 0 or allocation in demand.candidate_sizes


__all__ = [
    "AllocationPlan",
    "TenantDemand",
    "candidate_world_sizes",
    "plan_allocations",
    "priority_order",
    "within_bounds",
]
