"""Multi-tenant fleet supervisor: N training jobs on one bounded device pool.

PR 5's chaos harness proves ONE job survives kills; this supervisor is the
control plane that makes preemption a *scheduling decision* across many
jobs (MinT, PAPERS.md). Every tenant is a real ``python -m llmtrain_tpu
train --auto-resume`` subprocess with a stable run id, scheduled onto an
emulated CPU device pool (``XLA_FLAGS=--xla_force_host_platform_device_
count=N`` per child); the deterministic policy (fleet/policy.py) decides
world sizes, and every resize/suspend/evict rides the machinery earlier
PRs proved correct:

* **Graceful-first escalation ladder** — SIGTERM (the trainer's clean
  preemption save, exit 0) → ``fleet.preempt_grace_sec`` deadline →
  SIGKILL. Either way the atomic manifest-commit protocol guarantees the
  next segment resumes from a valid commit; the supervisor ASSERTS that
  (newest-commit-loadable + resumed-from-newest-valid, the chaos
  invariants promoted to per-tenant, via resilience/harness.py).
* **Elastic resize** — capacity shifts re-launch a tenant with
  ``micro_batch_size`` scaled inversely to its new world size, so the
  resume is an elastic topology change (resilience/elastic.py) and the
  trajectory is preserved.
* **Seeded respawn backoff** — eviction ``k`` of a tenant sleeps a
  full-jitter delay drawn from ``retry_rng(seed, tenant_index)``
  (resilience/faults.py): deterministic per tenant, decorrelated across
  tenants.
* **Degrade, never crash** — when the pool shrinks below total demand,
  low-priority tenants shrink to ``min_devices`` and then SUSPEND
  (allocation 0, waiting on capacity, not on a timer).

Health aggregates into ``llmtrain_fleet_*`` gauges (telemetry registry +
Prometheus textfile/endpoint) and a ``fleet_report.json``/``.md`` with
per-tenant resume/eviction counts, exit-code taxonomy, and heartbeat
staleness read from the watchdog beacon files. See docs/robustness.md
"Fleet: many tenants, shared capacity".
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import time
from collections import Counter
from pathlib import Path
from typing import Any, Callable

import yaml

from ..config.schemas import FleetTenantConfig, RunConfig
from ..resilience.exit_codes import RETRYABLE_EXIT_CODES
from ..resilience.faults import retry_rng
from ..resilience.harness import (
    KILL_RETURNCODES,
    TERM_RETURNCODES,
    DrillInvariantError,
    aligned_log_every,
    assert_newest_loadable,
    derive_segment_config,
    log_size,
    newest_committed_step_live,
    segment_resumed_step,
    summary_of,
    train_segment_command,
)
from ..resilience.watchdog import heartbeat_age_seconds
from ..telemetry.prometheus import (
    federate_prometheus,
    render_prometheus,
    write_textfile,
)
from ..telemetry.registry import MetricsRegistry
from ..utils.logging import get_logger
from . import tenant as ts
from .policy import (
    TenantDemand,
    candidate_world_sizes,
    plan_allocations,
    priority_order,
    within_bounds,
)
from .tenant import TenantStateMachine

logger = get_logger()


class FleetInvariantError(DrillInvariantError):
    """A fleet-level recovery/scheduling invariant failed — a tenant ran
    outside its bounds, resumed from the wrong commit, or wedged."""


class _Tenant:
    """Supervisor-side runtime record for one tenant."""

    def __init__(
        self,
        index: int,
        cfg: FleetTenantConfig,
        base_config: dict[str, Any],
        *,
        seed: int,
        runs_root: Path,
        log_file_name: str,
    ) -> None:
        self.index = index
        self.cfg = cfg
        self.name = cfg.name
        self.base_config = base_config  # derived dict, cadence already pinned
        # The tenant's GLOBAL micro-batch, quoted at world size 1 (the
        # schema default when the config omits it): every launch divides
        # it by the granted world size so the elastic contract holds.
        self.global_micro = int(base_config["trainer"].get("micro_batch_size", 8))
        self.max_steps = int(base_config["trainer"]["max_steps"])
        self.save_every = int(base_config["trainer"]["save_every_steps"])
        self.log_every = int(base_config["trainer"]["log_every_steps"])
        self.demand_sizes = candidate_world_sizes(
            self.global_micro, cfg.min_devices, cfg.max_devices
        )
        self.sm = TenantStateMachine(cfg.name)
        self.run_dir = runs_root / cfg.name
        self.ckpt_dir = self.run_dir / "checkpoints"
        self.log_file = self.run_dir / "logs" / log_file_name
        # Seeded per-tenant backoff stream: deterministic respawn delays
        # per tenant, decorrelated across tenants (the retry_rng contract).
        self.rng = retry_rng(seed, index)
        self.proc: subprocess.Popen | None = None
        self.out_path: Path | None = None
        self.err_path: Path | None = None
        self.allocation = 0
        self.segments: list[dict[str, Any]] = []
        self.counts: Counter = Counter()
        self.exit_codes: list[int] = []
        # Allocation-0 windows in WALL-CLOCK time (time.time(), not
        # monotonic — the goodput ledger intersects them with timeline
        # segment boundaries, which are unix stamps). The state machine's
        # history records transitions without timestamps on purpose, so
        # the supervisor tracks the windows itself.
        self.suspension_windows: list[tuple[float, float]] = []
        self.suspended_since: float | None = None
        self.next_spawn_at = 0.0
        self.kill_deadline: float | None = None
        self.hard_evict_requested = False
        # Why the in-flight preemption was started: "evict" counts toward
        # the eviction metrics and the backoff ladder; "resize"/"suspend"
        # are routine scheduling moves and must not.
        self.preempt_kind = "evict"
        self.final_summary: dict[str, Any] | None = None
        # Lazily-built READ-side checkpoint manager for high-cadence
        # newest-commit probes: reusing one instance lets its
        # (path, size, mtime) verify cache skip re-hashing an unchanged
        # newest payload on every reconcile tick.
        self._probe_mgr: Any = None

    def probe_manager(self) -> Any:
        if self._probe_mgr is None:
            from ..training.checkpoint import CheckpointManager

            self._probe_mgr = CheckpointManager(self.ckpt_dir)
        return self._probe_mgr

    # ------------------------------------------------------------- queries

    def demand(self) -> TenantDemand:
        return TenantDemand(
            name=self.name,
            priority=self.cfg.priority,
            candidate_sizes=self.demand_sizes,
            runnable=not self.sm.terminal,
        )

    def live_allocation(self) -> int:
        """Devices this tenant's process currently occupies (a preempting
        process still holds its devices until it is reaped)."""
        return self.allocation if self.proc is not None else 0

    def heartbeat_age(self) -> float | None:
        hb = self.run_dir / "heartbeat"
        return heartbeat_age_seconds(hb) if hb.exists() else None

    def close_suspension(self) -> None:
        """Close the open allocation-0 window (tenant relaunching, or the
        report is being finalized)."""
        if self.suspended_since is not None:
            self.suspension_windows.append((self.suspended_since, time.time()))
            self.suspended_since = None

    def all_suspension_windows(self) -> list[tuple[float, float]]:
        """Closed windows plus the still-open one, if any, up to now."""
        out = list(self.suspension_windows)
        if self.suspended_since is not None:
            out.append((self.suspended_since, time.time()))
        return out

    def evictions_total(self) -> int:
        return (
            self.counts["evictions_graceful"]
            + self.counts["evictions_hard"]
            + self.counts["self_preemptions"]
            + self.counts["injected_kills"]
        )


class FleetSupervisor:
    """Schedules, preempts, resizes, and heals a fleet of train subprocesses.

    ``fault_provider(tenant_name, segment_index) -> dict | None`` lets the
    storm drill (fleet/chaos.py) install seeded in-config faults
    (``preempt_at_step``, ``kill_at_step``, ``kill_during_checkpoint``)
    into specific segments; production use leaves it None.
    """

    def __init__(
        self,
        cfg: RunConfig,
        resolved: dict[str, Any],
        *,
        work_dir: str | Path,
        seed: int = 0,
        max_steps: int | None = None,
        save_every: int | None = None,
        fault_provider: Callable[[str, int], dict[str, Any] | None] | None = None,
        extra_tenant_overrides: dict[str, Any] | None = None,
        fresh: bool = False,
        drill: bool = False,
    ) -> None:
        if not cfg.fleet.tenants:
            raise ValueError(
                "fleet mode needs at least one tenant under fleet.tenants "
                "(see configs/presets/gpt_fleet_smoke.yaml)"
            )
        if cfg.run.device != "cpu":
            raise ValueError(
                "the fleet supervisor schedules an EMULATED CPU device pool "
                "(per-tenant --xla_force_host_platform_device_count); set "
                "run.device: cpu — real accelerator fleets are the k8s "
                "layer's job (docs/k8s.md)"
            )
        self._cfg = cfg
        self._fleet = cfg.fleet
        self._seed = seed
        self._fault_provider = fault_provider
        self._capacity = cfg.fleet.pool_devices
        self.work_dir = Path(work_dir)
        self.work_dir.mkdir(parents=True, exist_ok=True)
        self._cfg_dir = self.work_dir / "cfg"
        self._seg_dir = self.work_dir / "segments"
        self._runs_root = self.work_dir / "runs"
        if fresh and self._runs_root.exists():
            # fresh=True is DRILL semantics (the storm re-runs from zero,
            # not from last drill's completed tenants). The production
            # default is False: a supervisor restart (k8s Job retry, OOM)
            # must NOT destroy tenants' committed checkpoints — every
            # tenant auto-resumes from its newest commit instead.
            import shutil

            shutil.rmtree(self._runs_root)
        for d in (self._cfg_dir, self._seg_dir, self._runs_root):
            d.mkdir(parents=True, exist_ok=True)

        self.metrics = MetricsRegistry(None)
        self._capacity_changes: list[tuple[float, int]] = []
        self._started_at: float | None = None
        self._endpoint = None
        self._last_textfile_write = 0.0
        # Fleet event timeline: launch/preempt/escalate instants land in
        # work_dir/telemetry/timeline.jsonl so ``llmtrain trace`` can line
        # supervisor disruptions up against serving and promote traces.
        self.timeline: Any = None
        try:
            from ..telemetry.timeline import EventTimeline

            tel_dir = self.work_dir / "telemetry"
            tel_dir.mkdir(parents=True, exist_ok=True)
            self.timeline = EventTimeline(tel_dir / "timeline.jsonl")
        except Exception:  # noqa: BLE001 — telemetry must not block launch
            self.timeline = None

        self.tenants: dict[str, _Tenant] = {}
        for i, tcfg in enumerate(cfg.fleet.tenants):
            base = self._derive_tenant_base(
                resolved,
                tcfg,
                max_steps=max_steps,
                save_every=save_every,
                extra_overrides=extra_tenant_overrides,
                drill=drill,
            )
            self.tenants[tcfg.name] = _Tenant(
                i,
                tcfg,
                base,
                seed=seed,
                runs_root=self._runs_root,
                log_file_name=base.get("logging", {}).get("file_name", "train.log"),
            )

    # ------------------------------------------------------------- derive

    def _derive_tenant_base(
        self,
        resolved: dict[str, Any],
        tcfg: FleetTenantConfig,
        *,
        max_steps: int | None,
        save_every: int | None,
        extra_overrides: dict[str, Any] | None,
        drill: bool = False,
    ) -> dict[str, Any]:
        """The tenant's world-size-independent config: base run + tenant
        overrides, fleet section stripped, output re-rooted, watchdog
        heartbeat enabled for the fleet health view, Prometheus off (every
        tenant binding one port would race it — the FLEET owns /metrics).

        Drill semantics (``drill=True``, or an explicit max_steps /
        save_every override) additionally pin the cadence so resume points
        align with log boundaries (the bitwise-trajectory precondition),
        push eval to the end, and disable trackers — segments get killed
        mid-flight and must not strand external state. A plain production
        ``llmtrain fleet`` run keeps each tenant's own save/eval cadence
        and tracker config untouched."""
        from ..resilience.harness import deep_merge

        pin = drill or max_steps is not None or save_every is not None
        base = dict(resolved)
        base.pop("fleet", None)
        overrides = dict(tcfg.overrides)
        if extra_overrides:
            overrides = deep_merge(overrides, extra_overrides)
        merged = deep_merge(base, overrides)
        trainer = merged.get("trainer", {})
        steps = int(max_steps or trainer.get("max_steps", 100))
        if pin:
            save = int(
                save_every
                or min(trainer.get("save_every_steps", steps), max(1, steps // 3))
            )
            save = max(1, min(save, steps))
            log_every = aligned_log_every(
                save, int(trainer.get("log_every_steps", 1))
            )
        else:
            save = int(trainer.get("save_every_steps", steps))
            log_every = int(trainer.get("log_every_steps", 1))
        derived = derive_segment_config(
            merged,
            root_dir=str(self._runs_root),
            max_steps=steps,
            save_every=save,
            log_every=log_every,
            faults=None,
        )
        if not pin:
            # Production tenants keep their configured eval cadence and
            # tracker; the drill derive disabled them above.
            derived["trainer"]["eval_every_steps"] = int(
                trainer.get("eval_every_steps", steps)
            )
            derived["mlflow"]["enabled"] = bool(
                (merged.get("mlflow") or {}).get("enabled", True)
            )
        # The fleet health view reads each tenant's watchdog beacon file;
        # the resume-selection invariant reads its train.log — and the
        # "resumed from ... at step N" line it parses is logged at INFO,
        # so the level is pinned (a WARNING-level tenant would suppress it
        # and fail the invariant on a correct resume).
        logging_cfg = derived.setdefault("logging", {})
        logging_cfg["log_to_file"] = True
        logging_cfg["level"] = "INFO"
        wd = derived.setdefault("resilience", {}).setdefault("watchdog", {})
        wd["enabled"] = True
        wd.setdefault("heartbeat_interval_sec", 0.2)
        return derived

    # ------------------------------------------------------------ plumbing

    def _child_env(self, allocation: int) -> dict[str, str]:
        """Child env emulating an ``allocation``-device slice of the pool:
        any inherited forced-device-count flag is REPLACED, not appended —
        XLA honors the first occurrence, and the test suite's own 8-device
        flag would otherwise leak into every tenant."""
        env = dict(os.environ)
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={allocation}")
        env["XLA_FLAGS"] = " ".join(flags)
        env["JAX_PLATFORMS"] = "cpu"
        return env

    def _write_segment_cfg(
        self, t: _Tenant, segment: int, allocation: int, faults: dict[str, Any] | None
    ) -> Path:
        cfg = json.loads(json.dumps(t.base_config))
        # Elastic contract: micro_batch_size x world size stays constant.
        cfg["trainer"]["micro_batch_size"] = t.global_micro // allocation
        cfg["resilience"]["faults"] = dict(faults or {})
        path = self._cfg_dir / f"{t.name}_seg{segment:03d}.yaml"
        path.write_text(yaml.safe_dump(cfg, sort_keys=False), encoding="utf-8")
        return path

    def devices_in_use(self) -> int:
        return sum(t.live_allocation() for t in self.tenants.values())

    @property
    def capacity(self) -> int:
        return self._capacity

    def set_capacity(self, devices: int) -> None:
        """Capacity shift (maintenance, a preemptible slice vanishing...):
        the next reconcile shrinks/suspends/regrows tenants to match."""
        if devices < 0:
            raise ValueError(f"capacity must be >= 0, got {devices}")
        if devices != self._capacity:
            logger.info(
                "fleet: capacity %d -> %d devices", self._capacity, devices
            )
            self._capacity = devices
            self._capacity_changes.append((time.monotonic(), devices))
            self.metrics.inc("fleet/capacity_changes")

    def request_eviction(self, name: str, mode: str = "graceful") -> bool:
        """Storm/operator-driven eviction of a running tenant. ``graceful``
        starts the SIGTERM→deadline→SIGKILL ladder; ``hard`` is an
        immediate SIGKILL (the crash-shaped eviction). Returns False when
        the tenant is not currently running."""
        t = self.tenants[name]
        if t.proc is None or t.sm.state != ts.RUNNING:
            return False
        if mode == "hard":
            t.hard_evict_requested = True
            t.proc.kill()
            logger.warning("fleet: hard-evicting tenant %s (SIGKILL)", name)
        else:
            self._preempt(t, reason="evict")
        return True

    # ------------------------------------------------------------ lifecycle

    def _launch(self, t: _Tenant, allocation: int) -> None:
        if not within_bounds(allocation, t.demand()) or allocation == 0:
            raise FleetInvariantError(
                f"tenant {t.name}: allocation {allocation} outside its "
                f"feasible sizes {t.demand_sizes} — the scheduler tried to "
                "run a tenant beyond its [min_devices, quota] bounds"
            )
        segment = len(t.segments)
        faults = (
            self._fault_provider(t.name, segment) if self._fault_provider else None
        )
        cfg_path = self._write_segment_cfg(t, segment, allocation, faults)
        # Per-tenant invariant (the chaos contract): BEFORE every respawn
        # the newest commit must load, and the segment must then resume
        # from exactly that step.
        expected_resume = (
            assert_newest_loadable(t.ckpt_dir, error_cls=FleetInvariantError)
            if t.ckpt_dir.is_dir()
            else 0
        )
        record: dict[str, Any] = {
            "segment": segment,
            "allocation": allocation,
            "faults": dict(faults or {}),
            "expected_resume": expected_resume,
            "log_offset": log_size(t.log_file),
            "started_at": time.monotonic(),
        }
        t.out_path = self._seg_dir / f"{t.name}_seg{segment:03d}.out"
        t.err_path = self._seg_dir / f"{t.name}_seg{segment:03d}.err"
        cmd = train_segment_command(cfg_path, t.name)
        with t.out_path.open("wb") as out, t.err_path.open("wb") as err:
            t.proc = subprocess.Popen(
                cmd, stdout=out, stderr=err, env=self._child_env(allocation)
            )
        if segment > 0:
            t.counts["respawns"] += 1
            self.metrics.inc("fleet/respawns")
        if t.allocation and allocation != t.allocation:
            t.counts["resizes"] += 1
            self.metrics.inc("fleet/resizes")
        t.allocation = allocation
        t.hard_evict_requested = False
        t.kill_deadline = None
        t.segments.append(record)
        t.sm.transition(ts.RUNNING, f"segment {segment} on {allocation} device(s)")
        self._fleet_instant(
            "fleet/launch",
            tenant=t.name,
            segment=segment,
            allocation=allocation,
            resume_step=expected_resume,
        )
        logger.info(
            "fleet: tenant %s segment %d launched on %d device(s)%s",
            t.name,
            segment,
            allocation,
            f" (resume from step {expected_resume})" if expected_resume else "",
        )

    def _fleet_instant(self, name: str, **args: Any) -> None:
        """Timeline instant + periodic flush, never raising: the
        supervisor's control loop must not die to a full disk."""
        if self.timeline is None:
            return
        try:
            self.timeline.instant(name, cat="fleet", **args)
            self.timeline.flush()
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass

    def _preempt(self, t: _Tenant, *, reason: str, kind: str = "evict") -> None:
        """Rung 1 of the escalation ladder: SIGTERM → the trainer's clean
        preemption save; the reconcile loop hard-kills past the deadline."""
        if t.proc is None:
            return
        t.preempt_kind = kind
        t.sm.transition(ts.PREEMPTING, reason)
        t.kill_deadline = time.monotonic() + self._fleet.preempt_grace_sec
        try:
            t.proc.send_signal(signal.SIGTERM)
        except OSError:  # already gone; the reaper will classify it
            pass
        self._fleet_instant(
            "fleet/preempt", tenant=t.name, reason=reason, kind=kind
        )
        logger.info("fleet: preempting tenant %s (%s)", t.name, reason)

    def _escalate_overdue(self, now: float) -> None:
        for t in self.tenants.values():
            if (
                t.sm.state == ts.PREEMPTING
                and t.proc is not None
                and t.kill_deadline is not None
                and now > t.kill_deadline
            ):
                logger.warning(
                    "fleet: tenant %s ignored SIGTERM for %.1fs — escalating "
                    "to SIGKILL",
                    t.name,
                    self._fleet.preempt_grace_sec,
                )
                t.counts["escalations"] += 1
                self.metrics.inc("fleet/escalations")
                t.proc.kill()
                t.kill_deadline = None
                self._fleet_instant(
                    "fleet/escalate",
                    tenant=t.name,
                    grace_sec=self._fleet.preempt_grace_sec,
                )

    def _backoff_delay(self, t: _Tenant) -> float:
        # Every disruption escalates the ladder — retryable exits (75/76)
        # included, or a hang-looping tenant would hammer the pool at the
        # base delay until its respawn budget ran out.
        attempt = max(1, self._disruptions(t))
        cap = min(
            self._fleet.respawn_backoff_max_sec,
            self._fleet.respawn_backoff_base_sec * (2 ** (attempt - 1)),
        )
        return t.rng.uniform(0.0, cap)

    # ------------------------------------------------------------- reaping

    def _reap(self, t: _Tenant) -> None:
        """Classify a finished segment, check the per-tenant recovery
        invariants, and route the tenant to its next state."""
        proc = t.proc
        assert proc is not None
        rc = proc.returncode
        t.proc = None
        t.exit_codes.append(rc)
        record = t.segments[-1]
        record["returncode"] = rc
        record["wall_sec"] = round(time.monotonic() - record["started_at"], 2)
        stdout = t.out_path.read_text(errors="replace") if t.out_path else ""
        stderr = t.err_path.read_text(errors="replace") if t.err_path else ""
        was_preempting = t.sm.state == ts.PREEMPTING

        # Invariant 1: restorability survived whatever ended the segment.
        if t.ckpt_dir.is_dir():
            record["newest_committed_step"] = assert_newest_loadable(
                t.ckpt_dir, error_cls=FleetInvariantError
            )
        # Invariant 2: the segment resumed from the newest valid commit
        # observed at launch — a torn/uncommitted selection fails here.
        # A segment that died BEFORE logging its restore point (eviction
        # during interpreter startup: rc != 0, nothing logged) selected
        # nothing, so the invariant is vacuous for it — but a segment that
        # ran (exit 0, or far enough to log) must show exactly the
        # expected step.
        observed = segment_resumed_step(t.log_file, record["log_offset"])
        record["observed_resume"] = observed
        expected = record["expected_resume"]
        # No check when expected == 0: a fresh segment can still log a
        # "resumed from" line legitimately — a spike rollback restores a
        # checkpoint the segment committed itself mid-run.
        if observed is None and expected > 0 and rc != 0:
            record["died_before_resume"] = True
            t.counts["preresume_deaths"] += 1
        elif expected > 0 and observed != expected:
            raise FleetInvariantError(
                f"tenant {t.name} segment {record['segment']} resumed from "
                f"step {observed}, expected the newest valid commit "
                f"{expected} — selection picked a checkpoint it should not "
                "have"
            )

        faults = record.get("faults") or {}
        if rc == 0:
            summary = summary_of(
                stdout,
                returncode=rc,
                stderr=stderr,
                label=f"tenant {t.name} segment {record['segment']}",
                error_cls=FleetInvariantError,
            )
            record["summary"] = summary
            result = summary.get("train_result") or {}
            if result.get("preempted"):
                record["preempted"] = True
                if was_preempting and t.preempt_kind != "evict":
                    # Routine scheduling moves (resize/suspend) are not
                    # evictions: they have their own counters and must
                    # not escalate the respawn-backoff ladder.
                    t.counts[f"preemptions_{t.preempt_kind}"] += 1
                elif was_preempting:
                    t.counts["evictions_graceful"] += 1
                    self.metrics.inc("fleet/evictions")
                elif "preempt_at_step" in faults or "sigterm_at_step" in faults:
                    t.counts["self_preemptions"] += 1
                    self.metrics.inc("fleet/evictions")
                else:  # an external SIGTERM we did not send (pod drain...)
                    t.counts["evictions_graceful"] += 1
                    self.metrics.inc("fleet/evictions")
                self._to_backoff(t, "preempted cleanly")
            elif int(result.get("final_step") or 0) >= t.max_steps:
                record["completed"] = True
                t.final_summary = summary
                t.sm.transition(ts.COMPLETED, f"exit 0 at step {result.get('final_step')}")
                logger.info(
                    "fleet: tenant %s COMPLETED (final_loss=%s, %d eviction(s), "
                    "%d respawn(s))",
                    t.name,
                    result.get("final_loss"),
                    t.evictions_total(),
                    t.counts["respawns"],
                )
            else:
                t.sm.transition(
                    ts.FAILED,
                    f"exit 0 at step {result.get('final_step')} before "
                    f"max_steps {t.max_steps}",
                )
        elif rc in KILL_RETURNCODES:
            if was_preempting and t.preempt_kind != "evict":
                t.counts[f"preemptions_{t.preempt_kind}"] += 1
                t.counts["escalated_preemptions"] += 1
            elif was_preempting:
                t.counts["evictions_hard"] += 1  # ladder escalated
                self.metrics.inc("fleet/evictions")
            elif t.hard_evict_requested:
                t.counts["evictions_hard"] += 1
                self.metrics.inc("fleet/evictions")
            elif "kill_at_step" in faults or faults.get("kill_during_checkpoint"):
                t.counts["injected_kills"] += 1
                self.metrics.inc("fleet/evictions")
            else:
                t.counts["crashes"] += 1
                self.metrics.inc("fleet/crashes")
            self._to_backoff(t, f"killed (exit {rc})")
        elif rc in TERM_RETURNCODES:
            # SIGTERM landed before the trainer could turn it into a clean
            # preemption exit (interpreter startup, early init): the commit
            # protocol still guarantees the respawn, it just cost progress.
            if was_preempting and t.preempt_kind != "evict":
                t.counts[f"preemptions_{t.preempt_kind}"] += 1
            elif was_preempting:
                t.counts["evictions_hard"] += 1
                self.metrics.inc("fleet/evictions")
            else:
                t.counts["crashes"] += 1
                self.metrics.inc("fleet/crashes")
            self._to_backoff(t, f"SIGTERM died uncleanly (exit {rc})")
        elif rc in RETRYABLE_EXIT_CODES:
            t.counts["retryable_exits"] += 1
            self.metrics.inc("fleet/retryable_exits")
            self._to_backoff(t, f"retryable exit {rc}")
        else:
            t.sm.transition(ts.FAILED, f"fatal exit {rc}")
            logger.error(
                "fleet: tenant %s FAILED (exit %d); stderr tail: %s",
                t.name,
                rc,
                stderr[-1000:],
            )

    def _disruptions(self, t: _Tenant) -> int:
        """Real disruptions (evictions + crashes + retryable exits) — the
        measure behind both the backoff ladder and the crash-loop budget.
        Scheduler-initiated resize/suspend relaunches are routine moves
        and count toward neither: a healthy tenant on a capacity-flapping
        pool must never be failed for the scheduler's own churn."""
        return (
            t.evictions_total()
            + t.counts["crashes"]
            + t.counts["retryable_exits"]
        )

    def _to_backoff(self, t: _Tenant, reason: str) -> None:
        if self._disruptions(t) >= self._fleet.max_respawns_per_tenant:
            t.sm.transition(
                ts.FAILED,
                f"respawn budget ({self._fleet.max_respawns_per_tenant}) "
                "exhausted",
            )
            return
        delay = self._backoff_delay(t)
        t.next_spawn_at = time.monotonic() + delay
        t.sm.transition(ts.BACKOFF, f"{reason}; respawn in {delay:.2f}s")

    # ------------------------------------------------------------ the loop

    def _reconcile(self, now: float) -> None:
        plan = plan_allocations(
            self._capacity, [t.demand() for t in self.tenants.values()]
        )
        targets = plan.allocations
        order = priority_order(
            [t.demand() for t in self.tenants.values() if not t.sm.terminal]
        )
        for d in order:
            t = self.tenants[d.name]
            target = targets.get(t.name, 0)
            state = t.sm.state
            if state == ts.RUNNING and target != t.allocation:
                self._preempt(
                    t,
                    reason=(
                        f"resize {t.allocation} -> {target}"
                        if target
                        else "pool shrank below demand — suspending"
                    ),
                    kind="resize" if target else "suspend",
                )
            elif state == ts.BACKOFF:
                if target == 0:
                    t.counts["suspensions"] += 1
                    self.metrics.inc("fleet/suspensions")
                    t.suspended_since = time.time()
                    t.sm.transition(ts.SUSPENDED, "no capacity granted")
                elif now >= t.next_spawn_at and self._fits(t, target):
                    self._launch(t, target)
            elif state == ts.SUSPENDED:
                # next_spawn_at still applies: capacity returning must not
                # relaunch every suspended tenant in the same tick — the
                # per-tenant jitter schedule survives the suspension.
                if (
                    target > 0
                    and now >= t.next_spawn_at
                    and self._fits(t, target)
                ):
                    t.close_suspension()
                    self._launch(t, target)
            elif state == ts.QUEUED:
                if target > 0 and self._fits(t, target):
                    self._launch(t, target)

    def _fits(self, t: _Tenant, target: int) -> bool:
        """Never launch beyond capacity: devices freed by a preempting
        tenant only become launchable once its process is reaped."""
        return self.devices_in_use() - t.live_allocation() + target <= self._capacity

    def _check_segment_timeouts(self, now: float) -> None:
        for t in self.tenants.values():
            if t.proc is None or not t.segments:
                continue
            started = t.segments[-1]["started_at"]
            if now - started > self._fleet.segment_timeout_sec:
                t.proc.kill()
                t.proc.wait(timeout=10)
                raise FleetInvariantError(
                    f"tenant {t.name} segment {len(t.segments) - 1} exceeded "
                    f"{self._fleet.segment_timeout_sec:.0f}s — a scheduled "
                    "tenant must make progress, not wedge"
                )

    def _render_metrics(self) -> str:
        """One rendering of the fleet's Prometheus view — the /metrics
        endpoint, the textfile snapshot, and the final flush all serve
        exactly this, so the three transports cannot diverge.

        The fleet's own gauges come first; below them, every tenant's
        textfile snapshot (``{run_dir}/telemetry/metrics.prom``, written
        by the tenant's Telemetry flush) is federated in with a
        ``tenant="<name>"`` label, so one scrape of the supervisor covers
        the whole fleet without per-tenant service discovery."""
        own = render_prometheus(
            self.metrics.latest(),
            self.metrics.counters(),
            info={"run_name": self._cfg.run.name, "mode": "fleet"},
        )
        sources: dict[str, str] = {}
        for t in self.tenants.values():
            prom = t.run_dir / "telemetry" / "metrics.prom"
            try:
                sources[t.name] = prom.read_text(encoding="utf-8")
            except OSError:
                continue  # tenant not started yet / already cleaned up
        federated = federate_prometheus(sources)
        return own + federated if federated else own

    def _publish_metrics(self) -> None:
        states = Counter(t.sm.state for t in self.tenants.values())
        now = time.monotonic()
        stale = 0
        for t in self.tenants.values():
            if t.sm.state != ts.RUNNING or not t.segments:
                continue
            age = t.heartbeat_age()
            if age is None:
                # No beacon file at all: healthy during startup, but a
                # tenant that has run past the staleness window without
                # EVER heartbeating is exactly the hung-from-birth case
                # this gauge exists to surface.
                running_for = now - t.segments[-1]["started_at"]
                if running_for > self._fleet.heartbeat_stale_sec:
                    stale += 1
            elif age > self._fleet.heartbeat_stale_sec:
                stale += 1
        self.metrics.publish(
            {
                "fleet/pool_devices": float(self._capacity),
                "fleet/devices_in_use": float(self.devices_in_use()),
                "fleet/tenants_running": float(
                    states[ts.RUNNING] + states[ts.PREEMPTING]
                ),
                "fleet/tenants_suspended": float(states[ts.SUSPENDED]),
                "fleet/tenants_backoff": float(states[ts.BACKOFF]),
                "fleet/tenants_completed": float(states[ts.COMPLETED]),
                "fleet/tenants_failed": float(states[ts.FAILED]),
                "fleet/heartbeat_stale": float(stale),
            }
        )
        # Gauges update in-memory every tick; the textfile (a full render
        # + atomic tmp/rename on the runs volume) follows the PR-4 "one
        # flush per interval" spirit — scrapers poll in seconds, not at
        # the 10 Hz reconcile cadence.
        if now - self._last_textfile_write >= 1.0:
            self._last_textfile_write = now
            write_textfile(
                self.work_dir / "fleet_metrics.prom", self._render_metrics()
            )

    def run(
        self,
        *,
        timeout_sec: float = 1800.0,
        on_tick: Callable[["FleetSupervisor"], None] | None = None,
    ) -> dict[str, Any]:
        """Drive the fleet until every tenant is terminal; returns (and
        writes) the fleet report. ``on_tick`` is the storm drill's hook —
        it may shift capacity and request evictions between reconciles."""
        self._started_at = time.monotonic()
        deadline = self._started_at + timeout_sec
        if self._cfg.telemetry.prometheus:
            from ..telemetry.prometheus import PrometheusEndpoint

            try:
                self._endpoint = PrometheusEndpoint(
                    self._render_metrics,
                    host=self._cfg.telemetry.prometheus_host,
                    port=self._cfg.telemetry.prometheus_port,
                )
                logger.info(
                    "fleet: /metrics endpoint on port %d", self._endpoint.port
                )
            except OSError as exc:
                logger.warning("fleet: /metrics endpoint unavailable (%s)", exc)
        try:
            while not all(t.sm.terminal for t in self.tenants.values()):
                now = time.monotonic()
                if now > deadline:
                    raise FleetInvariantError(
                        f"fleet did not converge within {timeout_sec:.0f}s: "
                        + ", ".join(
                            f"{t.name}={t.sm.state}" for t in self.tenants.values()
                        )
                    )
                for t in self.tenants.values():
                    if t.proc is not None and t.proc.poll() is not None:
                        self._reap(t)
                self._check_segment_timeouts(now)
                self._escalate_overdue(now)
                self._reconcile(now)
                self._publish_metrics()
                if on_tick is not None:
                    on_tick(self)
                time.sleep(self._fleet.tick_sec)
            return self.finalize()
        finally:
            self._shutdown()

    def _shutdown(self) -> None:
        for t in self.tenants.values():
            if t.proc is not None:
                t.proc.kill()
                try:
                    t.proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — teardown must not raise
                    pass
                t.proc = None
        if self._endpoint is not None:
            self._endpoint.close()
            self._endpoint = None
        if self.timeline is not None:
            try:
                self.timeline.flush()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    # -------------------------------------------------------------- report

    def newest_commit(self, name: str) -> int:
        """Newest COMMITTED step for a tenant — manifest-only and
        side-effect-free, because callers (the storm controller, health
        views) probe tenants whose writer is alive mid-commit."""
        t = self.tenants[name]
        if not t.ckpt_dir.is_dir():
            return 0
        return newest_committed_step_live(t.ckpt_dir, mgr=t.probe_manager())

    def _tenant_report(self, t: _Tenant) -> dict[str, Any]:
        result = (t.final_summary or {}).get("train_result") or {}
        report_path = t.run_dir / "report.json"
        resume_count = 0
        if report_path.is_file():
            try:
                resil = json.loads(report_path.read_text()).get("resilience") or {}
                resume_count = int(resil.get("resume_count", 0))
            except (OSError, ValueError):
                pass
        hb = t.heartbeat_age()
        # Per-tenant goodput ledger over the tenant's OWN durable run-dir
        # artifacts, with the supervisor's wall-clock allocation-0 windows
        # carved out of restart_overhead as `suspended` — the PR-8
        # eviction/respawn/suspension COUNTS become seconds here.
        goodput: dict[str, Any] | None = None
        try:
            from ..telemetry.goodput import compute_goodput

            goodput = compute_goodput(
                t.run_dir, suspensions=t.all_suspension_windows()
            )
        except Exception as exc:  # noqa: BLE001 — reporting must not fail the fleet
            logger.warning("fleet: goodput ledger for %s failed: %s", t.name, exc)
        return {
            "goodput": goodput,
            "state": t.sm.state,
            "priority": t.cfg.priority,
            "min_devices": t.cfg.min_devices,
            "max_devices": t.cfg.max_devices,
            "feasible_world_sizes": list(t.demand_sizes),
            "segments": len(t.segments),
            "allocations": [s["allocation"] for s in t.segments],
            "evictions": {
                "graceful": t.counts["evictions_graceful"],
                "hard": t.counts["evictions_hard"],
                "self_preempt": t.counts["self_preemptions"],
                "injected_kill": t.counts["injected_kills"],
                "total": t.evictions_total(),
            },
            "escalations": t.counts["escalations"],
            "scheduling_preemptions": {
                "resize": t.counts["preemptions_resize"],
                "suspend": t.counts["preemptions_suspend"],
                "escalated": t.counts["escalated_preemptions"],
            },
            "respawns": t.counts["respawns"],
            "resizes": t.counts["resizes"],
            "suspensions": t.counts["suspensions"],
            "crashes": t.counts["crashes"],
            "retryable_exits": t.counts["retryable_exits"],
            "exit_codes": list(t.exit_codes),
            "resume_count": resume_count,
            "final_step": result.get("final_step"),
            "final_loss": result.get("final_loss"),
            "heartbeat_age_sec": round(hb, 3) if hb is not None else None,
            "report_json": str(report_path) if report_path.is_file() else None,
            "history": [list(h) for h in t.sm.history],
        }

    def finalize(self) -> dict[str, Any]:
        """Aggregate the fleet view and write fleet_report.json/.md."""
        tenants = {
            name: self._tenant_report(t) for name, t in self.tenants.items()
        }
        wall = (
            round(time.monotonic() - self._started_at, 2)
            if self._started_at is not None
            else 0.0
        )
        report = {
            "pool_devices": self._fleet.pool_devices,
            "final_capacity": self._capacity,
            "capacity_changes": len(self._capacity_changes),
            "seed": self._seed,
            "wall_time_sec": wall,
            "tenants": tenants,
            "totals": {
                "evictions": sum(v["evictions"]["total"] for v in tenants.values()),
                "escalations": sum(v["escalations"] for v in tenants.values()),
                "respawns": sum(v["respawns"] for v in tenants.values()),
                "resizes": sum(v["resizes"] for v in tenants.values()),
                "suspensions": sum(v["suspensions"] for v in tenants.values()),
                "crashes": sum(v["crashes"] for v in tenants.values()),
                "completed": sum(
                    1 for v in tenants.values() if v["state"] == ts.COMPLETED
                ),
                "failed": sum(
                    1 for v in tenants.values() if v["state"] == ts.FAILED
                ),
            },
        }
        # Fleet-wide goodput: second-weighted across tenants (sum of
        # productive seconds over sum of wall seconds), not a mean of
        # per-tenant fractions — a tiny tenant must not swing the fleet.
        ledgers = [
            v["goodput"] for v in tenants.values() if v.get("goodput")
        ]
        goodput_totals: dict[str, float] = {}
        for ledger in ledgers:
            for cat, sec in ledger["categories"].items():
                goodput_totals[cat] = round(
                    goodput_totals.get(cat, 0.0) + float(sec), 3
                )
        fleet_wall = sum(float(x["wall_clock_sec"]) for x in ledgers)
        fleet_frac = (
            goodput_totals.get("productive_train", 0.0) / fleet_wall
            if fleet_wall > 0
            else 0.0
        )
        report["totals"]["goodput_sec"] = goodput_totals
        report["totals"]["goodput_wall_clock_sec"] = round(fleet_wall, 3)
        report["totals"]["goodput_frac"] = round(fleet_frac, 6)
        self.metrics.publish(
            {
                "fleet/goodput_frac": fleet_frac,
                "fleet/goodput_wall_clock_sec": fleet_wall,
                **{
                    f"fleet/goodput_{cat}_sec": sec
                    for cat, sec in goodput_totals.items()
                },
            }
        )
        # Final metrics snapshot, unthrottled: the textfile a collector
        # reads after the run must reflect the terminal state.
        write_textfile(
            self.work_dir / "fleet_metrics.prom", self._render_metrics()
        )
        (self.work_dir / "fleet_report.json").write_text(
            json.dumps(report, indent=2), encoding="utf-8"
        )
        (self.work_dir / "fleet_report.md").write_text(
            render_fleet_report_md(report), encoding="utf-8"
        )
        return report


def render_fleet_report_md(report: dict[str, Any]) -> str:
    """Human-readable twin of fleet_report.json."""
    lines = [
        "# Fleet report",
        "",
        f"- pool: {report['pool_devices']} device(s), "
        f"{report['capacity_changes']} capacity change(s)",
        f"- wall time: {report['wall_time_sec']}s (seed {report['seed']})",
        f"- tenants: {len(report['tenants'])} "
        f"({report['totals']['completed']} completed, "
        f"{report['totals']['failed']} failed)",
        f"- evictions: {report['totals']['evictions']} "
        f"(escalated to SIGKILL: {report['totals']['escalations']}), "
        f"respawns: {report['totals']['respawns']}, "
        f"resizes: {report['totals']['resizes']}, "
        f"suspensions: {report['totals']['suspensions']}",
    ]
    if "goodput_frac" in report["totals"]:
        lines.append(
            f"- fleet goodput: {report['totals']['goodput_frac']:.1%} of "
            f"{report['totals']['goodput_wall_clock_sec']}s tenant "
            "wall-clock (second-weighted)"
        )
    lines += [
        "",
        "| tenant | state | prio | devices | segs | evict | respawn | "
        "resume_count | final_step | final_loss | goodput |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for name in sorted(report["tenants"]):
        v = report["tenants"][name]
        ledger = v.get("goodput") or {}
        goodput = (
            f"{ledger['goodput_frac']:.1%}" if ledger else "n/a"
        )
        lines.append(
            f"| {name} | {v['state']} | {v['priority']} | "
            f"[{v['min_devices']},{v['max_devices']}] | {v['segments']} | "
            f"{v['evictions']['total']} | {v['respawns']} | "
            f"{v['resume_count']} | {v['final_step']} | {v['final_loss']} | "
            f"{goodput} |"
        )
    return "\n".join(lines) + "\n"


__all__ = ["FleetInvariantError", "FleetSupervisor", "render_fleet_report_md"]
