"""Seeded preemption storm over the fleet: the scheduler's acceptance drill.

The single-job chaos harness (resilience/chaos.py) proves "die anywhere,
resume, trajectory preserved" for one run; this drill proves the fleet
supervisor preserves that contract for EVERY tenant at once while it is
actively scheduling against them. One seeded storm delivers:

* a **scripted capacity drop** — the pool shrinks below total demand once
  every tenant has a commit, forcing shrink-to-min / suspend decisions,
  then recovers (tenants grow back through elastic resume);
* **seeded random evictions** — per-tenant in-config faults
  (``preempt_at_step`` for step-exact graceful self-preemption,
  ``kill_at_step`` for the crash-shaped eviction) plus supervisor-
  delivered external evictions through the SIGTERM→deadline→SIGKILL
  ladder;
* **one mid-checkpoint kill** — ``kill_during_checkpoint`` dies between a
  tenant's staged checkpoint files and its manifest publish, the torn
  window the atomic commit protocol exists for.

After the storm, every tenant must have COMPLETED, and for each tenant:
the logged loss trajectory is bitwise-equal to an uninterrupted
per-tenant reference at every comparable step, and the final
params/opt_state trees are bitwise-identical. The per-cycle chaos
invariants (newest commit loadable, resumed-from-newest-valid, no torn
selection) are asserted by the supervisor at every launch/reap, and
tenant device bounds are asserted at every launch.

Two measured caveats, both counted in the result rather than silently
absorbed:

* A tenant gracefully preempted MID log interval resumes from its
  preemption save, so the first interval it logs after the resume
  averages fewer steps than the reference's same-step interval. The
  per-step losses are still bitwise-identical — only that one partial
  MEAN is not comparable — so the comparison skips exactly that boundary
  (``skipped_partial_points``).
* Bitwise parity holds for tenants whose world size never changed.
  Running the SAME math on a different device count reorders the
  floating-point reductions (measured on this backend: fresh ws1 vs ws2
  runs agree bitwise for several steps, then drift by rounding), so a
  tenant the scheduler RESIZED mid-storm is compared against its
  reference at ``resize_loss_rtol`` instead — the elastic-resume
  contract's reduction-order noise bound — and the result records which
  parity each tenant was held to. The acceptance drill uses fixed-size
  tenants (min_devices == max_devices) so every tenant is bitwise.

Driven by ``llmtrain fleet --storm`` and ``make verify-fleet``.
"""

from __future__ import annotations

import json
import random
import time
from pathlib import Path
from typing import Any

import yaml

from ..resilience.harness import (
    run_train_segment,
    summary_of,
    trees_bitwise_equal,
)
from ..utils.logging import get_logger
from . import tenant as ts
from .supervisor import FleetInvariantError, FleetSupervisor

logger = get_logger()


def partial_interval_step(resumed_step: int | None, log_every: int) -> int | None:
    """The single log boundary whose interval mean is NOT comparable after
    a mid-interval resume: the first boundary after ``resumed_step`` when
    the resume point is not itself a boundary. None when every logged
    interval is a full window (aligned resume, or no resume at all)."""
    if not resumed_step or resumed_step % log_every == 0:
        return None
    return resumed_step + (log_every - resumed_step % log_every)


def _storm_fault_plan(
    sup: FleetSupervisor, seed: int
) -> tuple[dict[str, dict[str, Any]], str]:
    """Seeded segment-0 fault per tenant, rotating the three disruption
    shapes so a ≥3-tenant storm always contains a graceful preemption, a
    hard kill, and the mid-checkpoint kill. Steps land past the first
    save boundary so every tenant has a commit to resume from (which is
    what makes ``resume_count >= 1`` assertable per tenant)."""
    rng = random.Random(f"llmtrain-fleet-storm:{seed}")
    kinds = ("preempt", "kill_during_checkpoint", "kill")
    plan: dict[str, dict[str, Any]] = {}
    midckpt_tenant = ""
    for i, (name, t) in enumerate(sorted(sup.tenants.items())):
        kind = kinds[i % len(kinds)]
        # The fault must land after the first commit (so the respawn has
        # something to resume — the resume_count >= 1 assertion) and clear
        # of the final log interval (a disruption there leaves the
        # completing segment with ONLY the partial boundary to log — zero
        # comparable trajectory points on a correct run). A cadence with
        # no such window is a config problem, not a recovery failure:
        # reject it up front with the remediation.
        lo = t.save_every + 1
        hi = t.max_steps - t.log_every - 1
        if lo > hi:
            raise ValueError(
                f"tenant {name}: no storm-fault window between the first "
                f"save boundary ({t.save_every}) and the final log "
                f"interval (max_steps {t.max_steps}, log_every "
                f"{t.log_every}) — lower --save-every or raise --max-steps"
            )
        if kind == "preempt":
            plan[name] = {"preempt_at_step": rng.randint(lo, hi)}
        elif kind == "kill":
            plan[name] = {"kill_at_step": rng.randint(lo, hi)}
        else:
            # Die INSIDE the async write of the second save boundary: the
            # first boundary's commit is the guaranteed fallback. A cadence
            # with only one boundary would leave the killed tenant nothing
            # to resume from (falsely failing the resume_count assertion) —
            # reject it up front like the window check above.
            if 2 * t.save_every > t.max_steps:
                raise ValueError(
                    f"tenant {name}: the mid-checkpoint kill needs at least "
                    f"two save boundaries within max_steps ({t.max_steps}) "
                    f"at save_every {t.save_every} — lower --save-every or "
                    "raise --max-steps"
                )
            boundary = 2 * t.save_every
            plan[name] = {"kill_at_step": boundary, "kill_during_checkpoint": True}
            midckpt_tenant = name
    # run_fleet_storm requires >= 2 tenants, so the rotation always
    # assigned kinds[1] (the mid-checkpoint kill) to somebody.
    assert midckpt_tenant, "storm fault rotation must place the mid-ckpt kill"
    return plan, midckpt_tenant


class _StormController:
    """on_tick controller: capacity drop + external evictions, gated on
    observed commit progress so every disruption lands on a tenant that
    has something real to lose (and therefore something real to resume)."""

    def __init__(
        self,
        sup: FleetSupervisor,
        seed: int,
        *,
        drop_to: int,
        hold_sec: float,
        external_evictions: int,
        min_run_sec: float = 2.5,
    ) -> None:
        rng = random.Random(f"llmtrain-fleet-storm-ctl:{seed}")
        names = sorted(sup.tenants)
        self._hold_sec = hold_sec
        self._drop_to = drop_to
        self._dropped_at: float | None = None
        # A pool already at the drop target has no capacity cycle to run
        # (a 2-tenant pool of 1): mark the cycle done so the storm still
        # converges; the drill only asserts the cycle when one was due.
        self._restored = drop_to >= sup.capacity
        self._pool = sup.capacity
        # An external eviction waits for the target segment to be genuinely
        # mid-run (past interpreter/jax startup) so it interrupts real
        # training progress, not a process that has not restored yet.
        self._min_run_sec = min_run_sec
        # (tenant, mode) external evictions, distinct tenants first.
        picks: list[tuple[str, str]] = []
        pool = list(names)
        for _ in range(external_evictions):
            if not pool:
                pool = list(names)
            name = pool.pop(rng.randrange(len(pool)))
            picks.append((name, rng.choice(("graceful", "hard"))))
        self._evictions = picks
        self._evict_gate: dict[str, int] = {}

    def __call__(self, sup: FleetSupervisor) -> None:
        now = time.monotonic()
        # Scripted capacity drop once every tenant holds a commit.
        if not self._restored:
            if self._dropped_at is None:
                if all(sup.newest_commit(n) > 0 for n in sup.tenants):
                    sup.set_capacity(self._drop_to)
                    self._dropped_at = now
            elif now - self._dropped_at >= self._hold_sec:
                sup.set_capacity(self._pool)
                self._restored = True
        # External evictions: fire each once its tenant is running with
        # fresh commit progress since the previous disruption. Segment 0
        # is off-limits — it belongs to the tenant's seeded in-config
        # fault, and racing an external SIGKILL against an injected one
        # would make the eviction attribution (and the drill's
        # mid-checkpoint-kill assertion) nondeterministic.
        remaining: list[tuple[str, str]] = []
        for name, mode in self._evictions:
            t = sup.tenants[name]
            if t.sm.terminal:
                continue  # completed before we got to it — storm moves on
            gate = self._evict_gate.get(name, 0)
            if (
                t.sm.state == ts.RUNNING
                and len(t.segments) >= 2
                and now - t.segments[-1]["started_at"] >= self._min_run_sec
                and sup.newest_commit(name) > gate
                and sup.request_eviction(name, mode)
            ):
                self._evict_gate[name] = sup.newest_commit(name)
                logger.info(
                    "storm: external %s eviction delivered to tenant %s",
                    mode,
                    name,
                )
                continue
            remaining.append((name, mode))
        self._evictions = remaining

    @property
    def capacity_cycle_done(self) -> bool:
        return self._restored


def run_fleet_storm(
    config_path: str | Path,
    *,
    seed: int = 0,
    max_steps: int | None = None,
    save_every: int | None = None,
    work_dir: str | Path | None = None,
    timeout_sec: float = 900.0,
    step_delay_sec: float = 0.15,
    capacity_drop_hold_sec: float = 2.0,
    external_evictions: int = 2,
    resize_loss_rtol: float = 0.02,
) -> dict[str, Any]:
    """Run the seeded preemption storm; returns the result record.

    Raises :class:`FleetInvariantError` the moment any per-tenant recovery
    invariant, bounds invariant, or parity check fails. Tenants whose
    world size never changed are held to BITWISE parity; resized tenants
    to ``resize_loss_rtol`` (see module doc).
    """
    from ..config import load_and_validate_config
    from ..training.checkpoint import CheckpointManager

    cfg, _, resolved = load_and_validate_config(str(config_path))
    if len(cfg.fleet.tenants) < 2:
        raise ValueError(
            "the preemption storm needs at least 2 fleet tenants "
            f"(got {len(cfg.fleet.tenants)})"
        )
    work = (
        Path(work_dir)
        if work_dir is not None
        else Path(cfg.output.root_dir) / f"fleet_storm_{cfg.run.name}_s{seed}"
    )
    started = time.perf_counter()

    # Tenants are throttled (trainer.extra.step_delay_sec) so externally
    # delivered evictions and the capacity drop reliably land while the
    # tiny smoke models are mid-run; the throttle changes wall-clock only,
    # never the math, so references run unthrottled.
    sup = FleetSupervisor(
        cfg,
        resolved,
        work_dir=work,
        seed=seed,
        max_steps=max_steps,
        save_every=save_every,
        extra_tenant_overrides={
            "trainer": {"extra": {"step_delay_sec": step_delay_sec}}
        },
        # Drill semantics: pinned cadence + trackers off, and a rerun with
        # the same seed starts from zero — auto-resuming last drill's
        # completed tenants would log empty trajectories and falsely fail
        # the bitwise comparison.
        fresh=True,
        drill=True,
    )

    # ------------------------------------------------- per-tenant references
    # Each reference runs at the tenant's INITIAL planned allocation (the
    # deterministic full-capacity plan), so a tenant the storm never
    # resizes is bit-for-bit comparable against it.
    from .policy import plan_allocations

    initial_plan = plan_allocations(
        cfg.fleet.pool_devices, [t.demand() for t in sup.tenants.values()]
    ).allocations
    ref_allocs = {
        name: (initial_plan.get(name) or t.demand_sizes[0])
        for name, t in sup.tenants.items()
    }
    # Build the seeded fault plan BEFORE the references run: an infeasible
    # cadence must be rejected up front, not after minutes of reference
    # wall-clock.
    fault_plan, midckpt_tenant = _storm_fault_plan(sup, seed)
    refs_root = work / "refs"
    if refs_root.exists():
        import shutil

        shutil.rmtree(refs_root)
    refs_root.mkdir(parents=True, exist_ok=True)

    def run_reference(name: str) -> dict[str, Any]:
        t = sup.tenants[name]
        ref_cfg = json.loads(json.dumps(t.base_config))
        ref_alloc = ref_allocs[name]
        ref_cfg["trainer"]["micro_batch_size"] = t.global_micro // ref_alloc
        ref_cfg["trainer"].setdefault("extra", {})["step_delay_sec"] = 0
        ref_cfg["output"]["root_dir"] = str(refs_root)
        ref_path = work / "cfg" / f"{name}_reference.yaml"
        ref_path.parent.mkdir(parents=True, exist_ok=True)
        ref_path.write_text(yaml.safe_dump(ref_cfg, sort_keys=False), encoding="utf-8")
        proc = run_train_segment(
            ref_path,
            name,
            timeout_sec=timeout_sec,
            label=f"{name} reference",
            error_cls=FleetInvariantError,
            env=sup._child_env(ref_alloc),
        )
        if proc.returncode != 0:
            raise FleetInvariantError(
                f"uninterrupted reference for tenant {name} failed (exit "
                f"{proc.returncode}): {(proc.stderr or '')[-2000:]}"
            )
        return summary_of(
            proc.stdout,
            returncode=proc.returncode,
            stderr=proc.stderr,
            label=f"{name} reference",
            error_cls=FleetInvariantError,
        )

    # The references are independent subprocesses with separate run dirs:
    # run them concurrently (the threads only block on child waits) — the
    # deterministic math cannot depend on host scheduling.
    from concurrent.futures import ThreadPoolExecutor

    names = sorted(sup.tenants)
    with ThreadPoolExecutor(max_workers=len(names)) as pool:
        ref_summaries: dict[str, dict[str, Any]] = dict(
            zip(names, pool.map(run_reference, names))
        )

    # ------------------------------------------------------------- the storm
    # A planned fault stays installed across respawns until it actually
    # FIRED (another storm event — a capacity-drop suspension, an external
    # eviction — may end the segment first) or until observed progress
    # makes it unfirable (the step-exact injections never re-fire on a
    # resume past their step). One-shot per tenant either way.
    pending_faults = dict(fault_plan)

    def fault_provider(name: str, segment: int) -> dict[str, Any] | None:
        t = sup.tenants[name]
        fault = pending_faults.get(name)
        if not fault:
            return None
        fired = (
            t.counts["self_preemptions"] >= 1 or t.counts["injected_kills"] >= 1
        )
        if fired:
            pending_faults.pop(name)
            return None
        if not fault.get("kill_during_checkpoint"):
            # kill_during_checkpoint aims at "the first save at/after the
            # step" and stays firable on any resumed segment; the
            # step-exact faults die once the resume point passes them.
            at = fault.get("preempt_at_step") or fault.get("kill_at_step")
            if at is not None and sup.newest_commit(name) >= at:
                pending_faults.pop(name)
                return None
        return fault

    sup._fault_provider = fault_provider
    drop_to = max(1, min(t.cfg.min_devices for t in sup.tenants.values()))
    controller = _StormController(
        sup,
        seed,
        drop_to=drop_to,
        hold_sec=capacity_drop_hold_sec,
        external_evictions=external_evictions,
    )
    fleet_report = sup.run(timeout_sec=timeout_sec, on_tick=controller)

    # ----------------------------------------------------------- assertions
    failures: list[str] = []
    not_completed = [
        n for n, v in fleet_report["tenants"].items() if v["state"] != ts.COMPLETED
    ]
    if not_completed:
        states = {
            n: fleet_report["tenants"][n]["state"] for n in not_completed
        }
        raise FleetInvariantError(f"storm left tenants unfinished: {states}")
    tenant_results: dict[str, dict[str, Any]] = {}
    for name, t in sorted(sup.tenants.items()):
        view = fleet_report["tenants"][name]
        # Bounds invariant, re-checked post-hoc over the whole history
        # (the supervisor also asserts it at every launch).
        bad = [a for a in view["allocations"] if a not in t.demand_sizes]
        if bad:
            failures.append(
                f"{name}: allocations {bad} outside feasible sizes "
                f"{list(t.demand_sizes)}"
            )
        if view["evictions"]["total"] < 1:
            failures.append(f"{name}: storm delivered no eviction")
        if view["resume_count"] < 1:
            failures.append(
                f"{name}: resume_count {view['resume_count']} — evictions "
                "did not accumulate resumes (the --auto-resume run-dir "
                "propagation is broken)"
            )

        # Parity vs the uninterrupted reference: bitwise when the world
        # size never changed, resize_loss_rtol when the scheduler resized
        # the tenant (different device counts reorder the float
        # reductions — see module doc).
        ref_alloc = ref_allocs[name]
        resized = any(a != ref_alloc for a in view["allocations"])
        rtol = resize_loss_rtol if resized else 0.0

        def loss_mismatch(got: Any, want: Any) -> bool:
            if rtol == 0.0 or got is None or want is None:
                return got != want
            return abs(float(got) - float(want)) > rtol * max(
                abs(float(want)), 1e-8
            )

        ref_result = ref_summaries[name].get("train_result") or {}
        if view["final_step"] != ref_result.get("final_step"):
            failures.append(
                f"{name}: final_step {view['final_step']} != "
                f"{ref_result.get('final_step')}"
            )
        if loss_mismatch(view["final_loss"], ref_result.get("final_loss")):
            failures.append(
                f"{name}: final_loss {view['final_loss']!r} != "
                f"{ref_result.get('final_loss')!r} "
                f"({'bitwise' if rtol == 0.0 else f'rtol {rtol}'})"
            )
        final_seg = t.segments[-1] if t.segments else {}
        skip_step = partial_interval_step(
            final_seg.get("observed_resume"), t.log_every
        )
        overlap = skipped = 0
        try:
            ref_traj = {
                int(s): v
                for s, v in json.loads(
                    (refs_root / name / "report.json").read_text()
                )["loss"]["trajectory"]
            }
            storm_traj = json.loads(
                (t.run_dir / "report.json").read_text()
            )["loss"]["trajectory"]
        except (OSError, KeyError, ValueError) as exc:
            failures.append(f"{name}: loss trajectories unreadable: {exc}")
        else:
            for s, v in storm_traj:
                s = int(s)
                if s not in ref_traj:
                    continue
                if s == skip_step:
                    skipped += 1
                    continue
                overlap += 1
                if loss_mismatch(v, ref_traj[s]):
                    failures.append(
                        f"{name}: train/loss at step {s}: {v!r} != "
                        f"{ref_traj[s]!r} "
                        f"({'bitwise' if rtol == 0.0 else f'rtol {rtol}'})"
                    )
            if overlap == 0 and skipped == 0:
                # With skipped > 0 the final segment's only logged point
                # was the one partial boundary (an external eviction can
                # land inside the final interval); the final-checkpoint
                # tree comparison below still pins correctness bitwise.
                failures.append(f"{name}: no comparable trajectory points")

        ref_newest = CheckpointManager(
            refs_root / name / "checkpoints"
        ).latest_valid_checkpoint()
        storm_newest = CheckpointManager(t.ckpt_dir).latest_valid_checkpoint()
        if ref_newest is None or storm_newest is None:
            failures.append(f"{name}: missing final checkpoint on one side")
        else:
            ref_payload = CheckpointManager.load(ref_newest)
            storm_payload = CheckpointManager.load(storm_newest)
            if int(ref_payload["step"]) != int(storm_payload["step"]):
                failures.append(
                    f"{name}: final checkpoint steps differ: "
                    f"{int(storm_payload['step'])} vs {int(ref_payload['step'])}"
                )
            if not resized:
                for key in ("params", "opt_state"):
                    diff = trees_bitwise_equal(
                        ref_payload[key], storm_payload[key], f"{name}/{key}"
                    )
                    if diff is not None:
                        failures.append(diff)
        # Goodput floor: the supervisor already attributed each tenant's
        # wall clock (suspension windows included) in _tenant_report; a
        # storm that recovers correctness but burns the clock on restart
        # churn fails here, not in a dashboard three days later.
        ledger = view.get("goodput")
        floor = cfg.resilience.chaos.min_goodput_frac
        if ledger is None:
            failures.append(f"{name}: no goodput ledger in fleet report")
        else:
            wall = float(ledger["wall_clock_sec"])
            attributed = sum(float(v) for v in ledger["categories"].values())
            if abs(attributed - wall) > 0.01 * wall + 0.05:
                failures.append(
                    f"{name}: goodput ledger does not balance: "
                    f"{attributed:.3f}s attributed vs {wall:.3f}s wall"
                )
            if floor > 0.0 and float(ledger["goodput_frac"]) < floor:
                failures.append(
                    f"{name}: goodput_frac {ledger['goodput_frac']:.4f} "
                    f"below floor {floor} "
                    f"(categories: {ledger['categories']})"
                )
        tenant_results[name] = {
            "evictions": view["evictions"],
            "respawns": view["respawns"],
            "resizes": view["resizes"],
            "resume_count": view["resume_count"],
            "segment_faults": fault_plan.get(name) or {},
            "reference_allocation": ref_alloc,
            "parity": "bitwise" if not resized else f"loss_rtol<={rtol}",
            "trajectory_points_compared": overlap,
            "skipped_partial_points": skipped,
            "final_loss": view["final_loss"],
            "goodput": ledger,
        }

    if drop_to < cfg.fleet.pool_devices and fleet_report["capacity_changes"] < 2:
        failures.append(
            "capacity drop never completed its drop/restore cycle "
            f"({fleet_report['capacity_changes']} change(s))"
        )
    if midckpt_tenant and sup.tenants[midckpt_tenant].counts["injected_kills"] < 1:
        failures.append(
            f"mid-checkpoint kill never fired on tenant {midckpt_tenant}"
        )
    if failures:
        raise FleetInvariantError(
            "fleet storm diverged from the per-tenant references: "
            + "; ".join(failures)
        )

    result = {
        "seed": seed,
        "tenants": tenant_results,
        "pool_devices": cfg.fleet.pool_devices,
        "capacity_drop_to": drop_to,
        "capacity_changes": fleet_report["capacity_changes"],
        "mid_checkpoint_kill_tenant": midckpt_tenant,
        "total_evictions": fleet_report["totals"]["evictions"],
        "total_respawns": fleet_report["totals"]["respawns"],
        "total_suspensions": fleet_report["totals"]["suspensions"],
        "fleet_goodput_frac": fleet_report["totals"].get("goodput_frac"),
        "bitwise_match": all(
            r["parity"] == "bitwise" for r in tenant_results.values()
        ),
        "fleet_report_json": str(work / "fleet_report.json"),
        "work_dir": str(work),
        "wall_time_sec": round(time.perf_counter() - started, 2),
    }
    (work / "storm_result.json").write_text(
        json.dumps(result, indent=2), encoding="utf-8"
    )
    return result


__all__ = ["partial_interval_step", "run_fleet_storm"]
