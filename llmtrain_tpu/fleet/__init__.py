"""Multi-tenant fleet control plane: many jobs, shared device capacity,
preemption-aware scheduling (``llmtrain fleet``, docs/robustness.md
"Fleet: many tenants, shared capacity").

* ``policy`` — deterministic pure scheduling policy (quota / priority /
  shrink-before-suspend over feasible elastic world sizes).
* ``tenant`` — the validated tenant lifecycle state machine.
* ``supervisor`` — the control loop: real train subprocesses with
  ``--auto-resume``, the SIGTERM→deadline→SIGKILL escalation ladder,
  seeded full-jitter respawn backoff, elastic resizes, fleet health
  (``llmtrain_fleet_*`` gauges, fleet_report.json/.md).
* ``chaos`` — the seeded preemption-storm acceptance drill: every
  tenant's trajectory must end bitwise-equal to its uninterrupted
  reference.
"""

from .policy import (
    AllocationPlan,
    TenantDemand,
    candidate_world_sizes,
    plan_allocations,
    priority_order,
    within_bounds,
)
from .supervisor import FleetInvariantError, FleetSupervisor, render_fleet_report_md
from .tenant import InvalidTransitionError, TenantStateMachine

__all__ = [
    "AllocationPlan",
    "FleetInvariantError",
    "FleetSupervisor",
    "InvalidTransitionError",
    "TenantDemand",
    "TenantStateMachine",
    "candidate_world_sizes",
    "plan_allocations",
    "priority_order",
    "render_fleet_report_md",
    "within_bounds",
]
