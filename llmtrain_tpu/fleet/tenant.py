"""Tenant lifecycle state machine.

Every tenant of the fleet supervisor moves through an explicit, validated
state machine — the supervisor can only take transitions this table
allows, so a control-flow bug (respawning a completed tenant, suspending
one that already failed) surfaces as a loud
:class:`InvalidTransitionError` instead of a silently corrupted fleet.

States::

    queued ──► running ──► completed
                 │  ▲            ▲
                 ▼  │            │
            preempting ──────────┘ (finished during the grace window)
               │   │
               ▼   ▼
          suspended backoff ──► running
               │      ▲
               └──────┘ (capacity returned)

* ``queued`` — admitted to the fleet, never launched yet.
* ``running`` — a live train subprocess owns the tenant's allocation.
* ``preempting`` — SIGTERM sent (resize/suspend/evict); the escalation
  ladder's deadline clock is running toward SIGKILL.
* ``backoff`` — exited and will respawn after its seeded full-jitter
  delay (crash, retryable exit, eviction with capacity still granted).
* ``suspended`` — exited with no capacity granted; waits for the pool,
  not for a timer. "Suspend rather than crash" is this state.
* ``completed`` / ``failed`` — terminal.
"""

from __future__ import annotations

QUEUED = "queued"
RUNNING = "running"
PREEMPTING = "preempting"
BACKOFF = "backoff"
SUSPENDED = "suspended"
COMPLETED = "completed"
FAILED = "failed"

ALL_STATES = (QUEUED, RUNNING, PREEMPTING, BACKOFF, SUSPENDED, COMPLETED, FAILED)
TERMINAL_STATES = (COMPLETED, FAILED)

TRANSITIONS: dict[str, frozenset[str]] = {
    QUEUED: frozenset({RUNNING, FAILED}),
    RUNNING: frozenset({PREEMPTING, BACKOFF, SUSPENDED, COMPLETED, FAILED}),
    PREEMPTING: frozenset({BACKOFF, SUSPENDED, COMPLETED, FAILED}),
    BACKOFF: frozenset({RUNNING, SUSPENDED, FAILED}),
    SUSPENDED: frozenset({RUNNING, BACKOFF, FAILED}),
    COMPLETED: frozenset(),
    FAILED: frozenset(),
}


class InvalidTransitionError(RuntimeError):
    """The supervisor attempted a lifecycle move the table forbids — a
    control-plane bug, never a tenant failure."""


class TenantStateMachine:
    """Current state + audited history of one tenant's lifecycle."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._state = QUEUED
        # [(state, reason)] starting with the initial state; the fleet
        # report embeds this so every eviction/suspension is explainable.
        self.history: list[tuple[str, str]] = [(QUEUED, "admitted")]

    @property
    def state(self) -> str:
        return self._state

    @property
    def terminal(self) -> bool:
        return self._state in TERMINAL_STATES

    def can(self, to: str) -> bool:
        return to in TRANSITIONS[self._state]

    def transition(self, to: str, reason: str = "") -> None:
        if to not in TRANSITIONS:
            raise InvalidTransitionError(
                f"tenant {self.name!r}: unknown state {to!r}"
            )
        if to not in TRANSITIONS[self._state]:
            raise InvalidTransitionError(
                f"tenant {self.name!r}: illegal transition "
                f"{self._state} -> {to} ({reason or 'no reason given'})"
            )
        self._state = to
        self.history.append((to, reason))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TenantStateMachine({self.name!r}, state={self._state!r})"


__all__ = [
    "ALL_STATES",
    "BACKOFF",
    "COMPLETED",
    "FAILED",
    "InvalidTransitionError",
    "PREEMPTING",
    "QUEUED",
    "RUNNING",
    "SUSPENDED",
    "TERMINAL_STATES",
    "TRANSITIONS",
    "TenantStateMachine",
]
