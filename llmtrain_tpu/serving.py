"""HTTP inference server over the compiled decode loop.

Beyond-reference serving surface (the reference's only inference story
is eager notebook cells, reference
notebooks/trained_vs_random_completion.ipynb). ``llmtrain_tpu serve``
loads a checkpoint once, then serves JSON over stdlib
``http.server`` — no new dependencies, which keeps the air-gapped TPU
image story intact:

* ``GET /healthz`` — liveness + model/checkpoint metadata.
* ``POST /v1/generate`` — ``{"prompt": ...}`` or
  ``{"prompt_ids": [...]}`` plus the generate() sampling knobs; returns
  completion ids, decoded text when a tokenizer exists, and latency.

Device discipline: one TPU chip runs one decode at a time, so requests
serialize through a lock (no fake concurrency that would interleave
XLA programs); ``jax.jit``'s compile cache makes repeated
(prompt_len, max_new_tokens) shapes reuse their compiled loop, so
steady-state serving pays compile once per shape bucket. The CLI layer
(cli.py ``_handle_serve``) owns checkpoint loading/quantization; this
module owns only the HTTP surface, so it is testable with an in-memory
model.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import jax
import numpy as np


@dataclass
class ServerState:
    """Everything a request needs; built once by the CLI before serving."""

    model: Any
    params: Any
    tokenizer: Any | None
    step: int
    checkpoint: str
    eos_token_id: int | None = None
    max_new_tokens_cap: int = 256
    default_max_new_tokens: int = 48
    # One decode at a time: a TPU chip is a serial device and generate()
    # is not re-entrant across identical jit cache entries anyway.
    lock: threading.Lock = field(default_factory=threading.Lock)
    requests_served: int = 0


def _bad_request(msg: str) -> tuple[int, dict]:
    return 400, {"error": msg}


def _handle_generate_request(state: ServerState, body: dict) -> tuple[int, dict]:
    """Pure request logic (no HTTP): validate -> decode -> respond."""
    from .generation import generate

    if not isinstance(body, dict):
        return _bad_request("request body must be a JSON object")
    unknown = set(body) - {
        "prompt", "prompt_ids", "max_new_tokens", "temperature",
        "top_k", "top_p", "seed", "eos_token_id",
    }
    if unknown:
        return _bad_request(f"unknown fields: {sorted(unknown)}")
    if ("prompt" in body) == ("prompt_ids" in body):
        return _bad_request("provide exactly one of 'prompt' or 'prompt_ids'")

    vocab = int(getattr(state.model, "vocab_size", 0) or 0)
    if "prompt" in body:
        if state.tokenizer is None:
            return _bad_request(
                "this server has no tokenizer; send 'prompt_ids' instead"
            )
        if not isinstance(body["prompt"], str) or not body["prompt"]:
            return _bad_request("'prompt' must be a non-empty string")
        ids = np.asarray(state.tokenizer.encode(body["prompt"]), dtype=np.int32)
    else:
        raw = body["prompt_ids"]
        if (
            not isinstance(raw, list)
            or not raw
            or not all(isinstance(t, int) for t in raw)
        ):
            return _bad_request("'prompt_ids' must be a non-empty list of ints")
        bound = vocab or 2**31 - 1  # int32 dtype bound when vocab unknown
        if not all(0 <= t < bound for t in raw):
            return _bad_request(f"prompt token ids must be in [0, {bound})")
        ids = np.asarray(raw, dtype=np.int32)
    if ids.size == 0:
        return _bad_request("prompt encodes to zero tokens")

    # A server started with a cap below the default must still accept
    # knob-less requests: the effective default is min(default, cap).
    max_new = body.get(
        "max_new_tokens",
        min(state.default_max_new_tokens, state.max_new_tokens_cap),
    )
    if not isinstance(max_new, int) or max_new < 1:
        return _bad_request("'max_new_tokens' must be a positive int")
    if max_new > state.max_new_tokens_cap:
        return _bad_request(
            f"'max_new_tokens' exceeds the server cap "
            f"({state.max_new_tokens_cap})"
        )
    block_size = int(getattr(state.model, "block_size", 10**9))
    if ids.size + max_new > block_size:
        return _bad_request(
            f"prompt ({ids.size}) + max_new_tokens ({max_new}) exceeds the "
            f"model block_size ({block_size})"
        )
    temperature = body.get("temperature", 1.0)
    if not isinstance(temperature, (int, float)) or isinstance(temperature, bool):
        return _bad_request("'temperature' must be a number")
    if temperature < 0:
        return _bad_request("'temperature' must be >= 0")
    top_k = body.get("top_k")
    if top_k is not None and (not isinstance(top_k, int) or isinstance(top_k, bool)):
        return _bad_request("'top_k' must be an int")
    top_p = body.get("top_p")
    if top_p is not None and (
        not isinstance(top_p, (int, float)) or isinstance(top_p, bool)
    ):
        return _bad_request("'top_p' must be a number")
    seed = body.get("seed", 0)
    if not isinstance(seed, int) or isinstance(seed, bool):
        return _bad_request("'seed' must be an int")
    eos = body.get("eos_token_id", state.eos_token_id)
    if eos is not None and (not isinstance(eos, int) or isinstance(eos, bool)):
        return _bad_request("'eos_token_id' must be an int")

    t0 = time.monotonic()
    with state.lock:
        out = generate(
            state.model,
            state.params,
            ids[None, :],
            max_new_tokens=max_new,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            eos_token_id=eos,
            rng=jax.random.key(seed),
        )
        state.requests_served += 1
    latency_ms = (time.monotonic() - t0) * 1000.0

    completion = [int(t) for t in np.asarray(out)[0, ids.size :]]
    if eos is not None and eos in completion:
        completion = completion[: completion.index(eos) + 1]
    text = None
    if state.tokenizer is not None:
        try:
            text = state.tokenizer.decode(completion)
        except Exception:  # noqa: BLE001 — decode is best-effort for ids
            text = None
    return 200, {
        "completion_ids": completion,
        "text": text,
        "prompt_tokens": int(ids.size),
        "latency_ms": round(latency_ms, 3),
    }


def _handle_health(state: ServerState) -> tuple[int, dict]:
    return 200, {
        "status": "ok",
        "model": type(state.model).__name__,
        "step": state.step,
        "checkpoint": state.checkpoint,
        "requests_served": state.requests_served,
    }


class _Handler(BaseHTTPRequestHandler):
    # Set by make_server().
    state: ServerState = None  # type: ignore[assignment]

    def _respond(self, code: int, payload: dict) -> None:
        data = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path == "/healthz":
            self._respond(*_handle_health(self.state))
        else:
            self._respond(404, {"error": f"no route for GET {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path != "/v1/generate":
            self._respond(404, {"error": f"no route for POST {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length) or b"null")
        except (ValueError, json.JSONDecodeError):
            self._respond(400, {"error": "body is not valid JSON"})
            return
        try:
            self._respond(*_handle_generate_request(self.state, body))
        except Exception as exc:  # noqa: BLE001 — server must not die
            self._respond(500, {"error": f"generation failed: {exc}"})

    def log_message(self, fmt: str, *args: Any) -> None:
        from .utils.logging import get_logger

        get_logger().info("serve: %s", fmt % args)


def make_server(
    state: ServerState, host: str = "127.0.0.1", port: int = 0
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral; read ``server_address[1]``), don't serve."""
    handler = type("BoundHandler", (_Handler,), {"state": state})
    return ThreadingHTTPServer((host, port), handler)


__all__ = ["ServerState", "make_server"]
