"""Llama-family decoder (RMSNorm + RoPE + SwiGLU + untied head), mesh-first.

Beyond-reference model family (the reference ships GPT only,
``src/llmtrain/models/gpt.py``; SURVEY §2.1): the architecture used by
Llama/Mistral-class checkpoints —

* **RMSNorm** instead of LayerNorm: no mean subtraction, no bias; f32
  statistics for bf16 safety (same discipline as gpt_pipeline's
  ``_layernorm``).
* **Rotary position embeddings** (ops/rope.py) instead of learned
  position embeddings — applied to q/k inside attention, so the KV cache
  stores rotated keys and long-context scaling is a ``rope_theta`` knob,
  not a parameter-table resize.
* **SwiGLU MLP**: ``down(silu(gate(x)) * up(x))``, all bias-free.
* **Untied lm_head** by default (``model.tie_embeddings: false`` is the
  Llama convention; the flag still works both ways).

Everything else — GQA narrow K/V, flash/ring/ulysses attention routing,
KV-cache decode, chunked CE, remat policies, logical-axis sharding — is
the shared machinery in ``models/gpt.py``/``ops/``: attention reuses
``CausalSelfAttention`` (with ``use_bias=False, rope=True``), so there is
exactly one KV-cache and one kernel-dispatch implementation in the
package. Numerics are parity-tested against HF ``transformers``' torch
Llama in tests/test_llama.py.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .activation_policy import tag_block_input, tier_block_classes
from .gpt import (
    _DENSE_INIT,
    _EMBED_INIT,
    REMAT_POLICIES,
    CausalSelfAttention,
    GPTAdapter,
    _scaled_init,
)
from .gpt_moe import GPTMoEAdapter as _GPTMoEAdapter


class RMSNorm(nn.Module):
    """Root-mean-square norm, f32 statistics, scale-only (no bias).

    ``offset=True`` is the Gemma parameterization: the stored scale is a
    zero-initialized delta and the output multiplies by ``1 + scale`` —
    the identity transform at init, and the exact layout HF Gemma
    checkpoints store (models/gemma.py).
    """

    eps: float = 1e-6
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    offset: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # Logical axis "norm" maps to None (parallel/sharding.py): a (D,)
        # scale gains nothing from fsdp sharding, and mapping it to
        # "embed"→fsdp makes XLA reshard the residual-stream grads
        # embed-wise for the dscale reduction — an involuntary-full-
        # rematerialization path on fsdp×tensor meshes.
        init = (
            nn.initializers.zeros_init() if self.offset
            else nn.initializers.ones_init()
        )
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(init, ("norm",)),
            (x.shape[-1],),
            self.param_dtype,
        )
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps
        )
        mult = scale.astype(jnp.float32)
        if self.offset:
            mult = 1.0 + mult
        return (norm * mult).astype(self.dtype)


class LlamaBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    n_layers: int
    dropout: float
    dtype: Any
    param_dtype: Any
    attention: str = "dense"
    decode: bool = False
    cache_len: int = 0
    n_kv_heads: int = 0
    assume_packed: bool = False
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    # Qwen2 convention (models/qwen2.py): bias on q/k/v only; out_proj
    # and the MLP stay bias-free either way.
    qkv_bias: bool = False
    # Gemma conventions (models/gemma.py): tanh-GELU GeGLU MLP and the
    # (1 + scale) RMSNorm parameterization.
    mlp_act: str = "silu"
    norm_offset: bool = False
    sliding_window: int = 0  # Mistral-style window; 0 = full causal
    ring_slack: int = 0  # extra rolling-cache slots (speculative decode)
    kv_cache_dtype: str = "model"  # "int8": quantized decode cache
    # Paged block-pool decode cache (models/gpt.py CausalSelfAttention):
    # RoPE rotates by the per-row absolute positions the paged path
    # tracks, so the llama family serves continuous-batching too.
    paged: bool = False
    paged_num_blocks: int = 0
    paged_block_tokens: int = 0
    # Mixture-of-Experts MLP with SwiGLU experts (models/moe.py,
    # mlp_type="swiglu" — the Mixtral layout); 0 = dense SwiGLU.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    router_top_k: int = 1

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        attention_mask: jax.Array | None = None,
        deterministic: bool = True,
        positions: jax.Array | None = None,
        block_tables: jax.Array | None = None,
    ) -> jax.Array:
        # Residual tag consumed by the "offload" activation tier's
        # checkpoint policy; identity under every other policy.
        x = tag_block_input(x)
        norm_kw = dict(
            eps=self.rms_norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            offset=self.norm_offset,
        )
        # Pin the norm outputs' sharding: without the constraint XLA's
        # backward pass reshards the residual-stream grads through a
        # full-rematerialization path on fsdp×tensor meshes (SPMD warning
        # seen in dryrun_llama).
        act = ("batch", "length", "act_embed")
        h = nn.with_logical_constraint(RMSNorm(name="attn_norm", **norm_kw)(x), act)
        x = x + CausalSelfAttention(
            d_model=self.d_model,
            n_heads=self.n_heads,
            n_layers=self.n_layers,
            dropout=self.dropout,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            attention=self.attention,
            decode=self.decode,
            cache_len=self.cache_len,
            n_kv_heads=self.n_kv_heads,
            assume_packed=self.assume_packed,
            use_bias=False,
            qkv_bias=self.qkv_bias or None,
            rope=True,
            rope_theta=self.rope_theta,
            sliding_window=self.sliding_window,
            ring_slack=self.ring_slack,
            kv_cache_dtype=self.kv_cache_dtype,
            paged=self.paged,
            paged_num_blocks=self.paged_num_blocks,
            paged_block_tokens=self.paged_block_tokens,
            name="attn",
        )(
            h,
            attention_mask,
            deterministic=deterministic,
            positions=positions,
            block_tables=block_tables,
        )

        h = nn.with_logical_constraint(RMSNorm(name="mlp_norm", **norm_kw)(x), act)
        if self.n_experts > 0:
            from .moe import MoEMLP

            h = MoEMLP(
                d_model=self.d_model,
                d_ff=self.d_ff,
                n_experts=self.n_experts,
                n_layers=self.n_layers,
                capacity_factor=self.capacity_factor,
                aux_loss_weight=self.moe_aux_weight,
                router_top_k=self.router_top_k,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                mlp_type="swiglu",
                name="moe_mlp",
            )(h)
        else:
            dense_kw = dict(
                use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype
            )
            gate = nn.Dense(
                self.d_ff,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "mlp")),
                name="mlp_gate",
                **dense_kw,
            )(h)
            up = nn.Dense(
                self.d_ff,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "mlp")),
                name="mlp_up",
                **dense_kw,
            )(h)
            if self.mlp_act == "silu":
                h = nn.silu(gate) * up
            elif self.mlp_act == "gelu_tanh":
                # Gemma's GeGLU: HF hidden_activation gelu_pytorch_tanh.
                h = nn.gelu(gate, approximate=True) * up
            else:
                raise ValueError(
                    f"mlp_act {self.mlp_act!r} unknown; expected 'silu' "
                    "or 'gelu_tanh'"
                )
            h = nn.with_logical_constraint(h, ("batch", "length", "act_mlp"))
            h = nn.Dense(
                self.d_model,
                kernel_init=nn.with_logical_partitioning(
                    _scaled_init(self.n_layers), ("mlp", "embed")
                ),
                name="mlp_down",
                **dense_kw,
            )(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        x = x + h
        return nn.with_logical_constraint(x, ("batch", "length", "act_embed"))


class Llama(nn.Module):
    """Llama-family decoder-only language model."""

    vocab_size: int
    block_size: int
    d_model: int
    n_layers: int
    n_heads: int
    d_ff: int
    dropout: float
    tie_embeddings: bool = False  # Llama convention: untied head
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str = "nothing"
    # Per-layer activation tiers (models/gpt.py GPT.activation_tiers):
    # overrides the remat fields above when set.
    activation_tiers: tuple[str, ...] | None = None
    attention: str = "dense"
    decode: bool = False
    decode_cache_len: int = 0
    loss_impl: str = "dense"
    ce_chunk: int = 8192
    # Fused lm-head + CE Pallas kernel knobs (models/gpt.py GPT fields;
    # the loss machinery is shared via GPTAdapter).
    fused_ce_block_t: int = 256
    fused_ce_block_v: int = 512
    pallas_interpret: bool = False
    z_loss: float = 0.0
    n_kv_heads: int = 0
    assume_packed: bool = False
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    # Qwen2 convention: bias on the q/k/v projections only.
    qkv_bias: bool = False
    # Gemma conventions: tanh-GELU GeGLU, (1 + scale) RMSNorm, and
    # sqrt(d_model)-scaled input embeddings (the tied lm_head read is
    # NOT scaled — HF Gemma semantics).
    mlp_act: str = "silu"
    norm_offset: bool = False
    embed_scale: bool = False
    # Sliding-window attention (model.extra.sliding_window, the Mistral
    # architecture knob): O(T·W) attention on the flash path.
    sliding_window: int = 0
    # Decode-cache storage dtype (model.extra.kv_cache_dtype).
    kv_cache_dtype: str = "model"
    # Extra rolling-cache slots for speculative decode rollback safety
    # (models/gpt.py CausalSelfAttention.ring_slack).
    ring_slack: int = 0
    # Paged block-pool decode cache for continuous-batching serving; set
    # via for_paged_decoding().
    paged: bool = False
    paged_num_blocks: int = 0
    paged_block_tokens: int = 0
    # Mixture-of-Experts with SwiGLU experts (model.name llama_moe — the
    # Mixtral architecture); 0 = dense SwiGLU MLPs.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    router_top_k: int = 1

    def for_paged_decoding(
        self, *, num_blocks: int, block_tokens: int
    ) -> "Llama":
        """Clone configured for paged-KV continuous-batching decode (the
        GPT.for_paged_decoding contract; serving/engine.py dispatches on
        this method's presence). RoPE needs no special casing — the paged
        attention rotates q/k by its per-row absolute positions — but the
        sliding-window ring and the int8 cache keep their named raise, so
        Mistral-with-window configs fall back to ``serving.mode: simple``
        with an actionable error instead of silently wrong K/V."""
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (got {num_blocks})")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1 (got {block_tokens})")
        if self.sliding_window:
            raise ValueError(
                "paged decode does not support sliding_window models yet; "
                "use for_decoding() (rolling-ring cache)"
            )
        if self.kv_cache_dtype != "model":
            raise ValueError(
                "paged decode does not support kv_cache_dtype="
                f"{self.kv_cache_dtype!r} yet; use for_decoding()"
            )
        return self.clone(
            decode=True,
            paged=True,
            remat=False,
            activation_tiers=None,
            paged_num_blocks=num_blocks,
            paged_block_tokens=block_tokens,
        )

    def for_decoding(
        self, cache_len: int | None = None, *, ring_slack: int = 0
    ) -> "Llama":
        """Clone configured for cached autoregressive decoding (same
        contract as GPT.for_decoding — generation.py dispatches on it)."""
        if cache_len is None:
            cache_len = self.block_size
        return self.clone(
            decode=True,
            remat=False,
            activation_tiers=None,
            decode_cache_len=min(cache_len, self.block_size),
            ring_slack=ring_slack,
        )

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        *,
        deterministic: bool = True,
        return_hidden: bool = False,
        positions: jax.Array | None = None,
        block_tables: jax.Array | None = None,
    ) -> jax.Array:
        _, seqlen = input_ids.shape
        if seqlen > self.block_size:
            raise ValueError(
                f"Input sequence length {seqlen} exceeds block size {self.block_size}."
            )

        token_embedding = nn.Embed(
            self.vocab_size,
            self.d_model,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            embedding_init=nn.with_logical_partitioning(_EMBED_INIT, ("vocab", "embed")),
            name="token_embedding",
        )
        # No position embedding: RoPE rotates q/k inside attention, and at
        # decode time the cache cursor supplies absolute positions — the
        # model-level position_index variable GPT keeps (gpt.py:506-514)
        # has no Llama analogue.
        x = token_embedding(input_ids)
        if self.embed_scale:
            # HF Gemma casts the sqrt(d) normalizer to the activation
            # dtype BEFORE multiplying (a bf16 rounding the parity tests
            # would catch if skipped).
            x = x * jnp.asarray(self.d_model**0.5, dtype=x.dtype)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        x = nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        if self.activation_tiers is not None:
            if len(self.activation_tiers) != self.n_layers:
                raise ValueError(
                    f"activation_tiers has {len(self.activation_tiers)} "
                    f"entries for a {self.n_layers}-layer model"
                )
            tier_classes = tier_block_classes(LlamaBlock, self.activation_tiers)
            layer_classes = [tier_classes[t] for t in self.activation_tiers]
        else:
            block_cls = LlamaBlock
            if self.remat:
                if self.remat_policy not in REMAT_POLICIES:
                    raise ValueError(
                        f"remat_policy {self.remat_policy!r} unknown; expected "
                        f"one of {sorted(REMAT_POLICIES)}"
                    )
                block_cls = nn.remat(
                    LlamaBlock,
                    static_argnums=(3,),
                    policy=REMAT_POLICIES[self.remat_policy],
                )
            layer_classes = [block_cls] * self.n_layers

        paged = self.decode and self.paged
        for layer in range(self.n_layers):
            block = layer_classes[layer](
                d_model=self.d_model,
                n_heads=self.n_heads,
                d_ff=self.d_ff,
                n_layers=self.n_layers,
                dropout=self.dropout,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                attention=self.attention,
                decode=self.decode,
                cache_len=(self.decode_cache_len or self.block_size) if self.decode else 0,
                n_kv_heads=self.n_kv_heads,
                assume_packed=self.assume_packed,
                rope_theta=self.rope_theta,
                rms_norm_eps=self.rms_norm_eps,
                qkv_bias=self.qkv_bias,
                mlp_act=self.mlp_act,
                norm_offset=self.norm_offset,
                sliding_window=self.sliding_window,
                kv_cache_dtype=self.kv_cache_dtype,
                ring_slack=self.ring_slack if self.decode else 0,
                paged=paged,
                paged_num_blocks=self.paged_num_blocks if paged else 0,
                paged_block_tokens=self.paged_block_tokens if paged else 0,
                n_experts=self.n_experts,
                capacity_factor=self.capacity_factor,
                moe_aux_weight=self.moe_aux_weight,
                router_top_k=self.router_top_k,
                name=f"block_{layer}",
            )
            if paged:
                # kwargs only on the paged path: the remat wrapper's
                # positional static_argnums contract stays untouched
                # (paged implies remat=False anyway, gpt.py precedent).
                x = block(
                    x,
                    attention_mask,
                    deterministic,
                    positions=positions,
                    block_tables=block_tables,
                )
            else:
                x = block(x, attention_mask, deterministic)

        x = RMSNorm(
            name="norm_f",
            eps=self.rms_norm_eps,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            offset=self.norm_offset,
        )(x)

        if return_hidden:
            # Chunked-CE path: the loss contracts hidden states against the
            # vocab matrix (ops/chunked_ce.py via GPTAdapter.vocab_matrix —
            # param names match, so the adapter machinery is inherited).
            return nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        if self.tie_embeddings:
            logits = token_embedding.attend(x)
        else:
            logits = nn.Dense(
                self.vocab_size,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "vocab")),
                name="lm_head",
            )(x)
        return nn.with_logical_constraint(logits, ("batch", "length", "act_vocab"))


@register_model("llama")
class LlamaAdapter(GPTAdapter):
    """Adapter for the Llama family.

    Inherits the GPT adapter's loss machinery wholesale — chunked CE,
    z-loss, vocab-matrix access, mesh validation — because the Llama
    module keeps the same top-level param names (``token_embedding``,
    ``lm_head``) and loss-relevant attributes.
    """

    known_extra_keys = GPTAdapter.known_extra_keys | frozenset(
        {"rope_theta", "rms_norm_eps"}
    )

    def build_model(self, cfg: RunConfig) -> nn.Module:
        if cfg.model.extra.get("fused_norm"):
            # The fused Pallas add+norm kernel is LayerNorm-shaped; the
            # llama family norms are RMSNorm and are not wired to it.
            raise ValueError(
                "model.extra.fused_norm is not supported by the llama "
                "family (RMSNorm blocks); it is a gpt-family knob"
            )
        base = super().build_model(cfg)  # runs all shared validation
        rope_theta = float(cfg.model.extra.get("rope_theta", 10000.0))
        if rope_theta <= 0:
            raise ValueError(f"model.extra.rope_theta must be > 0, got {rope_theta}")
        rms_norm_eps = float(cfg.model.extra.get("rms_norm_eps", 1e-6))
        if rms_norm_eps <= 0:
            raise ValueError(
                f"model.extra.rms_norm_eps must be > 0, got {rms_norm_eps}"
            )
        if (cfg.model.d_model // cfg.model.n_heads) % 2 != 0:
            raise ValueError(
                "RoPE needs an even head dim: d_model/n_heads = "
                f"{cfg.model.d_model // cfg.model.n_heads}"
            )
        # The schema default (tie_embeddings: true, GPT convention —
        # config/schemas.py) is wrong for this family: a config that does
        # not mention the flag gets the Llama convention (untied head);
        # an explicit value wins either way.
        tie = (
            cfg.model.tie_embeddings
            if "tie_embeddings" in cfg.model.model_fields_set
            else False
        )
        return Llama(
            vocab_size=base.vocab_size,
            block_size=base.block_size,
            d_model=base.d_model,
            n_layers=base.n_layers,
            n_heads=base.n_heads,
            d_ff=base.d_ff,
            dropout=base.dropout,
            tie_embeddings=tie,
            dtype=base.dtype,
            param_dtype=base.param_dtype,
            remat=base.remat,
            remat_policy=base.remat_policy,
            activation_tiers=base.activation_tiers,
            attention=base.attention,
            loss_impl=base.loss_impl,
            ce_chunk=base.ce_chunk,
            fused_ce_block_t=base.fused_ce_block_t,
            fused_ce_block_v=base.fused_ce_block_v,
            pallas_interpret=base.pallas_interpret,
            z_loss=base.z_loss,
            n_kv_heads=base.n_kv_heads,
            assume_packed=base.assume_packed,
            rope_theta=rope_theta,
            rms_norm_eps=rms_norm_eps,
            sliding_window=base.sliding_window,
            kv_cache_dtype=base.kv_cache_dtype,
        )


@register_model("llama_moe")
class LlamaMoEAdapter(_GPTMoEAdapter, LlamaAdapter):
    """Mixtral-class adapter: the llama family + SwiGLU-expert MoE.

    Cooperative MRO does the composition: ``GPTMoEAdapter.build_model``
    validates/clones the MoE knobs and its ``compute_loss_components``
    folds the sown load-balance aux loss; ``super().build_model`` resolves
    to ``LlamaAdapter.build_model``, so the trunk is the Llama module
    (whose blocks route the MLP through ``MoEMLP(mlp_type="swiglu")``).
    With ``model.extra.sliding_window`` this is the full Mixtral layout.
    """

    known_extra_keys = (
        _GPTMoEAdapter.known_extra_keys | LlamaAdapter.known_extra_keys
    )
    _moe_name = "llama_moe"
    _dense_name = "llama"


__all__ = ["Llama", "LlamaBlock", "RMSNorm", "LlamaAdapter", "LlamaMoEAdapter"]
