"""Mixture-of-Experts GPT adapter (``gpt_moe``).

New model family beyond the reference (dense-MLP GPT only). Reuses the GPT
trunk (models/gpt.py) with every block's MLP replaced by the Switch-style
``MoEMLP`` (models/moe.py); expert parallelism comes from the mesh's
``expert`` axis via sharding annotations alone.

Config knobs ride the ``model.extra`` escape hatch (the reference's plugin
mechanism, reference config/schemas.py:37):

    model:
      name: gpt_moe
      extra:
        n_experts: 8           # required, >= 2
        capacity_factor: 1.25  # optional
        moe_aux_weight: 0.01   # optional; load-balance loss scale
        router_top_k: 1        # optional; 2 = GShard second-choice routing

The training objective is CE + load-balance aux (sown by each MoE layer);
the aux term is folded into the per-example loss sums proportionally to
token counts, so the trainer's token-weighted aggregation reports exactly
``CE + aux`` with unchanged per-rank metric semantics.
"""

from __future__ import annotations

import jax

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .base import Batch, Params, masked_ce_components, validate_lm_batch
from .gpt import GPTAdapter


@register_model("gpt_moe")
class GPTMoEAdapter(GPTAdapter):
    """GPT with Mixture-of-Experts MLPs and expert parallelism."""

    known_extra_keys = GPTAdapter.known_extra_keys | frozenset(
        {"n_experts", "capacity_factor", "moe_aux_weight", "router_top_k"}
    )
    # Subclass hooks so the MoE machinery (build + aux-loss fold) serves
    # other families too (models/llama.py's LlamaMoEAdapter).
    _moe_name = "gpt_moe"
    _dense_name = "gpt"

    def build_model(self, cfg: RunConfig):
        extra = cfg.model.extra
        n_experts = int(extra.get("n_experts", 0))
        if n_experts < 2:
            raise ValueError(
                f"{self._moe_name} requires model.extra.n_experts >= 2 "
                f"(got {n_experts}); use model.name {self._dense_name!r} "
                "for a dense MLP"
            )
        base = super().build_model(cfg)
        return base.clone(
            n_experts=n_experts,
            capacity_factor=float(extra.get("capacity_factor", 1.25)),
            moe_aux_weight=float(extra.get("moe_aux_weight", 0.01)),
            router_top_k=int(extra.get("router_top_k", 1)),
        )

    def compute_loss_components(
        self,
        model,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, jax.Array]:
        input_ids, labels, attention_mask = validate_lm_batch(batch)
        # chunked_ce and fused_ce both contract hidden states against the
        # vocab matrix outside the forward (return_hidden path).
        chunked = getattr(model, "loss_impl", "dense") in ("chunked_ce", "fused_ce")
        out, mutated = model.apply(
            {"params": params},
            input_ids,
            attention_mask=attention_mask,
            deterministic=deterministic,
            rngs=rngs,
            mutable=["losses"],
            return_hidden=chunked,
        )
        if chunked:
            # Streamed CE over vocab chunks (ops/chunked_ce.py): `out` is
            # the post-ln_f hidden states, never [B,T,V].
            loss_sum, tokens = self.chunked_components_from_hidden(
                model, params, out, labels, attention_mask
            )
        else:
            loss_sum, tokens = masked_ce_components(
                out, labels, attention_mask, z_loss=getattr(model, "z_loss", 0.0)
            )
        aux = sum(jax.tree.leaves(mutated.get("losses", {})))
        # Fold aux in proportionally to tokens: the trainer's
        # sum(loss_sum)/sum(tokens) then equals CE + aux exactly.
        return loss_sum + aux * tokens, tokens


__all__ = ["GPTMoEAdapter"]
