"""Gemma-family adapter: the llama stack with Gemma's three conventions.

Beyond-reference model family (the reference ships GPT only,
``src/llmtrain/models/gpt.py``; SURVEY §2.1). Gemma (v1) is
architecturally llama — RMSNorm, RoPE, GQA, gated MLP, bias-free
projections — with three parameterization changes, each a knob threaded
through ``models/llama.py``:

* **GeGLU** MLP: ``gelu_tanh(gate) * up`` (HF ``gelu_pytorch_tanh``)
  instead of SiLU;
* **(1 + scale) RMSNorm**: the stored scale is a zero-init delta — the
  layout HF Gemma checkpoints use, so interop needs no transform;
* **sqrt(d_model)-scaled input embeddings** (the tied lm_head read is
  not scaled), with **tied embeddings the family default**.

Everything else — attention dispatch, KV-cache decode, chunked CE,
remat, sharding, LoRA/EMA/quantization composition — is the shared
machinery; still exactly one attention implementation in the package.
The param tree is the llama tree (norm deltas instead of norm scales),
so ``interop/llama_hf.py`` exports/imports HF ``GemmaForCausalLM``
state dicts unchanged. Known limitation: head_dim is derived as
``d_model // n_heads`` (the whole-package convention), so checkpoints
with a decoupled head_dim — Gemma-7B's 16 heads × 256 at hidden 3072 —
do not import; Gemma-2B geometry (head_dim == d_model/n_heads) does.
Numerics are parity-tested against transformers' torch Gemma in
tests/test_gemma.py.
"""

from __future__ import annotations

from flax import linen as nn

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .llama import LlamaAdapter


@register_model("gemma")
class GemmaAdapter(LlamaAdapter):
    """Adapter for the Gemma family (GeGLU + offset norms + scaled embed)."""

    def build_model(self, cfg: RunConfig) -> nn.Module:
        base = super().build_model(cfg)  # full llama validation stack
        updates: dict = {
            "mlp_act": "gelu_tanh",
            "norm_offset": True,
            "embed_scale": True,
        }
        if "tie_embeddings" not in cfg.model.model_fields_set:
            # Gemma convention: tied head (llama's unset-default is
            # untied; an explicit config value wins either way).
            updates["tie_embeddings"] = True
        return base.clone(**updates)
