"""LoRA fine-tuning as a family-agnostic adapter wrapper.

Beyond-reference capability (the reference trains full-rank only): wrap
ANY registered model family in low-rank adaptation without touching its
module code. The design is deliberately functional, the TPU-idiomatic
shape of LoRA:

* the trainable state becomes ``{"base": <frozen family params>,
  "lora": <A/B factor tree>}`` — one pytree, so the existing train step,
  checkpointing, sharding, and resume machinery apply unchanged;
* the merge ``W' = W + (alpha/rank) * A @ B`` happens INSIDE the jitted
  loss with ``stop_gradient`` on the base leaves, so XLA dead-code
  eliminates the entire frozen backward pass — the compiled step computes
  gradients only for the factors;
* freezing is an ``optax.masked`` wrapper (``wrap_optimizer``): moments
  exist only for LoRA leaves, so AdamW optimizer state drops from
  2x params to 2x factors — the usual reason to LoRA-tune at all;
* base leaves keep their flax logical-axis boxes through the merge
  (``replace_boxed``), so FSDP/TP shardings of the frozen weights
  survive and the small factors replicate (parallel/sharding.py treats
  metadata-less leaves as replicated).

Config surface (any family)::

    model:
      extra:
        lora: {rank: 8, alpha: 16}            # defaults target attention
        # lora: {rank: 8, alpha: 16, targets: [qkv_proj, out_proj, mlp_fc]}

Targets name the parent flax module of a ``kernel``/``embedding`` leaf;
the families share the naming (``qkv_proj``/``q_proj``/``kv_proj``/
``out_proj`` attention projections, ``mlp_*`` dense layers, models/gpt.py
and models/llama.py). ``llmtrain_tpu train`` consumes the config like any
other; ``generate``/``eval``/``export`` merge automatically on load
(``inference_params``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.schemas import RunConfig
from .base import Batch, Metrics, ModelAdapter, Params

# Attention projections: the classic LoRA target set, shared verbatim by
# every built-in family (models/gpt.py, models/llama.py incl. GQA).
DEFAULT_TARGETS = ("qkv_proj", "q_proj", "kv_proj", "out_proj")

# Leaf names eligible for adaptation (norm scales and biases stay out).
_FACTORABLE_LEAVES = ("kernel", "embedding")


@dataclass(frozen=True)
class LoraSpec:
    rank: int
    alpha: float
    targets: tuple[str, ...]

    @property
    def scale(self) -> float:
        return self.alpha / self.rank

    @classmethod
    def from_extra(cls, extra: dict) -> "LoraSpec | None":
        """Parse ``model.extra.lora``; None when absent (LoRA off)."""
        raw = extra.get("lora")
        if raw is None:
            return None
        if not isinstance(raw, dict):
            raise ValueError(
                f"model.extra.lora must be a mapping, got {type(raw).__name__}"
            )
        unknown = sorted(set(raw) - {"rank", "alpha", "targets"})
        if unknown:
            raise ValueError(
                f"model.extra.lora: unknown keys {unknown}; expected "
                "rank/alpha/targets"
            )
        rank = int(raw.get("rank", 8))
        if rank < 1:
            raise ValueError(f"model.extra.lora.rank must be >= 1, got {rank}")
        alpha = float(raw.get("alpha", 2.0 * rank))
        if alpha <= 0:
            raise ValueError(f"model.extra.lora.alpha must be > 0, got {alpha}")
        raw_targets = raw.get("targets", DEFAULT_TARGETS)
        if (
            not isinstance(raw_targets, (list, tuple))
            or not raw_targets
            or not all(isinstance(t, str) and t for t in raw_targets)
        ):
            # isinstance first: tuple("qkv_proj") would silently explode a
            # YAML string into characters and fail much later, misleadingly.
            raise ValueError(
                "model.extra.lora.targets must be a non-empty list of module names"
            )
        targets = tuple(raw_targets)
        return cls(rank=rank, alpha=alpha, targets=targets)


def _path_names(path: tuple) -> tuple[str, ...]:
    return tuple(str(getattr(k, "key", k)) for k in path)


def _is_box(leaf: Any) -> bool:
    return isinstance(leaf, nn.meta.AxisMetadata)


def _unbox(leaf: Any) -> jax.Array:
    return leaf.unbox() if _is_box(leaf) else leaf


def _split_index(module: str, ndim: int) -> int:
    """Where the kernel factors as (fan_in dims | fan_out dims).

    flax ``DenseGeneral`` lays kernels out input-dims-first: projections
    INTO heads are ``(d_model, *out)`` (split 1) while ``out_proj``
    contracts the leading ``(heads, head_dim)`` dims (split ndim-1).
    Embeddings are ``(vocab, d_model)`` — split 1.
    """
    return ndim - 1 if module == "out_proj" else 1


def _target_entry(names: tuple[str, ...], leaf: Any, spec: LoraSpec):
    """``(module, shape, split)`` when this leaf is adapted, else None."""
    if len(names) < 2 or names[-1] not in _FACTORABLE_LEAVES:
        return None
    module = names[-2]
    if module not in spec.targets:
        return None
    shape = tuple(_unbox(leaf).shape)
    if len(shape) < 2:
        return None
    return module, shape, _split_index(module, len(shape))


def init_lora(base_params: Params, spec: LoraSpec, rng: jax.Array) -> Params:
    """The factor tree: nested dict mirroring target paths, each holding
    ``a: (fan_in, rank)`` Gaussian and ``b: (rank, fan_out)`` zeros — so
    the initial delta is exactly zero and step 0 reproduces the base
    model."""
    flat = jax.tree_util.tree_flatten_with_path(
        base_params, is_leaf=_is_box
    )[0]
    lora: dict = {}
    matched: list[str] = []
    modules_seen: set[str] = set()
    for path, leaf in flat:
        names = _path_names(path)
        if len(names) >= 2 and names[-1] in _FACTORABLE_LEAVES:
            modules_seen.add(names[-2])
        entry = _target_entry(names, leaf, spec)
        if entry is None:
            continue
        _, shape, split = entry
        fan_in = math.prod(shape[:split])
        fan_out = math.prod(shape[split:])
        dtype = _unbox(leaf).dtype
        rng, a_rng = jax.random.split(rng)
        node = lora
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = {
            "a": (
                jax.random.normal(a_rng, (fan_in, spec.rank), dtype)
                / jnp.sqrt(jnp.asarray(fan_in, dtype))
            ),
            "b": jnp.zeros((spec.rank, fan_out), dtype),
        }
        matched.append("/".join(names[:-1]))
    if not matched:
        raise ValueError(
            f"model.extra.lora.targets {list(spec.targets)} matched no "
            f"parameters; factorable modules in this model: "
            f"{sorted(modules_seen)}"
        )
    return lora


def merge_lora(
    base_params: Params,
    lora_params: Params,
    spec: LoraSpec,
    *,
    freeze_base: bool = False,
) -> Params:
    """``W + scale * (A @ B)`` on target leaves, boxes preserved.

    ``freeze_base=True`` stops gradients at every base leaf — the
    training path, where only the factors are trainable and XLA drops
    the frozen backward entirely.
    """

    def one(path, leaf):
        names = _path_names(path)
        value = _unbox(leaf)
        if freeze_base:
            value = jax.lax.stop_gradient(value)
        entry = _target_entry(names, leaf, spec)
        if entry is not None:
            node: Any = lora_params
            for name in names:
                node = node[name]
            delta = (node["a"] @ node["b"]) * spec.scale
            value = value + delta.reshape(value.shape).astype(value.dtype)
        return leaf.replace_boxed(value) if _is_box(leaf) else value

    return jax.tree_util.tree_map_with_path(one, base_params, is_leaf=_is_box)


def lora_mask(params: Params) -> Params:
    """Trainable-leaf mask over the combined tree: True for the factors,
    False for the frozen base. Flax metadata boxes are masked WHOLE
    (``is_leaf``) so one flag aligns with one array."""
    return {
        "base": jax.tree.map(lambda _: False, params["base"], is_leaf=_is_box),
        "lora": jax.tree.map(lambda _: True, params["lora"]),
    }


def lora_only_optimizer(tx):
    """Run ``tx`` on the ``lora`` subtree only; pass base updates through.

    Base gradients are structural zeros (``stop_gradient`` in the merge),
    so passing them through applies ``base + 0``. Deliberately NOT
    ``optax.masked``: its ``MaskedNode`` placeholders would sit inside
    flax metadata boxes and fight both the checkpoint serializer and
    ``state_shardings`` — this wrapper's state is ``tx``'s state over the
    factor subtree, plain arrays that checkpoint and shard like any
    other. Moments for the frozen base never exist, which is the LoRA
    memory win."""
    import optax

    def init(params):
        return tx.init(params["lora"])

    def update(updates, state, params=None):
        lora_updates, new_state = tx.update(
            updates["lora"], state, None if params is None else params["lora"]
        )
        return {"base": updates["base"], "lora": lora_updates}, new_state

    return optax.GradientTransformation(init, update)


class LoraAdapter(ModelAdapter):
    """Wraps any base adapter; params become ``{"base": ..., "lora": ...}``.

    The Trainer/CLI pick this up via :func:`build_adapter`; the existing
    getattr-duck-typed hooks (``validate_mesh``, ``batch_divisor``) and
    the two new ones (``wrap_optimizer``, ``inference_params``) carry the
    LoRA specifics without touching the core train step.
    """

    supports_pipeline = False  # stacked-layer param trees name differently

    def __init__(self, base: ModelAdapter, spec: LoraSpec) -> None:
        self._base = base
        self._spec = spec
        base_known = getattr(base, "known_extra_keys", None)
        self.known_extra_keys = (
            None if base_known is None else frozenset(base_known) | {"lora"}
        )
        validate = getattr(base, "validate_mesh", None)
        if validate is not None:
            self.validate_mesh = validate  # bound method of the base

    @property
    def spec(self) -> LoraSpec:
        return self._spec

    def build_model(self, cfg: RunConfig) -> nn.Module:
        return self._base.build_model(cfg)

    def build_tokenizer(self, cfg: RunConfig):
        return self._base.build_tokenizer(cfg)

    def batch_divisor(self, cfg: RunConfig, mesh: Any) -> int:
        return self._base.batch_divisor(cfg, mesh)

    def init_params(self, model: nn.Module, cfg: RunConfig, rng: jax.Array) -> Params:
        # The base tree is bit-identical to a non-LoRA init of the same
        # seed; the factor init draws from an independent folded stream.
        base_params = self._base.init_params(model, cfg, rng)
        lora = init_lora(base_params, self._spec, jax.random.fold_in(rng, 0x10A))
        return {"base": base_params, "lora": lora}

    def _merged(self, params: Params, *, freeze_base: bool) -> Params:
        if (
            not isinstance(params, dict)
            or "base" not in params
            or "lora" not in params
        ):
            raise ValueError(
                "LoRA is enabled (model.extra.lora) but the parameter tree "
                "has no base/lora split — was this checkpoint trained "
                "without LoRA? Drop model.extra.lora to consume it."
            )
        return merge_lora(
            params["base"], params["lora"], self._spec, freeze_base=freeze_base
        )

    def compute_loss(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, Metrics]:
        return self._base.compute_loss(
            model,
            self._merged(params, freeze_base=True),
            batch,
            rngs=rngs,
            deterministic=deterministic,
        )

    def compute_loss_components(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ):
        return self._base.compute_loss_components(
            model,
            self._merged(params, freeze_base=True),
            batch,
            rngs=rngs,
            deterministic=deterministic,
        )

    def wrap_optimizer(self, tx):
        """Freeze the base: moments only for the factors."""
        return lora_only_optimizer(tx)

    def trainable_param_mask(self, params: Params) -> Params:
        """Which leaves train — feeds the Trainer's trainable count and
        its frozen-aware MFU FLOP model (utils/hw.py)."""
        return lora_mask(params)

    def inference_params(self, params: Params) -> Params:
        """Plain merged tree in the base family's structure — what
        ``generate``/``eval``/``export`` apply and write."""
        return self._merged(params, freeze_base=False)


def to_inference_params(adapter: ModelAdapter, params: Params) -> Params:
    """Merge-on-load rule in one place: LoRA checkpoints become plain
    family trees for any consumer that applies or exports weights."""
    merge = getattr(adapter, "inference_params", None)
    return params if merge is None else merge(params)


def build_adapter(cfg: RunConfig) -> ModelAdapter:
    """The one adapter factory: registry lookup + optional LoRA wrap.

    Every consumer (Trainer, generate/eval/export CLI paths) builds its
    adapter here so ``model.extra.lora`` means the same thing everywhere.
    """
    from ..registry import get_model_adapter

    base = get_model_adapter(cfg.model.name)()
    spec = LoraSpec.from_extra(cfg.model.extra)
    if spec is None:
        return base
    if getattr(base, "supports_pipeline", False):
        raise ValueError(
            "model.extra.lora does not support stacked-layer pipeline "
            "models; use a per-block family (gpt, llama, ...)"
        )
    return LoraAdapter(base, spec)
