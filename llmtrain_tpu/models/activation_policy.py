"""Per-layer activation policies: remat tiers + backward-pass host offload.

Maps the parsed ``model.extra.activation_tiers`` spec (see
config/activation_tiers.py for the grammar) onto flax block wrappers:

- ``none``      — the bare block class (save everything).
- ``selective`` — ``nn.remat`` with ``dots_saveable``: matmul outputs stay
  resident, elementwise ops replay in the backward pass.
- ``full``      — ``nn.remat`` with the default save-nothing policy.
- ``offload``   — ``nn.remat`` with
  ``save_and_offload_only_these_names``: the tagged block-input residual
  (:data:`OFFLOAD_RESIDUAL_NAME`, see ``checkpoint_name`` in the block
  bodies) is staged to the backend's ``pinned_host`` memory space between
  the forward and backward pass; everything else recomputes like ``full``.

Offload needs a ``pinned_host`` memory space on the backend.  The CPU
emulation backend exposes only ``unpinned_host`` (which *is* device memory
there), so :func:`resolve_activation_tiers` downgrades ``offload`` ->
``full`` with a once-per-process warning — the same capability-probe
discipline as ``trainer.zero.host_offload`` (parallel/sharding.py
``host_memory_kind``) and ``resolve_matmul_precision`` (ops/quant.py).
"""

from __future__ import annotations

import functools
import logging
from typing import Any

import jax
import jax.ad_checkpoint
from flax import linen as nn

logger = logging.getLogger("llmtrain")

# Residual name tagged via jax.ad_checkpoint.checkpoint_name at block
# entry; inert under every policy except offload's.
OFFLOAD_RESIDUAL_NAME = "block_input"

_FALLBACK_WARNED: set[str] = set()


@functools.lru_cache(maxsize=1)
def offload_supported() -> bool:
    """True when the default backend exposes a ``pinned_host`` memory
    space (real TPU/GPU runtimes; the CPU container does not)."""
    try:
        dev = jax.local_devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:  # pragma: no cover - defensive: odd backends
        return False
    return "pinned_host" in kinds


def resolve_activation_tiers(tiers: tuple[str, ...]) -> tuple[str, ...]:
    """Downgrade ``offload`` to ``full`` when the backend has no
    ``pinned_host`` memory space, warning once per process."""
    if "offload" not in tiers or offload_supported():
        return tiers
    if "offload" not in _FALLBACK_WARNED:
        _FALLBACK_WARNED.add("offload")
        n = sum(1 for t in tiers if t == "offload")
        logger.warning(
            "activation_tiers: backend %s has no pinned_host memory space; "
            "falling back offload -> full remat for %d layer(s) "
            "(residuals recompute instead of staging to host)",
            jax.default_backend(),
            n,
        )
    return tuple("full" if t == "offload" else t for t in tiers)


def _offload_policy() -> Any:
    return jax.checkpoint_policies.save_and_offload_only_these_names(
        names_which_can_be_saved=[],
        names_which_can_be_offloaded=[OFFLOAD_RESIDUAL_NAME],
        offload_src="device",
        offload_dst="pinned_host",
    )


def tier_block_classes(
    block_cls: Any, tiers: tuple[str, ...]
) -> dict[str, Any]:
    """One wrapped block class per tier actually used in ``tiers``.

    Built once per model ``__call__`` so flax sees a stable class per
    tier (static_argnums=(3,) keeps ``deterministic`` trace-static, same
    as the legacy ``model.remat`` wrap).
    """
    classes: dict[str, Any] = {}
    for tier in set(tiers):
        if tier == "none":
            classes[tier] = block_cls
        elif tier == "selective":
            classes[tier] = nn.remat(
                block_cls,
                static_argnums=(3,),
                policy=jax.checkpoint_policies.dots_saveable,
            )
        elif tier == "full":
            classes[tier] = nn.remat(block_cls, static_argnums=(3,))
        elif tier == "offload":
            classes[tier] = nn.remat(
                block_cls, static_argnums=(3,), policy=_offload_policy()
            )
        else:  # pragma: no cover - parser rejects unknown tiers upstream
            raise ValueError(f"unknown activation tier {tier!r}")
    return classes


def tag_block_input(x: jax.Array) -> jax.Array:
    """Tag the block-input residual for the offload checkpoint policy.

    A no-op identity under every other policy (and outside remat), so the
    blocks call it unconditionally.
    """
    return jax.ad_checkpoint.checkpoint_name(x, OFFLOAD_RESIDUAL_NAME)


__all__ = [
    "OFFLOAD_RESIDUAL_NAME",
    "offload_supported",
    "resolve_activation_tiers",
    "tag_block_input",
    "tier_block_classes",
]
