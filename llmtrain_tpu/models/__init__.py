"""Model plugins: the ModelAdapter contract and built-in adapters."""

from .base import Batch, Metrics, ModelAdapter, Params, masked_cross_entropy, validate_lm_batch

__all__ = [
    "Batch",
    "Metrics",
    "ModelAdapter",
    "Params",
    "masked_cross_entropy",
    "validate_lm_batch",
]
