"""Tiny dummy model adapter — the fast fake backend for tests.

Parity target: reference ``src/llmtrain/models/dummy_gpt.py`` — a minimal
embed→mix→lm_head model with the same defensive clamps (d_model capped at 64,
n_heads divisibility fixed, reference :43-47) registered as ``dummy_gpt``.
The mixer is a single gelu MLP rather than a torch TransformerEncoder layer:
the dummy backend's contract is "cheap, deterministic, loss can decrease",
not architectural fidelity.
"""

from __future__ import annotations

from typing import Any

import jax
from flax import linen as nn

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .base import (
    Batch,
    ModelAdapter,
    Params,
    lm_loss_components,
)


class _TinyLM(nn.Module):
    vocab_size: int
    d_model: int

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        *,
        deterministic: bool = True,
    ) -> jax.Array:
        del attention_mask, deterministic
        x = nn.Embed(self.vocab_size, self.d_model, name="embed")(input_ids)
        h = nn.Dense(self.d_model * 2, name="mlp_in")(x)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, name="mlp_out")(h)
        x = nn.LayerNorm(name="ln_f")(x)
        return nn.Dense(self.vocab_size, use_bias=False, name="lm_head")(x)


@register_model("dummy_gpt")
class DummyGPTAdapter(ModelAdapter):
    """Tiny adapter for dry-run smoke tests."""

    known_extra_keys = frozenset()

    def build_model(self, cfg: RunConfig) -> nn.Module:
        vocab_size = cfg.model.vocab_size or 128
        d_model = min(cfg.model.d_model or 128, 64)
        return _TinyLM(vocab_size=vocab_size, d_model=d_model)

    def build_tokenizer(self, cfg: RunConfig) -> Any | None:
        del cfg
        return None

    def compute_loss_components(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, jax.Array]:
        return lm_loss_components(
            model, params, batch, rngs=rngs, deterministic=deterministic
        )


__all__ = ["DummyGPTAdapter", "_TinyLM"]
