"""Qwen2-family adapter: the Llama stack with attention-input biases.

Beyond-reference model family (the reference ships GPT only,
``src/llmtrain/models/gpt.py``; SURVEY §2.1). Architecturally Qwen2 is
Llama — RMSNorm, RoPE, SwiGLU, GQA, untied head — with exactly two
conventions changed:

* **bias on the q/k/v projections** (and only there: o_proj and the
  MLP stay bias-free) — the ``qkv_bias`` knob threaded through
  ``models/llama.py`` → ``models/gpt.py::CausalSelfAttention``;
* **rope_theta defaults to 1e6** (Qwen2's long-context base frequency;
  ``model.extra.rope_theta`` still wins).

Everything else — attention kernel dispatch, KV-cache decode, chunked
CE, remat, logical-axis sharding, LoRA/EMA/quantization composition —
is the shared llama/gpt machinery, so there is still exactly one
attention implementation in the package. The param tree is the llama
tree plus ``attn/{qkv,q,kv}_proj/bias`` leaves; HF interop
(``interop/llama_hf.py``) maps them to ``self_attn.{q,k,v}_proj.bias``,
which makes the exported dict load into HF ``Qwen2ForCausalLM``
(same state-dict names as Llama plus those biases). Numerics are
parity-tested against HF transformers' torch Qwen2 in
tests/test_qwen2.py.
"""

from __future__ import annotations

from flax import linen as nn

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .llama import LlamaAdapter


@register_model("qwen2")
class Qwen2Adapter(LlamaAdapter):
    """Adapter for the Qwen2 family (llama + qkv biases + 1e6 rope base)."""

    def build_model(self, cfg: RunConfig) -> nn.Module:
        base = super().build_model(cfg)  # full llama validation stack
        updates: dict = {"qkv_bias": True}
        if "rope_theta" not in cfg.model.extra:
            updates["rope_theta"] = 1_000_000.0
        return base.clone(**updates)
