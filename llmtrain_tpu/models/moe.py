"""Mixture-of-Experts MLP (Switch-style top-1 routing), mesh-first.

New capability beyond the reference (dense MLP only, reference
models/gpt.py:94-97), designed the TPU/XLA way (GShard/Switch pattern):
routing is expressed as dense one-hot dispatch/combine einsums over a
(tokens, experts, capacity) layout, and expert parallelism falls out of
sharding annotations — expert weights carry the logical ``expert`` axis and
dispatched activations carry ``act_expert``; with a mesh whose ``expert``
axis is > 1, XLA's SPMD partitioner inserts the token all-to-alls. No
hand-written collectives.

Semantics:

* top-1 routing (Switch Transformer): each token goes to its argmax expert,
  output scaled by the router probability.
* fixed expert capacity ``ceil(capacity_factor * T / n_experts)`` per
  sequence; tokens over capacity are dropped — they pass through the
  residual connection unchanged (output 0 from the MoE layer).
* load-balance auxiliary loss ``aux_weight * E^2 * mean_e(f_e * P_e)``
  sown into the ``losses`` collection; the gpt_moe adapter folds it into
  the training objective. ``sow`` is a no-op when the collection isn't
  mutable, so eval/generation paths need no changes.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

_DENSE_INIT = nn.initializers.normal(stddev=0.02)


def _scaled_init(n_layers: int) -> nn.initializers.Initializer:
    return nn.initializers.normal(stddev=0.02 / math.sqrt(2 * n_layers))


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP inside a transformer block."""

    d_model: int
    d_ff: int
    n_experts: int
    n_layers: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        batch, seqlen, d_model = x.shape
        n_exp = self.n_experts
        capacity = max(1, int(math.ceil(self.capacity_factor * seqlen / n_exp)))

        # Router in float32: softmax over tiny expert dim must not run bf16.
        router_logits = nn.Dense(
            n_exp,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", None)),
            name="router",
        )(x.astype(jnp.float32))
        gates = jax.nn.softmax(router_logits, axis=-1)  # (B, T, E) f32

        expert_index = jnp.argmax(gates, axis=-1)  # (B, T)
        expert_mask = jax.nn.one_hot(expert_index, n_exp, dtype=jnp.float32)

        # Switch load-balance loss: E * sum_e f_e * P_e per sequence
        # (fraction of tokens routed to e times mean router prob of e),
        # scaled so a perfectly uniform router gives aux_weight * 1.0.
        density = expert_mask.mean(axis=1)  # (B, E)
        density_proxy = gates.mean(axis=1)  # (B, E)
        aux = self.aux_loss_weight * n_exp * n_exp * jnp.mean(density * density_proxy)
        self.sow("losses", "moe_aux", aux)

        # Position of each token in its expert's queue (1-based), capacity cut.
        position_in_expert = jnp.cumsum(expert_mask, axis=1) * expert_mask
        expert_mask = expert_mask * (position_in_expert <= capacity)
        gate = jnp.sum(gates * expert_mask, axis=-1)  # (B, T); 0 when dropped

        # One-hot over capacity slots; dropped tokens (position 0 -> -1) map
        # to all-zero rows.
        position = jnp.sum(position_in_expert * expert_mask, axis=-1) - 1.0
        position_oh = jax.nn.one_hot(position.astype(jnp.int32), capacity, dtype=jnp.float32)
        dispatch = expert_mask[..., None] * position_oh[:, :, None, :]  # (B,T,E,C)
        combine = dispatch * gate[:, :, None, None]

        # Dispatch tokens: (B,T,E,C) x (B,T,D) -> (E,B,C,D). The E dim is
        # expert-sharded, B stays data-sharded (act_expert_group) — the
        # resharding between the two layouts is the all-to-all.
        expert_in = jnp.einsum(
            "btec,btd->ebcd", dispatch.astype(x.dtype), x.astype(x.dtype)
        )
        expert_in = nn.with_logical_constraint(
            expert_in, ("act_expert", "act_expert_group", None, "act_embed")
        )

        wi = self.param(
            "wi",
            nn.with_logical_partitioning(_DENSE_INIT, ("expert", "embed", "mlp")),
            (n_exp, d_model, self.d_ff),
            self.param_dtype,
        )
        bi = self.param(
            "bi",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("expert", "mlp")),
            (n_exp, self.d_ff),
            self.param_dtype,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(
                _scaled_init(self.n_layers), ("expert", "mlp", "embed")
            ),
            (n_exp, self.d_ff, d_model),
            self.param_dtype,
        )
        bo = self.param(
            "bo",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("expert", "embed")),
            (n_exp, d_model),
            self.param_dtype,
        )

        h = jnp.einsum("ebcd,edf->ebcf", expert_in, wi.astype(self.dtype))
        h = h + bi.astype(self.dtype)[:, None, None, :]
        h = nn.with_logical_constraint(h, ("act_expert", "act_expert_group", None, "act_mlp"))
        h = nn.gelu(h, approximate=False)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h, wo.astype(self.dtype))
        expert_out = expert_out + bo.astype(self.dtype)[:, None, None, :]
        expert_out = nn.with_logical_constraint(
            expert_out, ("act_expert", "act_expert_group", None, "act_embed")
        )

        # Combine back to (B, T, D); dropped tokens get 0 (residual carries them).
        out = jnp.einsum("btec,ebcd->btd", combine.astype(x.dtype), expert_out)
        return nn.with_logical_constraint(out, ("batch", "length", "act_embed"))


__all__ = ["MoEMLP"]
