"""Mixture-of-Experts MLP (top-1 Switch or top-2 GShard routing), mesh-first.

New capability beyond the reference (dense MLP only, reference
models/gpt.py:94-97), designed the TPU/XLA way (GShard/Switch pattern):
routing is expressed as dense one-hot dispatch/combine einsums over a
(tokens, experts, capacity) layout, and expert parallelism falls out of
sharding annotations — expert weights carry the logical ``expert`` axis and
dispatched activations carry ``act_expert``; with a mesh whose ``expert``
axis is > 1, XLA's SPMD partitioner inserts the token all-to-alls. No
hand-written collectives.

Semantics:

* ``router_top_k=1`` (Switch Transformer): each token goes to its argmax
  expert, output scaled by the raw router probability.
* ``router_top_k=2`` (GShard): each token also goes to its second-choice
  expert; the two RAW router probabilities are renormalized to sum to 1
  (before any capacity drop — a dropped choice contributes zero without
  inflating the survivor), and second choices queue BEHIND all first
  choices for capacity (first-choice priority).
* fixed expert capacity ``ceil(capacity_factor * k * T / n_experts)`` per
  sequence; tokens over capacity are dropped — they pass through the
  residual connection unchanged (output 0 from the MoE layer for that
  choice).
* load-balance auxiliary loss ``aux_weight * E^2 * mean_e(f_e * P_e)``
  (f from first choices) sown into the ``losses`` collection; the gpt_moe
  adapter folds it into the training objective. ``sow`` is a no-op when
  the collection isn't mutable, so eval/generation paths need no changes.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

_DENSE_INIT = nn.initializers.normal(stddev=0.02)


def _scaled_init(n_layers: int) -> nn.initializers.Initializer:
    return nn.initializers.normal(stddev=0.02 / math.sqrt(2 * n_layers))


def _expert_matmul(x: jax.Array, w: jax.Array, mode: str, spec: str) -> jax.Array:
    """Expert-batched matmul, optionally quantized (ops/quant.py).

    ``x`` (E, B, C, d_in) against stacked expert kernels ``w``
    (E, d_in, d_out) -> (E, B, C, d_out). ``mode`` "f32" keeps the
    original einsum (bit-identical to the pre-quantization build); the
    quantized modes route through ``quant_dot_general`` with the same
    contraction expressed as dot_general dimension numbers (batch dim E,
    contracting dim d_in) — per-(expert, output-unit) int8 scales,
    straight-through gradients to the f32 master weights. Only the
    expert kernels quantize: router and dispatch/combine one-hots are
    routing decisions, not matmul bandwidth, and stay f32.
    """
    if mode == "f32":
        return jnp.einsum(spec, x, w)
    from ..ops.quant import quant_dot_general

    return quant_dot_general(mode)(x, w, (((3,), (1,)), ((0,), (0,))))


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP inside a transformer block."""

    d_model: int
    d_ff: int
    n_experts: int
    n_layers: int
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    router_top_k: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    # Expert MLP flavor: "gelu" (GPT family, biased two-matmul MLP) or
    # "swiglu" (Mixtral/llama family: silu(x·wg) * (x·wu) → wo, bias-free
    # — the same block shape as models/llama.py's dense SwiGLU).
    mlp_type: str = "gelu"
    # Quantized expert matmuls (ops/quant.py): see _expert_matmul.
    matmul_precision: str = "f32"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        batch, seqlen, d_model = x.shape
        n_exp = self.n_experts
        k = self.router_top_k
        if k not in (1, 2):
            raise ValueError(f"router_top_k must be 1 or 2, got {k}")
        if k > n_exp:
            raise ValueError(f"router_top_k {k} exceeds n_experts {n_exp}")
        capacity = max(1, int(math.ceil(self.capacity_factor * k * seqlen / n_exp)))

        # Router in float32: softmax over tiny expert dim must not run bf16.
        router_logits = nn.Dense(
            n_exp,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", None)),
            name="router",
        )(x.astype(jnp.float32))
        gates = jax.nn.softmax(router_logits, axis=-1)  # (B, T, E) f32

        # Per-choice dispatch with first-choice capacity priority: choice c
        # tokens queue behind every earlier choice's (post-cut) enqueues.
        remaining = gates
        queued = jnp.zeros((batch, n_exp), jnp.float32)  # tokens enqueued per expert
        choices = []  # (mask_post_cut, raw_prob, kept, position) per choice
        first_choice_mask = None  # pre-cut first-choice one-hot, for the aux loss
        for _ in range(k):
            mask_pre = jax.nn.one_hot(
                jnp.argmax(remaining, axis=-1), n_exp, dtype=jnp.float32
            )
            if first_choice_mask is None:
                first_choice_mask = mask_pre
            pos = (jnp.cumsum(mask_pre, axis=1) + queued[:, None, :]) * mask_pre
            mask_post = mask_pre * (pos <= capacity)
            raw_prob = jnp.sum(remaining * mask_pre, axis=-1)  # (B, T) pre-drop
            kept = jnp.sum(mask_post, axis=-1)  # (B, T) 1.0 unless dropped
            position = jnp.sum(pos * mask_post, axis=-1) - 1.0
            choices.append((mask_post, raw_prob, kept, position))
            queued = queued + mask_post.sum(axis=1)
            remaining = remaining * (1.0 - mask_pre)

        # Load-balance loss from FIRST choices: E * sum_e f_e * P_e per
        # sequence (fraction of tokens routed to e times mean router prob
        # of e), scaled so a perfectly uniform router gives aux_weight*1.0.
        density = first_choice_mask.mean(axis=1)  # (B, E)
        density_proxy = gates.mean(axis=1)  # (B, E)
        aux = self.aux_loss_weight * n_exp * n_exp * jnp.mean(density * density_proxy)
        self.sow("losses", "moe_aux", aux)

        # Combine weights: k=1 keeps the raw Switch probability; k>1
        # renormalizes the RAW router probabilities to sum to 1 (GShard) —
        # BEFORE capacity drops, so a congested neighbor zeroes a dropped
        # choice's contribution without inflating the surviving one.
        if k == 1:
            weights = [p * kp for _, p, kp, _ in choices]
        else:
            denom = jnp.maximum(sum(p for _, p, _, _ in choices), 1e-9)
            weights = [p / denom * kp for _, p, kp, _ in choices]

        # One-hot over capacity slots; dropped tokens (position 0 -> -1) map
        # to all-zero rows.
        dispatch = jnp.zeros((batch, seqlen, n_exp, capacity), jnp.float32)
        combine = jnp.zeros((batch, seqlen, n_exp, capacity), jnp.float32)
        for (mask_i, _, _, position_i), weight_i in zip(choices, weights):
            position_oh = jax.nn.one_hot(
                position_i.astype(jnp.int32), capacity, dtype=jnp.float32
            )
            dispatch_i = mask_i[..., None] * position_oh[:, :, None, :]  # (B,T,E,C)
            dispatch = dispatch + dispatch_i
            combine = combine + dispatch_i * weight_i[:, :, None, None]

        # Dispatch tokens: (B,T,E,C) x (B,T,D) -> (E,B,C,D). The E dim is
        # expert-sharded, B stays data-sharded (act_expert_group) — the
        # resharding between the two layouts is the all-to-all.
        expert_in = jnp.einsum(
            "btec,btd->ebcd", dispatch.astype(x.dtype), x.astype(x.dtype)
        )
        expert_in = nn.with_logical_constraint(
            expert_in, ("act_expert", "act_expert_group", None, "act_embed")
        )

        if self.mlp_type == "swiglu":
            wg = self.param(
                "wg",
                nn.with_logical_partitioning(_DENSE_INIT, ("expert", "embed", "mlp")),
                (n_exp, d_model, self.d_ff),
                self.param_dtype,
            )
            wu = self.param(
                "wu",
                nn.with_logical_partitioning(_DENSE_INIT, ("expert", "embed", "mlp")),
                (n_exp, d_model, self.d_ff),
                self.param_dtype,
            )
            wo = self.param(
                "wo",
                nn.with_logical_partitioning(
                    _scaled_init(self.n_layers), ("expert", "mlp", "embed")
                ),
                (n_exp, self.d_ff, d_model),
                self.param_dtype,
            )
            gate = _expert_matmul(
                expert_in, wg.astype(self.dtype), self.matmul_precision,
                "ebcd,edf->ebcf",
            )
            up = _expert_matmul(
                expert_in, wu.astype(self.dtype), self.matmul_precision,
                "ebcd,edf->ebcf",
            )
            h = nn.silu(gate) * up
            h = nn.with_logical_constraint(
                h, ("act_expert", "act_expert_group", None, "act_mlp")
            )
            expert_out = _expert_matmul(
                h, wo.astype(self.dtype), self.matmul_precision, "ebcf,efd->ebcd"
            )
        elif self.mlp_type == "gelu":
            wi = self.param(
                "wi",
                nn.with_logical_partitioning(_DENSE_INIT, ("expert", "embed", "mlp")),
                (n_exp, d_model, self.d_ff),
                self.param_dtype,
            )
            bi = self.param(
                "bi",
                nn.with_logical_partitioning(nn.initializers.zeros_init(), ("expert", "mlp")),
                (n_exp, self.d_ff),
                self.param_dtype,
            )
            wo = self.param(
                "wo",
                nn.with_logical_partitioning(
                    _scaled_init(self.n_layers), ("expert", "mlp", "embed")
                ),
                (n_exp, self.d_ff, d_model),
                self.param_dtype,
            )
            bo = self.param(
                "bo",
                nn.with_logical_partitioning(nn.initializers.zeros_init(), ("expert", "embed")),
                (n_exp, d_model),
                self.param_dtype,
            )

            h = _expert_matmul(
                expert_in, wi.astype(self.dtype), self.matmul_precision,
                "ebcd,edf->ebcf",
            )
            h = h + bi.astype(self.dtype)[:, None, None, :]
            h = nn.with_logical_constraint(h, ("act_expert", "act_expert_group", None, "act_mlp"))
            h = nn.gelu(h, approximate=False)
            expert_out = _expert_matmul(
                h, wo.astype(self.dtype), self.matmul_precision, "ebcf,efd->ebcd"
            )
            expert_out = expert_out + bo.astype(self.dtype)[:, None, None, :]
        else:
            raise ValueError(
                f"mlp_type {self.mlp_type!r} unknown; expected 'gelu' or 'swiglu'"
            )
        expert_out = nn.with_logical_constraint(
            expert_out, ("act_expert", "act_expert_group", None, "act_embed")
        )

        # Combine back to (B, T, D); dropped tokens get 0 (residual carries them).
        out = jnp.einsum("btec,ebcd->btd", combine.astype(x.dtype), expert_out)
        return nn.with_logical_constraint(out, ("batch", "length", "act_embed"))


__all__ = ["MoEMLP"]
