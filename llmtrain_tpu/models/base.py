"""Model-adapter plugin contract.

Parity target: reference ``src/llmtrain/models/base.py`` (ModelAdapter ABC
with build_model/build_tokenizer/compute_loss, :12-27), adapted to JAX's
functional split between module definition and parameters:

* ``build_model`` returns a Flax module (pure function of params + inputs).
* ``init_params`` is new — JAX params are explicit, not stored in the module.
* ``compute_loss`` takes ``(model, params, batch)`` and must be jit-traceable:
  shape/dtype validation happens at trace time (Python raises are fine there),
  and returned metrics are JAX scalars, not floats.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.schemas import RunConfig

Params = Any  # PyTree of arrays
Batch = dict[str, jax.Array]
Metrics = dict[str, jax.Array]


class ModelAdapter(ABC):
    """Builds a Flax model + tokenizer and defines its training loss."""

    # Extra-dict keys this adapter understands (config/extras.py warns on
    # others). None disables the check for plugins with free-form extras.
    known_extra_keys: frozenset[str] | None = None

    # True only for models that stack their layer dim on the "layers"
    # logical axis so a mesh `pipeline` axis can shard stages
    # (models/gpt_pipeline.py). The Trainer rejects pipeline > 1 otherwise.
    supports_pipeline = False

    @abstractmethod
    def build_model(self, cfg: RunConfig) -> nn.Module:
        """Construct the (uninitialized) Flax module from config."""

    @abstractmethod
    def build_tokenizer(self, cfg: RunConfig) -> Any | None:
        """Construct the tokenizer, or None for models that need none."""

    def batch_divisor(self, cfg: RunConfig, mesh: Any) -> int:
        """Rows every applied batch must be a multiple of (default 1).

        Pipelined models return data_shards × microbatches: the Trainer
        pads eval batches up to this with zero-masked rows (exact under
        token-weighted aggregation) instead of silently dropping pipeline
        parallelism mid-eval.
        """
        return 1

    @staticmethod
    def _positive_extra(cfg: RunConfig, key: str, default: int) -> int:
        """Validated ``model.extra`` integer knob (>= 1), shared by adapters."""
        value = int(cfg.model.extra.get(key, default))
        if value < 1:
            raise ValueError(f"model.extra.{key} must be >= 1, got {value}")
        return value

    def init_params(self, model: nn.Module, cfg: RunConfig, rng: jax.Array) -> Params:
        """Initialize the parameter PyTree.

        Default: trace the module with a dummy ``(1, block_size)`` token batch.
        """
        tokens = jnp.zeros((1, cfg.model.block_size), dtype=jnp.int32)
        variables = model.init({"params": rng}, tokens, deterministic=True)
        return variables["params"]

    def compute_loss(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, Metrics]:
        """Pure loss function: ``(scalar loss, metrics dict of JAX scalars)``.

        Default derives the scalar from ``compute_loss_components`` (one
        forward, token-weighted mean). Adapters implement at least one of
        the two methods.
        """
        comps = self.compute_loss_components(
            model, params, batch, rngs=rngs, deterministic=deterministic
        )
        if comps is None:
            raise NotImplementedError(
                f"{type(self).__name__} must implement compute_loss or "
                "compute_loss_components"
            )
        loss_sum, tokens = comps
        loss = jnp.sum(loss_sum) / jnp.maximum(jnp.sum(tokens), 1.0)
        return loss, {"loss": loss}

    def compute_loss_components(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, jax.Array] | None:
        """Optional per-example ``(loss_sum, token_count)`` arrays of shape (B,).

        When an adapter implements this, the trainer derives the scalar loss
        as ``sum(loss_sum)/sum(token_count)`` and gets exact per-data-shard
        metrics (the ``*_rank_{r}`` keys, reference trainer.py:428-482) and
        token-weighted eval (reference trainer.py:243-289) from one forward.
        Returning None makes the trainer fall back to ``compute_loss``.
        """
        del model, params, batch, rngs, deterministic
        return None


def validate_lm_batch(batch: Batch) -> tuple[jax.Array, jax.Array, jax.Array | None]:
    """Trace-time validation shared by language-model adapters.

    Mirrors the reference's defensive checks (reference models/gpt.py:214-252):
    2-D input_ids/labels of equal shape, integer dtype, seq len >= 2, and an
    optional attention_mask matching input_ids.
    """
    input_ids = batch["input_ids"]
    labels = batch["labels"]
    attention_mask = batch.get("attention_mask")

    if input_ids.ndim != 2 or labels.ndim != 2:
        raise ValueError(
            f"Expected input_ids and labels to be 2D (B, T); "
            f"got {tuple(input_ids.shape)} and {tuple(labels.shape)}."
        )
    if input_ids.shape != labels.shape:
        raise ValueError(
            "Expected input_ids and labels to have the same shape; "
            f"got {tuple(input_ids.shape)} vs {tuple(labels.shape)}."
        )
    if not jnp.issubdtype(input_ids.dtype, jnp.integer) or not jnp.issubdtype(
        labels.dtype, jnp.integer
    ):
        raise ValueError(
            f"Expected integer input_ids and labels; got {input_ids.dtype} and {labels.dtype}."
        )
    if input_ids.shape[1] < 2:
        raise ValueError("Expected sequence length >= 2 for next-token loss.")

    if attention_mask is not None:
        if attention_mask.ndim != 2 or attention_mask.shape != input_ids.shape:
            raise ValueError(
                "Expected attention_mask to match input_ids shape; "
                f"got {tuple(attention_mask.shape)} vs {tuple(input_ids.shape)}."
            )
        if not (
            jnp.issubdtype(attention_mask.dtype, jnp.integer)
            or attention_mask.dtype == jnp.bool_
        ):
            raise ValueError(f"Expected bool or integer attention_mask; got {attention_mask.dtype}.")

    return input_ids, labels, attention_mask


def lm_loss_components(
    model: nn.Module,
    params: Params,
    batch: Batch,
    *,
    rngs: dict[str, jax.Array] | None = None,
    deterministic: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """Shared LM forward → per-example (loss_sum, token_count).

    Honors the model's ``z_loss`` field when present (models/gpt.py).
    """
    input_ids, labels, attention_mask = validate_lm_batch(batch)
    logits = model.apply(
        {"params": params},
        input_ids,
        attention_mask=attention_mask,
        deterministic=deterministic,
        rngs=rngs,
    )
    return masked_ce_components(
        logits, labels, attention_mask, z_loss=getattr(model, "z_loss", 0.0)
    )


def masked_cross_entropy(
    logits: jax.Array, labels: jax.Array, attention_mask: jax.Array | None
) -> jax.Array:
    """Position-wise CE with mask-aware mean (reference gpt.py:256-269).

    Labels are already shifted by the data pipeline (reference hf_text.py:125),
    so no shift happens here.
    """
    loss_sum, tokens = masked_ce_components(logits, labels, attention_mask)
    return jnp.sum(loss_sum) / jnp.maximum(jnp.sum(tokens), 1.0)


def masked_ce_components(
    logits: jax.Array,
    labels: jax.Array,
    attention_mask: jax.Array | None,
    *,
    z_loss: float = 0.0,
) -> tuple[jax.Array, jax.Array]:
    """Per-example ``(loss_sum, token_count)`` of shape (B,), CE in float32.

    ``z_loss > 0`` adds PaLM's softmax-normalizer regularizer
    ``z_loss * log(Z)^2`` per token (Z = sum exp(logits)) — keeps bf16
    logits from drifting large and the softmax well-conditioned. New
    capability over the reference (its loss is plain CE, gpt.py:256-269).
    """
    logits32 = logits.astype(jnp.float32)
    # One reduction serves both terms: CE = lse - logit[label], and the
    # z-loss reuses the same lse (mirrors ops/chunked_ce.py).
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)
    label_logit = jnp.take_along_axis(logits32, labels[..., None], axis=-1)[..., 0]
    per_token = lse - label_logit
    if z_loss > 0.0:
        per_token = per_token + z_loss * jnp.square(lse)
    if attention_mask is None:
        mask = jnp.ones_like(per_token)
    else:
        # BOOLEAN semantics (nonzero = real token): the mask may carry
        # segment ids > 1 for packed cross-document masking — they must
        # not become loss weights.
        mask = (attention_mask != 0).astype(jnp.float32)
    return jnp.sum(per_token * mask, axis=-1), jnp.sum(mask, axis=-1)
