"""Decoder-only GPT in Flax, designed mesh-first.

Parity target: reference ``src/llmtrain/models/gpt.py`` — learned token +
position embeddings (:127-128), pre-norm blocks (LN→attn→residual,
LN→MLP(GELU)→residual, :99-106), causal masking with padding-mask support
(:56-74), final LN + bias-free lm_head with optional weight tying (:142-146),
init N(0, 0.02) with residual projections scaled by 1/sqrt(2*n_layers)
(:151-165), block-size overflow raise (:41-42, :171-174), tiktoken gpt2
tokenizer + vocab sizing (:192-212), mask-aware CE loss (:214-271).

TPU-first divergences (the point of the rebuild):

* Every parameter carries *logical axis names* (``vocab``/``embed``/``heads``/
  ``kv``/``mlp``) via ``nn.with_logical_partitioning``, and activations carry
  ``nn.with_logical_constraint`` hints. Mapping logical names → mesh axes
  (data/fsdp/tensor/sequence) happens in ``llmtrain_tpu.parallel.sharding``,
  so the same module runs pure-DP, FSDP, TP, or SP without code changes.
* Attention is einsum-form with the softmax in float32 (bf16-safe on MXU);
  no (block_size, block_size) mask buffer is materialized as a parameter —
  the mask is built at trace time and fused by XLA.
* ``dtype``/``param_dtype`` split for bf16 compute over f32 master params.
* ``remat`` wraps blocks in ``nn.remat`` to trade FLOPs for HBM.
* ``attention='flash'`` routes to the Pallas kernel in ``llmtrain_tpu.ops``.
"""

from __future__ import annotations

import logging
import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.activation_tiers import canonical_tier_spec, parse_activation_tiers
from ..config.schemas import RunConfig
from ..registry.models import register_model
from .activation_policy import (
    resolve_activation_tiers,
    tag_block_input,
    tier_block_classes,
)
from .base import (
    Batch,
    ModelAdapter,
    Params,
    lm_loss_components,
)

_EMBED_INIT = nn.initializers.normal(stddev=0.02)
_DENSE_INIT = nn.initializers.normal(stddev=0.02)

# model.extra.remat_policy values -> jax.checkpoint policies (None = the
# default: save nothing, recompute the whole block).
REMAT_POLICIES = {
    "nothing": None,
    "dots": jax.checkpoint_policies.dots_saveable,
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
}


def _scaled_init(n_layers: int) -> nn.initializers.Initializer:
    """Residual-projection init, std 0.02/sqrt(2*n_layers) (reference :151-165)."""
    return nn.initializers.normal(stddev=0.02 / math.sqrt(2 * n_layers))


logger = logging.getLogger(__name__)

_TIER_MIGRATION_LOGGED = False


def _log_tier_migration(remat_policy: str, spec: str) -> None:
    """One-time (per process) log naming the remat->tiers migration."""
    global _TIER_MIGRATION_LOGGED
    if not _TIER_MIGRATION_LOGGED:
        _TIER_MIGRATION_LOGGED = True
        logger.info(
            "model.remat: true is deprecated; mapped remat_policy %r to "
            "model.extra.activation_tiers: %r (set activation_tiers "
            "directly to silence this)",
            remat_policy,
            spec,
        )


# Deprecation shim: `model.remat: true` maps onto the tier that keeps its
# remat_policy semantics ("dots_no_batch" has no tier — it stays on the
# legacy module remat path).
_REMAT_POLICY_TO_TIER = {"nothing": "full", "dots": "selective"}


def resolve_config_activation_tiers(cfg: RunConfig) -> tuple[str, ...] | None:
    """Per-layer activation tiers for ``cfg``, backend-resolved.

    Explicit ``model.extra.activation_tiers`` wins (and conflicts with
    ``model.remat: true``); the deprecated ``model.remat: true`` migrates
    to an equivalent all-layers tier with a one-time INFO log. Returns
    None when the model should use the legacy remat fields (remat off, or
    remat_policy ``dots_no_batch``).
    """
    spec = cfg.model.extra.get("activation_tiers")
    if spec is not None:
        if cfg.model.remat:
            raise ValueError(
                "model.remat: true conflicts with model.extra."
                "activation_tiers; drop model.remat (tiers subsume it)"
            )
        tiers = parse_activation_tiers(str(spec), cfg.model.n_layers)
        return resolve_activation_tiers(tiers)
    if cfg.model.remat:
        remat_policy = str(cfg.model.extra.get("remat_policy", "nothing"))
        tier = _REMAT_POLICY_TO_TIER.get(remat_policy)
        if tier is None:
            return None
        _log_tier_migration(remat_policy, f"{tier}:*")
        return (tier,) * cfg.model.n_layers
    return None


class FusedLayerNorm(nn.Module):
    """nn.LayerNorm twin backed by the Pallas fused kernel
    (ops/fused_norm.py). Same parameter names (``scale``/``bias``),
    shapes, and logical partitioning — checkpoints are interchangeable
    with the unfused path. The optional ``residual`` argument fuses the
    preceding residual add into the same VMEM pass and returns
    ``(normed, summed)``."""

    dtype: Any
    param_dtype: Any
    epsilon: float = 1e-6
    interpret: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, residual: jax.Array | None = None):
        from ..ops.fused_norm import fused_add_layer_norm, fused_layer_norm

        d = x.shape[-1]
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            (d,),
            self.param_dtype,
        )
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
            (d,),
            self.param_dtype,
        )
        x = x.astype(self.dtype)
        if residual is None:
            return fused_layer_norm(
                x, scale, bias, self.epsilon, 256, self.interpret
            )
        return fused_add_layer_norm(
            x,
            residual.astype(self.dtype),
            scale,
            bias,
            self.epsilon,
            256,
            self.interpret,
        )


class CausalSelfAttention(nn.Module):
    d_model: int
    n_heads: int
    n_layers: int
    dropout: float
    dtype: Any
    param_dtype: Any
    attention: str = "dense"
    decode: bool = False  # autoregressive KV-cache mode (generation only)
    cache_len: int = 0  # KV-cache capacity; block_size when decode=True
    # Grouped-query attention: K/V heads (0 = n_heads, classic MHA; 1 =
    # MQA). Queries in group g attend the shared K/V head g. The flash
    # path consumes narrow K/V natively (the Pallas kernels index K/V by
    # head group — no jnp.repeat in HBM, the training-bandwidth win); the
    # decode cache stores only n_kv_heads (the serving-memory win);
    # ring/ulysses/dense broadcast K/V up to n_heads before attention.
    # n_kv_heads == n_heads keeps the MHA fused-qkv parameter tree
    # (checkpoint compatibility).
    n_kv_heads: int = 0
    # Data is guaranteed packed (all-ones masks): drop the mask operand
    # from the flash kernels — identical math, no mask streaming.
    assume_packed: bool = False
    # Llama-family knobs (models/llama.py): bias-free projections and
    # rotary position embeddings (ops/rope.py). RoPE rotates q/k after
    # projection — at decode time inside ``_decode_attention`` so the
    # rotation uses absolute positions from the cache cursor BEFORE the
    # keys are written (cached keys are stored rotated; queries at later
    # steps then compare directly). GPT defaults leave both off.
    use_bias: bool = True
    # Qwen2-style bias split (models/qwen2.py): bias on the q/k/v
    # projections only, out_proj follows ``use_bias``. None = q/k/v
    # follow ``use_bias`` too (GPT fully biased, Llama fully bias-free).
    qkv_bias: bool | None = None
    rope: bool = False
    rope_theta: float = 10000.0
    # Sliding-window attention (Mistral semantics: query i attends keys in
    # (i-window, i]). 0 = full causal. Supported on the dense/flash/decode
    # paths; ring/ulysses reject it loudly (a windowed ring schedule is a
    # different algorithm — most hops would carry dead shards).
    sliding_window: int = 0
    # Extra rolling-cache slots beyond the window (decode only).
    # Speculative decoding (speculative.py) writes up to gamma+1 positions
    # that may be ROLLED BACK; in a W-slot ring those writes would evict
    # live window entries rollback cannot restore. With W+gamma+1 slots
    # every evicted position is provably outside all future queries'
    # windows (evicted = p - C <= row - W).
    ring_slack: int = 0
    # KV-cache storage dtype (decode only): "model" keeps the compute
    # dtype; "int8" stores codes + one f32 scale per written (batch,
    # position, kv-head) — amax over head_dim — halving cache HBM vs
    # bf16 (4x vs f32). Long-generation serving memory is KV-bound, so
    # this is the cache-side sibling of weight-only quantization
    # (ops/quant.py). Dequant happens in-graph at the attention read;
    # XLA fuses it into the score einsum's operand load. Speculative
    # rollback (cursor-only) is unaffected: rolled-back slots are
    # simply rewritten, codes and scales together.
    kv_cache_dtype: str = "model"
    # Paged decode (serving/paged_kv.py): the cache is a POOL of
    # fixed-size blocks shared by every in-flight sequence instead of a
    # per-row linear buffer. The caller passes per-row absolute positions
    # and a block table mapping logical block i -> physical pool block;
    # N sequences of different lengths then share ONE jitted program
    # (continuous batching, vLLM's PagedAttention layout). Batch size
    # never shapes the cache, so join/evict needs no cache reshuffle.
    paged: bool = False
    paged_num_blocks: int = 0
    paged_block_tokens: int = 0
    # Quantized training matmuls (ops/quant.py, model.extra.matmul_precision):
    # "int8"/"int8_act"/"fp8" route every projection through
    # quant_dot_general — straight-through gradients, f32 master weights,
    # unchanged param tree. "f32" keeps the stock flax path bit-identical.
    matmul_precision: str = "f32"

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        attention_mask: jax.Array | None = None,
        *,
        deterministic: bool = True,
        positions: jax.Array | None = None,
        block_tables: jax.Array | None = None,
    ) -> jax.Array:
        head_dim = self.d_model // self.n_heads
        kv_heads = self.n_kv_heads or self.n_heads
        qkv_use_bias = self.use_bias if self.qkv_bias is None else self.qkv_bias
        if self.sliding_window and self.attention in ("ring", "ulysses"):
            raise ValueError(
                f"sliding_window is not supported with attention="
                f"{self.attention!r}; use 'flash' or 'dense'"
            )
        # None under "f32": the stock flax dot path, bit-identical to a
        # build without the knob (ops/quant.quant_dot_general contract).
        from ..ops.quant import quant_dot_general

        quant_dg = quant_dot_general(self.matmul_precision)

        if kv_heads == self.n_heads:
            qkv = nn.DenseGeneral(
                features=(3, self.n_heads, head_dim),
                axis=-1,
                use_bias=qkv_use_bias,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "qkv", "heads", "kv")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("qkv", "heads", "kv")
                ),
                dot_general=quant_dg,
                name="qkv_proj",
            )(x)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            if self.n_heads % kv_heads != 0:
                raise ValueError(
                    f"n_heads ({self.n_heads}) must be divisible by "
                    f"n_kv_heads ({kv_heads})"
                )
            q = nn.DenseGeneral(
                features=(self.n_heads, head_dim),
                axis=-1,
                use_bias=qkv_use_bias,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "heads", "kv")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("heads", "kv")
                ),
                dot_general=quant_dg,
                name="q_proj",
            )(x)
            kv = nn.DenseGeneral(
                features=(2, kv_heads, head_dim),
                axis=-1,
                use_bias=qkv_use_bias,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "qkv", "heads", "kv")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros_init(), ("qkv", "heads", "kv")
                ),
                dot_general=quant_dg,
                name="kv_proj",
            )(x)
            k, v = kv[:, :, 0], kv[:, :, 1]
        q = nn.with_logical_constraint(q, ("batch", "length", "act_heads", "act_kv"))
        k = nn.with_logical_constraint(k, ("batch", "length", "act_heads", "act_kv"))
        v = nn.with_logical_constraint(v, ("batch", "length", "act_heads", "act_kv"))

        if self.rope and not self.decode:
            # Global-view positions: under sequence parallelism pjit keeps
            # the arange consistent with the length-sharded activations.
            # Rotating before the GQA broadcast/attention impls is exact —
            # RoPE is per-(position, feature), independent of head layout.
            # The decode path rotates inside _decode_attention, offset by
            # the cache cursor.
            from ..ops.rope import apply_rope

            q, k = apply_rope(
                q, k, jnp.arange(q.shape[1]), theta=self.rope_theta
            )

        if (
            not self.decode
            and kv_heads != self.n_heads
            and self.attention == "dense"
        ):
            # Only dense sees full-width K/V (compute-equivalent GQA).
            # Flash consumes narrow K/V natively (Pallas index maps), ring
            # rotates the narrow shards, ulysses exchanges them narrow
            # (G x less wire traffic in each case — blockwise groups
            # queries in its einsums), and the decode path keeps the
            # narrow cache, broadcasting at read.
            reps = self.n_heads // kv_heads
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)

        if self.decode and self.paged:
            # Paged KV decode: block-pool cache shared across sequences,
            # per-row positions/block tables (continuous batching serving).
            out = self._paged_decode_attention(q, k, v, positions, block_tables)
        elif self.decode:
            # KV-cache decode: append this call's keys/values at the cache
            # cursor, attend over the filled prefix. One compiled program
            # serves both prefill (T = prompt length) and per-token steps
            # (T = 1) — new capability over the reference, whose notebook
            # generation re-runs the full forward per token.
            out = self._decode_attention(q, k, v)
        elif self.attention == "flash":
            # Padding masks are applied INSIDE attention (reference
            # gpt.py:60-64 semantics) — the Pallas kernels take the (B, T)
            # key mask directly. assume_packed drops the operand when the
            # data is provably packed (all-ones masks ≡ no mask).
            from ..ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v,
                attention_mask=None if self.assume_packed else attention_mask,
                causal=True,
                window=self.sliding_window,
            )
        elif self.attention == "ring":
            # Sequence-parallel exact attention over the mesh's `sequence`
            # axis (ops/ring_attention.py); falls back to blockwise when no
            # ambient mesh shards the sequence. Padding masks are applied
            # inside attention here too (the mask shard rotates with its
            # K/V shard); assume_packed drops the operand like flash.
            from ..ops.ring_attention import ring_or_blockwise

            out = ring_or_blockwise(
                q, k, v,
                causal=True,
                key_mask=None if self.assume_packed else attention_mask,
            )
        elif self.attention == "ulysses":
            # All-to-all sequence parallelism (ops/ulysses_attention.py):
            # the ring alternative — 2 all-to-alls instead of s ppermutes.
            # The mask arrives full-sequence on every device (replicated
            # by the shard_map in_spec — no runtime gather).
            from ..ops.ulysses_attention import ulysses_or_blockwise

            out = ulysses_or_blockwise(
                q, k, v,
                causal=True,
                key_mask=None if self.assume_packed else attention_mask,
            )
        else:
            out = dense_attention(
                q,
                k,
                v,
                attention_mask=attention_mask,
                dropout=self.dropout,
                deterministic=deterministic,
                dropout_rng_module=self,
                window=self.sliding_window,
            )

        out = nn.DenseGeneral(
            features=self.d_model,
            axis=(-2, -1),
            use_bias=self.use_bias,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                _scaled_init(self.n_layers), ("heads", "kv", "embed")
            ),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
            dot_general=quant_dg,
            name="out_proj",
        )(out)
        out = nn.Dropout(self.dropout)(out, deterministic=deterministic)

        if attention_mask is not None:
            # Zero padded rows so they contribute nothing downstream
            # (reference gpt.py:73-74). Boolean compare: the mask may
            # carry segment ids > 1 (packed cross-document masking).
            out = out * (attention_mask != 0)[:, :, None].astype(out.dtype)
        return out

    def _decode_attention(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        """Cached causal attention: write k/v at the cursor, read the prefix.

        q/k/v: (B, T, H, Dh) with T = tokens appended this call. The cache
        holds ``cache_len`` positions — or, under a sliding window, a
        ROLLING buffer of ``min(cache_len, window)`` slots (the Mistral
        serving layout): slot ``pos % C`` holds position ``pos``, so
        per-layer KV memory is O(window) however long the generation. A
        per-slot position buffer (stored as position+1 so the zero-init
        cache means "empty") drives the mask instead of slot order.

        Rolling-prefill caveat: a prompt longer than the window writes
        only its last C keys, so logits at INTERIOR prefill positions
        (whose windows reach dropped keys) are approximate — harmless for
        generation, which samples from the final position only; its
        window is exactly the kept set. Rows must share one sequence
        length (generation batches rectangular prompts,
        generation.py:111-120).
        """
        if self.cache_len <= 0:
            raise ValueError("decode=True requires cache_len > 0 (the block size)")
        if self.kv_cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"kv_cache_dtype {self.kv_cache_dtype!r} unknown; expected "
                "'model' or 'int8'"
            )
        quant_cache = self.kv_cache_dtype == "int8"
        batch, t, n_heads, head_dim = q.shape
        kv_width = k.shape[2]  # n_kv_heads under GQA, else n_heads
        ring = (self.sliding_window + self.ring_slack) if self.sliding_window else 0
        rolling = bool(ring) and ring < self.cache_len
        cap = ring if rolling else self.cache_len
        cached_key = self.variable(
            "cache",
            "cached_key",
            jnp.zeros,
            (batch, cap, kv_width, head_dim),
            jnp.int8 if quant_cache else k.dtype,
        )
        cached_value = self.variable(
            "cache",
            "cached_value",
            jnp.zeros,
            (batch, cap, kv_width, head_dim),
            jnp.int8 if quant_cache else v.dtype,
        )
        if quant_cache:
            # One f32 scale per written (batch, slot, kv-head); zero on
            # never-written slots (dequantizes to 0.0, and the liveness
            # mask excludes those slots anyway).
            key_scale = self.variable(
                "cache", "key_scale", jnp.zeros,
                (batch, cap, kv_width, 1), jnp.float32,
            )
            value_scale = self.variable(
                "cache", "value_scale", jnp.zeros,
                (batch, cap, kv_width, 1), jnp.float32,
            )

            def _q8(x: jax.Array) -> tuple[jax.Array, jax.Array]:
                # ONE quantization recipe in the package: the weight
                # quantizer's math, reduced over head_dim per position.
                from ..ops.quant import quantize_array

                qa = quantize_array(x, reduce_axes=(x.ndim - 1,))
                return qa.q, qa.scale

        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )

        idx = cache_index.value
        if self.rope:
            # Rotate by absolute position BEFORE the cache write: the
            # cache then holds rotated keys, and later steps' queries
            # (rotated by their own positions) compare directly.
            from ..ops.rope import apply_rope

            q, k = apply_rope(
                q, k, idx + jnp.arange(t), theta=self.rope_theta
            )
        if rolling:
            # Slot position+1 per slot; 0 = never written (zero-init safe —
            # generation.py zeroes the cache tree from an eval_shape trace).
            cached_pos1 = self.variable(
                "cache", "cached_pos1", jnp.zeros, (cap,), jnp.int32
            )
            # Only the LAST `cap` tokens of this call can survive the ring;
            # t and cap are static, so this is a static slice. Writing at
            # most `cap` tokens keeps the scatter indices duplicate-free.
            keep = min(t, cap)
            pos = idx + t - keep + jnp.arange(keep)  # absolute positions kept
            slots = pos % cap
            if quant_cache:
                kc, ks = _q8(k[:, t - keep :])
                vc, vs = _q8(v[:, t - keep :])
                cached_key.value = cached_key.value.at[:, slots].set(kc)
                cached_value.value = cached_value.value.at[:, slots].set(vc)
                key_scale.value = key_scale.value.at[:, slots].set(ks)
                value_scale.value = value_scale.value.at[:, slots].set(vs)
            else:
                cached_key.value = cached_key.value.at[:, slots].set(
                    k[:, t - keep :].astype(cached_key.value.dtype)
                )
                cached_value.value = cached_value.value.at[:, slots].set(
                    v[:, t - keep :].astype(cached_value.value.dtype)
                )
            cached_pos1.value = cached_pos1.value.at[slots].set(pos + 1)
            col_pos = cached_pos1.value - 1  # (C,): -1 = empty slot
        else:
            if quant_cache:
                kc, ks = _q8(k)
                vc, vs = _q8(v)
                cached_key.value = jax.lax.dynamic_update_slice(
                    cached_key.value, kc, (0, idx, 0, 0)
                )
                cached_value.value = jax.lax.dynamic_update_slice(
                    cached_value.value, vc, (0, idx, 0, 0)
                )
                key_scale.value = jax.lax.dynamic_update_slice(
                    key_scale.value, ks, (0, idx, 0, 0)
                )
                value_scale.value = jax.lax.dynamic_update_slice(
                    value_scale.value, vs, (0, idx, 0, 0)
                )
            else:
                cached_key.value = jax.lax.dynamic_update_slice(
                    cached_key.value, k.astype(cached_key.value.dtype), (0, idx, 0, 0)
                )
                cached_value.value = jax.lax.dynamic_update_slice(
                    cached_value.value, v.astype(cached_value.value.dtype), (0, idx, 0, 0)
                )
            col_pos = None
        cache_index.value = idx + t

        keys, values = cached_key.value, cached_value.value
        if quant_cache:
            # In-graph dequant: XLA streams the int8 codes from HBM (the
            # bandwidth win) and fuses convert+multiply into the einsum
            # operand reads.
            keys = (keys.astype(jnp.float32) * key_scale.value).astype(q.dtype)
            values = (values.astype(jnp.float32) * value_scale.value).astype(
                q.dtype
            )
        scale = 1.0 / math.sqrt(head_dim)
        # Grouped-query decode (g=1 is classic MHA): the cache holds
        # n_kv_heads (the memory win) and stays narrow at read too —
        # queries are grouped against the shared K/V heads, so the
        # per-step HBM read is G x smaller than broadcasting the cache
        # (query head k*G+g attends kv head k, matching jnp.repeat
        # semantics).
        g = n_heads // kv_width
        qg = q.reshape(batch, t, kv_width, g, head_dim)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, keys) * scale
        scores = scores.astype(jnp.float32)
        row = (idx + jnp.arange(t))[None, None, None, :, None]
        if rolling:
            # Mask by each slot's ABSOLUTE position (slot order is ring
            # order, not sequence order): live iff written, causal, and
            # within the window.
            col = col_pos[None, None, None, None, :]
            live = (col >= 0) & (col <= row) & (row - col < self.sliding_window)
        else:
            # Query at absolute position idx+i may see cache slots <= idx+i
            # (and, under a window >= cache_len, the window constraint —
            # kept for exactness even though it can only bind when the
            # model's block_size exceeds the window).
            col = jnp.arange(cap)[None, None, None, None, :]
            live = col <= row
            if self.sliding_window:
                live = live & (row - col < self.sliding_window)
        scores = jnp.where(live, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs, values)
        return out.reshape(batch, t, n_heads, head_dim)

    def _paged_decode_attention(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        positions: jax.Array | None,
        block_tables: jax.Array | None,
    ) -> jax.Array:
        """Block-pool cached attention (continuous batching serving).

        The cache is a pool of ``paged_num_blocks`` blocks of
        ``paged_block_tokens`` positions each, SHARED by every in-flight
        sequence — batch size never shapes the cache, so sequences can
        join/leave the batch without a cache reshuffle. ``block_tables``
        (B, max_blocks) maps row b's logical block i to a physical pool
        block (the host-side free-list allocator in serving/paged_kv.py
        owns the mapping; physical block 0 is the reserved null block
        padded table entries point at). ``positions`` (B,) is each row's
        absolute position of the FIRST token in this call; rows at
        different depths coexist in one program — the continuous-batching
        primitive the linear cursor cache cannot express (its cursor is
        one scalar for the whole batch).

        Token t of row b writes K/V at pool[table[b, p//bt], p%bt] with
        p = positions[b]+t, then attends the gathered blocks masked by
        absolute position (col <= p) — the same liveness rule as the
        linear path, so outputs match single-sequence decode.
        """
        if positions is None or block_tables is None:
            raise ValueError(
                "paged decode requires the `positions` (B,) and "
                "`block_tables` (B, max_blocks) call arguments"
            )
        nb, bt = self.paged_num_blocks, self.paged_block_tokens
        if nb <= 1 or bt <= 0:
            raise ValueError(
                "paged decode requires paged_num_blocks > 1 and "
                f"paged_block_tokens > 0 (got {nb}, {bt}) — use "
                "GPT.for_paged_decoding()"
            )
        if self.sliding_window or self.kv_cache_dtype != "model":
            # Scope: full-causal, full-precision cache. RoPE is supported
            # (rotation by the per-row absolute positions below), so the
            # llama family serves paged; the sliding-window ring and the
            # int8 cache keep their named raise — for_paged_decoding()
            # pre-checks the model-level fields too.
            raise ValueError(
                "paged decode does not support sliding_window/"
                "quantized cache yet"
            )
        batch, t, n_heads, head_dim = q.shape
        kv_width = k.shape[2]
        paged_key = self.variable(
            "cache", "paged_key", jnp.zeros, (nb, bt, kv_width, head_dim), k.dtype
        )
        paged_value = self.variable(
            "cache", "paged_value", jnp.zeros, (nb, bt, kv_width, head_dim), v.dtype
        )
        # Absolute position of every token in this call, per row.
        pos = positions[:, None] + jnp.arange(t)[None, :]  # (B, t)
        if self.rope:
            # Rotate by PER-ROW absolute positions before the cache
            # write (the linear path's recipe at a (B, t) position grid):
            # the pool then holds rotated keys, directly comparable to
            # any later query rotated by its own positions.
            from ..ops.rope import apply_rope

            q, k = apply_rope(q, k, pos, theta=self.rope_theta)
        blocks = jnp.take_along_axis(block_tables, pos // bt, axis=1)  # (B, t)
        slots = pos % bt
        # Distinct rows hold disjoint physical blocks (allocator invariant),
        # so the only duplicate targets are padded rows' null-block writes —
        # garbage nothing live ever reads.
        paged_key.value = paged_key.value.at[blocks, slots].set(
            k.astype(paged_key.value.dtype)
        )
        paged_value.value = paged_value.value.at[blocks, slots].set(
            v.astype(paged_value.value.dtype)
        )

        s = block_tables.shape[1] * bt
        keys = paged_key.value[block_tables].reshape(batch, s, kv_width, head_dim)
        values = paged_value.value[block_tables].reshape(
            batch, s, kv_width, head_dim
        )
        scale = 1.0 / math.sqrt(head_dim)
        g = n_heads // kv_width  # grouped-query read, like the linear path
        qg = q.reshape(batch, t, kv_width, g, head_dim)
        scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, keys) * scale
        scores = scores.astype(jnp.float32)
        # Logical slot index IS the absolute position (block i covers
        # positions [i*bt, (i+1)*bt)): causal liveness is col <= row.
        row = pos[:, None, None, :, None]  # (B, 1, 1, t, 1)
        col = jnp.arange(s)[None, None, None, None, :]
        scores = jnp.where(col <= row, scores, jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        out = jnp.einsum("bkgqs,bskd->bqkgd", probs, values)
        return out.reshape(batch, t, n_heads, head_dim)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    attention_mask: jax.Array | None,
    dropout: float = 0.0,
    deterministic: bool = True,
    dropout_rng_module: nn.Module | None = None,
    window: int = 0,
) -> jax.Array:
    """Full-matrix causal attention; softmax in f32, matmuls on MXU dtype.

    q/k/v: (B, T, H, Dh). Returns (B, T, H, Dh). ``window`` > 0 restricts
    each query to its trailing ``window`` keys (Mistral sliding-window
    semantics) — the full-matrix reference for the flash kernels' skip-
    block implementation.
    """
    head_dim = q.shape[-1]
    seqlen = q.shape[1]
    scale = 1.0 / math.sqrt(head_dim)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    scores = scores.astype(jnp.float32)

    big_neg = jnp.finfo(jnp.float32).min
    causal = jnp.tril(jnp.ones((seqlen, seqlen), dtype=jnp.bool_))
    if window:
        pos = jnp.arange(seqlen)
        causal = causal & (pos[:, None] - pos[None, :] < window)
    scores = jnp.where(causal[None, None, :, :], scores, big_neg)
    if attention_mask is not None:
        # Segment semantics (packed sequences): nonzero = real token,
        # EQUAL nonzero values = same document — a key is live for a
        # query iff it is real and in the same segment. Plain 0/1
        # padding masks are the one-segment special case (identical
        # behavior to key-only masking for real queries; padded-query
        # rows become fully masked, which the caller's output zeroing
        # already covers).
        seg = attention_mask
        live = (seg != 0)[:, None, None, :] & (
            seg[:, None, :, None] == seg[:, None, None, :]
        )
        scores = jnp.where(live, scores, big_neg)

    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout > 0.0 and not deterministic and dropout_rng_module is not None:
        keep = 1.0 - dropout
        rng = dropout_rng_module.make_rng("dropout")
        mask = jax.random.bernoulli(rng, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0)

    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class TransformerBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    n_layers: int
    dropout: float
    dtype: Any
    param_dtype: Any
    attention: str = "dense"
    decode: bool = False
    cache_len: int = 0
    n_kv_heads: int = 0  # grouped-query attention (see CausalSelfAttention)
    assume_packed: bool = False  # drop the flash mask operand (packed data)
    sliding_window: int = 0  # Mistral-style window; 0 = full causal
    ring_slack: int = 0  # extra rolling-cache slots (speculative decode)
    kv_cache_dtype: str = "model"  # "int8": quantized decode cache
    # Paged block-pool decode cache (see CausalSelfAttention.paged).
    paged: bool = False
    paged_num_blocks: int = 0
    paged_block_tokens: int = 0
    # Mixture-of-Experts MLP (models/moe.py); 0 = dense MLP.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    router_top_k: int = 1
    # Quantized training matmuls (ops/quant.py): see CausalSelfAttention.
    matmul_precision: str = "f32"
    # Pallas fused residual-add + LayerNorm (ops/fused_norm.py): ln_1/ln_2
    # run in one VMEM pass each, ln_2 absorbing the attention residual
    # add. Param tree identical to the unfused path (FusedLayerNorm).
    fused_norm: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        attention_mask: jax.Array | None = None,
        deterministic: bool = True,
        positions: jax.Array | None = None,
        block_tables: jax.Array | None = None,
    ) -> jax.Array:
        # Residual tag consumed by the "offload" activation tier's
        # checkpoint policy; identity under every other policy.
        x = tag_block_input(x)
        ln_kw = dict(
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
        )
        if self.fused_norm:
            h = FusedLayerNorm(
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                interpret=self.pallas_interpret,
                name="ln_1",
            )(x)
        else:
            h = nn.LayerNorm(name="ln_1", **ln_kw)(x)
        attn_out = CausalSelfAttention(
            d_model=self.d_model,
            n_heads=self.n_heads,
            n_layers=self.n_layers,
            dropout=self.dropout,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            attention=self.attention,
            decode=self.decode,
            cache_len=self.cache_len,
            n_kv_heads=self.n_kv_heads,
            assume_packed=self.assume_packed,
            sliding_window=self.sliding_window,
            ring_slack=self.ring_slack,
            kv_cache_dtype=self.kv_cache_dtype,
            paged=self.paged,
            paged_num_blocks=self.paged_num_blocks,
            paged_block_tokens=self.paged_block_tokens,
            matmul_precision=self.matmul_precision,
            name="attn",
        )(
            h,
            attention_mask,
            deterministic=deterministic,
            positions=positions,
            block_tables=block_tables,
        )

        if self.fused_norm:
            # One kernel: x = x + attn_out; h = LN(x). The sum is both the
            # residual stream and the norm input, so it is read/written once.
            h, x = FusedLayerNorm(
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                interpret=self.pallas_interpret,
                name="ln_2",
            )(attn_out, residual=x)
        else:
            x = x + attn_out
            h = nn.LayerNorm(name="ln_2", **ln_kw)(x)
        if self.n_experts > 0:
            from .moe import MoEMLP

            h = MoEMLP(
                d_model=self.d_model,
                d_ff=self.d_ff,
                n_experts=self.n_experts,
                n_layers=self.n_layers,
                capacity_factor=self.capacity_factor,
                aux_loss_weight=self.moe_aux_weight,
                router_top_k=self.router_top_k,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                matmul_precision=self.matmul_precision,
                name="moe_mlp",
            )(h)
        else:
            from ..ops.quant import quant_dot_general

            quant_dg = quant_dot_general(self.matmul_precision)
            h = nn.Dense(
                self.d_ff,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "mlp")),
                bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("mlp",)),
                dot_general=quant_dg,
                name="mlp_fc",
            )(h)
            h = nn.with_logical_constraint(h, ("batch", "length", "act_mlp"))
            h = nn.gelu(h, approximate=False)
            h = nn.Dense(
                self.d_model,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_scaled_init(self.n_layers), ("mlp", "embed")),
                bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
                dot_general=quant_dg,
                name="mlp_proj",
            )(h)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        x = x + h
        return nn.with_logical_constraint(x, ("batch", "length", "act_embed"))


class GPT(nn.Module):
    """Decoder-only GPT language model."""

    vocab_size: int
    block_size: int
    d_model: int
    n_layers: int
    n_heads: int
    d_ff: int
    dropout: float
    tie_embeddings: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    # Rematerialization policy when remat=True (model.extra.remat_policy):
    # "nothing" (default — save no intermediates, recompute the whole
    # block) trades the most FLOPs for HBM; "dots" saves matmul outputs
    # and recomputes only the cheap elementwise ops — less recompute on
    # the MXU for a modest memory cost, often the better MFU point.
    remat_policy: str = "nothing"
    # Per-layer activation tiers (model.extra.activation_tiers), one of
    # none|selective|full|offload per block — parsed/validated by the
    # adapter (config/activation_tiers.py) and already backend-resolved
    # (offload -> full where pinned_host is missing). When set it
    # replaces the global remat/remat_policy pair above, which stays for
    # direct module users and the dots_no_batch policy.
    activation_tiers: tuple[str, ...] | None = None
    attention: str = "dense"
    decode: bool = False  # KV-cache generation mode (see for_decoding())
    decode_cache_len: int = 0  # KV-cache capacity; 0 = block_size
    # Mixture-of-Experts (models/moe.py); 0 = dense MLPs in every block.
    n_experts: int = 0
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    router_top_k: int = 1
    # Loss implementation hint consumed by GPTAdapter.compute_loss_components:
    # "dense" materializes logits; "chunked_ce" streams the CE over vocab
    # chunks of ce_chunk (ops/chunked_ce.py) — the forward then returns
    # hidden states via return_hidden and never builds [B,T,V];
    # "fused_ce" computes the loss in a Pallas kernel (ops/fused_ce.py)
    # tiled (fused_ce_block_t x fused_ce_block_v) so no logits tile ever
    # reaches HBM.
    loss_impl: str = "dense"
    ce_chunk: int = 8192
    fused_ce_block_t: int = 256
    fused_ce_block_v: int = 512
    # Pallas fused residual-add + LayerNorm in every block
    # (ops/fused_norm.py); cleared on decode clones — the kernels are
    # trained-shape tuned and decode runs T=1 slices.
    fused_norm: bool = False
    # Force interpret-mode Pallas kernels (fused_ce / fused_norm) on any
    # backend — CPU parity tests and the bench matrix run the real kernel
    # logic under emulation (model.extra.pallas_interpret).
    pallas_interpret: bool = False
    # PaLM z-loss coefficient: adds z_loss * log(Z)^2 per token to the LM
    # objective (both loss paths). 0 = off (reference behavior).
    z_loss: float = 0.0
    # Grouped-query attention: K/V heads (0 = n_heads/MHA, 1 = MQA). The
    # decode cache shrinks by n_heads/n_kv_heads (see CausalSelfAttention).
    n_kv_heads: int = 0
    # Data is guaranteed packed (all-ones masks): skip the in-attention
    # mask on the flash path (model.extra.assume_packed).
    assume_packed: bool = False
    # Sliding-window attention (model.extra.sliding_window): each query
    # attends its trailing W keys — O(T·W) attention compute on the flash
    # path. 0 = full causal.
    sliding_window: int = 0
    # Extra rolling-cache slots for speculative decode rollback safety
    # (see CausalSelfAttention.ring_slack); set via for_decoding().
    ring_slack: int = 0
    # Decode-cache storage dtype (model.extra.kv_cache_dtype): "int8"
    # halves KV-cache HBM vs bf16 (see CausalSelfAttention).
    kv_cache_dtype: str = "model"
    # Paged block-pool decode cache for continuous-batching serving
    # (see CausalSelfAttention.paged); set via for_paged_decoding().
    paged: bool = False
    paged_num_blocks: int = 0
    paged_block_tokens: int = 0
    # Quantized training matmuls (model.extra.matmul_precision, ops/quant.py):
    # "int8" quantizes projection weights per-channel with straight-through
    # gradients; "int8_act" also fake-quantizes activations; "fp8" runs
    # float8_e4m3fn matmuls where the backend supports them (the adapter
    # capability-resolves fp8 -> f32 with a warning otherwise). Embeddings
    # and the lm_head stay in the compute dtype — they are the
    # quality-sensitive ends of the stack and a rounding error of the
    # matmul byte budget. Param tree and checkpoints are unchanged.
    matmul_precision: str = "f32"

    def for_paged_decoding(
        self, *, num_blocks: int, block_tokens: int
    ) -> "GPT":
        """Clone configured for paged-KV continuous-batching decode.

        The cache becomes a pool of ``num_blocks`` blocks of
        ``block_tokens`` positions each, shared by every in-flight
        sequence; callers pass per-row ``positions`` and ``block_tables``
        to ``apply`` (serving/engine.py owns the jitted step). Same
        parameter structure as training (params transfer 1:1). Physical
        block 0 is the null block padded table entries point at, so the
        pool must hold at least 2 blocks.
        """
        if num_blocks < 2:
            raise ValueError(f"num_blocks must be >= 2 (got {num_blocks})")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1 (got {block_tokens})")
        # (No rope check: GPT has no rope field — rotary embeddings live
        # on CausalSelfAttention for the llama-family modules, and the
        # paged path rotates by per-row positions; Llama.for_paged_decoding
        # is the llama-family twin of this entrypoint.)
        if self.sliding_window:
            raise ValueError(
                "paged decode does not support sliding_window models yet; "
                "use for_decoding() (rolling-ring cache)"
            )
        if self.kv_cache_dtype != "model":
            raise ValueError(
                "paged decode does not support kv_cache_dtype="
                f"{self.kv_cache_dtype!r} yet; use for_decoding()"
            )
        return self.clone(
            decode=True,
            paged=True,
            remat=False,
            activation_tiers=None,
            fused_norm=False,
            paged_num_blocks=num_blocks,
            paged_block_tokens=block_tokens,
        )

    def for_decoding(
        self, cache_len: int | None = None, *, ring_slack: int = 0
    ) -> "GPT":
        """Clone configured for cached autoregressive decoding.

        Same parameter structure (params transfer 1:1); remat is dropped —
        it trades FLOPs for training memory and would re-run cache writes.
        ``cache_len`` sizes the per-layer KV cache to the actual output
        length (capped at ``block_size``) so short generations don't pay
        O(block_size) HBM and attention per step. ``ring_slack`` widens a
        windowed model's rolling cache for speculative-rollback safety
        (speculative.py passes gamma+1).
        """
        if cache_len is None:
            cache_len = self.block_size
        return self.clone(
            decode=True,
            remat=False,
            activation_tiers=None,
            fused_norm=False,
            decode_cache_len=min(cache_len, self.block_size),
            ring_slack=ring_slack,
        )

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        *,
        deterministic: bool = True,
        return_hidden: bool = False,
        positions: jax.Array | None = None,
        block_tables: jax.Array | None = None,
    ) -> jax.Array:
        _, seqlen = input_ids.shape
        if seqlen > self.block_size:
            raise ValueError(
                f"Input sequence length {seqlen} exceeds block size {self.block_size}."
            )

        token_embedding = nn.Embed(
            self.vocab_size,
            self.d_model,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            embedding_init=nn.with_logical_partitioning(_EMBED_INIT, ("vocab", "embed")),
            name="token_embedding",
        )
        position_embedding = nn.Embed(
            self.block_size,
            self.d_model,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            embedding_init=nn.with_logical_partitioning(_EMBED_INIT, ("position", "embed")),
            name="position_embedding",
        )

        if self.decode and self.paged:
            # Per-ROW absolute positions from the caller: rows at different
            # depths share one program (continuous batching). No cursor
            # variable — the scheduler owns each sequence's position.
            if positions is None:
                raise ValueError(
                    "paged decode requires the `positions` (B,) argument"
                )
            pos_ids = positions[:, None] + jnp.arange(seqlen)[None, :]
        elif self.decode:
            # Positions continue from the cache cursor across apply() calls.
            position_index = self.variable(
                "cache", "position_index", lambda: jnp.zeros((), jnp.int32)
            )
            pos_ids = (position_index.value + jnp.arange(seqlen))[None, :]
            position_index.value = position_index.value + seqlen
        else:
            pos_ids = jnp.arange(seqlen)[None, :]
        x = token_embedding(input_ids) + position_embedding(pos_ids)
        x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        x = nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        if self.activation_tiers is not None:
            if len(self.activation_tiers) != self.n_layers:
                raise ValueError(
                    f"activation_tiers has {len(self.activation_tiers)} "
                    f"entries for a {self.n_layers}-layer model"
                )
            tier_classes = tier_block_classes(
                TransformerBlock, self.activation_tiers
            )
            layer_classes = [tier_classes[t] for t in self.activation_tiers]
        else:
            block_cls = TransformerBlock
            if self.remat:
                if self.remat_policy not in REMAT_POLICIES:
                    # Direct module users; the adapter validates at config time.
                    raise ValueError(
                        f"remat_policy {self.remat_policy!r} unknown; expected "
                        f"one of {sorted(REMAT_POLICIES)}"
                    )
                # argnums include the module at 0; 3 = `deterministic`, a
                # trace-time bool that must stay static through the remat boundary.
                # policy=None is nn.remat's own default (save nothing).
                block_cls = nn.remat(
                    TransformerBlock,
                    static_argnums=(3,),
                    policy=REMAT_POLICIES[self.remat_policy],
                )
            layer_classes = [block_cls] * self.n_layers

        paged = self.decode and self.paged
        for layer in range(self.n_layers):
            block = layer_classes[layer](
                d_model=self.d_model,
                n_heads=self.n_heads,
                d_ff=self.d_ff,
                n_layers=self.n_layers,
                dropout=self.dropout,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                attention=self.attention,
                decode=self.decode,
                cache_len=(self.decode_cache_len or self.block_size) if self.decode else 0,
                n_kv_heads=self.n_kv_heads,
                assume_packed=self.assume_packed,
                sliding_window=self.sliding_window,
                ring_slack=self.ring_slack if self.decode else 0,
                kv_cache_dtype=self.kv_cache_dtype,
                paged=paged,
                paged_num_blocks=self.paged_num_blocks if paged else 0,
                paged_block_tokens=self.paged_block_tokens if paged else 0,
                n_experts=self.n_experts,
                capacity_factor=self.capacity_factor,
                moe_aux_weight=self.moe_aux_weight,
                router_top_k=self.router_top_k,
                matmul_precision=self.matmul_precision,
                fused_norm=self.fused_norm,
                pallas_interpret=self.pallas_interpret,
                name=f"block_{layer}",
            )
            if paged:
                # kwargs only on the paged path: the remat wrapper's
                # positional static_argnums contract stays untouched.
                x = block(
                    x,
                    attention_mask,
                    deterministic,
                    positions=positions,
                    block_tables=block_tables,
                )
            else:
                x = block(x, attention_mask, deterministic)

        x = nn.LayerNorm(
            name="ln_f",
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            scale_init=nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            bias_init=nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
        )(x)

        if return_hidden:
            # Chunked-CE path (ops/chunked_ce.py): the loss contracts the
            # hidden states against the vocab matrix itself; skipping the
            # lm_head here is what keeps [B,T,V] out of HBM. NOTE: an
            # untied model must still initialize lm_head params, so init
            # runs with return_hidden=False (adapter handles this).
            return nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        if self.tie_embeddings:
            logits = token_embedding.attend(x)
        else:
            logits = nn.Dense(
                self.vocab_size,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(_DENSE_INIT, ("embed", "vocab")),
                name="lm_head",
            )(x)
        return nn.with_logical_constraint(logits, ("batch", "length", "act_vocab"))


@register_model("gpt")
class GPTAdapter(ModelAdapter):
    """Model adapter for the decoder-only GPT implementation."""

    known_extra_keys = frozenset(
        {"tokenizer", "loss_impl", "ce_chunk", "z_loss", "n_kv_heads",
         "assume_packed", "remat_policy", "sliding_window",
         "kv_cache_dtype", "matmul_precision", "ce_auto_vocab",
         "activation_tiers", "fused_ce_block_t", "fused_ce_block_v",
         "fused_norm", "pallas_interpret"}
    )

    def build_model(self, cfg: RunConfig) -> nn.Module:
        vocab_size = cfg.model.vocab_size
        if vocab_size is None:
            tokenizer = self.build_tokenizer(cfg)
            tokenizer_vocab_size = getattr(tokenizer, "n_vocab", None)
            if not isinstance(tokenizer_vocab_size, int) or tokenizer_vocab_size <= 0:
                raise ValueError("GPT tokenizer must expose a positive integer n_vocab.")
            vocab_size = tokenizer_vocab_size
        ce_auto_vocab = self._positive_extra(cfg, "ce_auto_vocab", 32768)
        # Selection authority lives in ops/fused_ce.py (shared with the
        # autotune planner): explicit knob wins (unknown raises, fused_ce
        # without Pallas degrades to chunked_ce with a one-time warning);
        # unset auto-selects a streamed CE at vocab >= ce_auto_vocab —
        # the [B,T,V] logits tensor is the top memory-bound op in the
        # 50k-vocab roofline table (docs/perf.md).
        from ..ops.fused_ce import resolve_loss_impl
        from ..ops.fused_norm import resolve_fused_norm

        pallas_interpret = bool(cfg.model.extra.get("pallas_interpret", False))
        loss_impl = resolve_loss_impl(
            cfg.model.extra.get("loss_impl"),
            vocab_size=vocab_size,
            ce_auto_vocab=ce_auto_vocab,
            interpret=pallas_interpret,
        )
        fused_norm = resolve_fused_norm(
            bool(cfg.model.extra.get("fused_norm", False)),
            interpret=pallas_interpret,
        )
        ce_chunk = self._positive_extra(cfg, "ce_chunk", 8192)
        fused_ce_block_t = self._positive_extra(cfg, "fused_ce_block_t", 256)
        fused_ce_block_v = self._positive_extra(cfg, "fused_ce_block_v", 512)
        z_loss = float(cfg.model.extra.get("z_loss", 0.0))
        if z_loss < 0.0:
            raise ValueError(f"model.extra.z_loss must be >= 0, got {z_loss}")
        n_kv_heads = int(cfg.model.extra.get("n_kv_heads", 0))
        if n_kv_heads < 0:
            raise ValueError(f"model.extra.n_kv_heads must be >= 0, got {n_kv_heads}")
        if n_kv_heads and cfg.model.n_heads % n_kv_heads != 0:
            raise ValueError(
                f"model.n_heads ({cfg.model.n_heads}) must be divisible by "
                f"model.extra.n_kv_heads ({n_kv_heads})"
            )
        remat_policy = str(cfg.model.extra.get("remat_policy", "nothing"))
        if remat_policy not in REMAT_POLICIES:
            # Validated here (not only at trace under remat=True) so a
            # typo'd policy fails at config time even when remat is off.
            raise ValueError(
                f"model.extra.remat_policy {remat_policy!r} unknown; "
                f"expected one of {sorted(REMAT_POLICIES)}"
            )
        activation_tiers = resolve_config_activation_tiers(cfg)
        if cfg.model.attention in ("flash", "ring", "ulysses") and cfg.model.dropout > 0.0:
            raise ValueError(
                f"attention={cfg.model.attention!r} does not support "
                "attention-probability dropout; set model.dropout to 0.0 or "
                "use attention='dense'"
            )
        kv_cache_dtype = str(cfg.model.extra.get("kv_cache_dtype", "model"))
        if kv_cache_dtype not in ("model", "int8"):
            raise ValueError(
                f"model.extra.kv_cache_dtype {kv_cache_dtype!r} unknown; "
                "expected 'model' or 'int8'"
            )
        sliding_window = int(cfg.model.extra.get("sliding_window", 0))
        if sliding_window < 0:
            raise ValueError(
                f"model.extra.sliding_window must be >= 0, got {sliding_window}"
            )
        if sliding_window and cfg.model.attention in ("ring", "ulysses"):
            raise ValueError(
                "model.extra.sliding_window is not supported with "
                f"attention={cfg.model.attention!r}; use 'flash' or 'dense'"
            )
        # Validated like loss_impl (unknown raises at config time) then
        # capability-resolved: fp8 on a backend without float8 matmuls
        # degrades to f32 with a one-time warning (ops/quant.py).
        from ..ops.quant import resolve_matmul_precision

        matmul_precision = resolve_matmul_precision(
            str(cfg.model.extra.get("matmul_precision", "f32"))
        )
        return GPT(
            vocab_size=vocab_size,
            block_size=cfg.model.block_size,
            d_model=cfg.model.d_model,
            n_layers=cfg.model.n_layers,
            n_heads=cfg.model.n_heads,
            d_ff=cfg.model.d_ff,
            dropout=cfg.model.dropout,
            tie_embeddings=cfg.model.tie_embeddings,
            dtype=jnp.dtype(cfg.model.dtype),
            param_dtype=jnp.dtype(cfg.model.param_dtype),
            remat=cfg.model.remat,
            attention=cfg.model.attention,
            loss_impl=loss_impl,
            ce_chunk=ce_chunk,
            fused_ce_block_t=fused_ce_block_t,
            fused_ce_block_v=fused_ce_block_v,
            fused_norm=fused_norm,
            pallas_interpret=pallas_interpret,
            z_loss=z_loss,
            n_kv_heads=n_kv_heads,
            assume_packed=bool(cfg.model.extra.get("assume_packed", False)),
            remat_policy=remat_policy,
            activation_tiers=activation_tiers,
            sliding_window=sliding_window,
            kv_cache_dtype=kv_cache_dtype,
            matmul_precision=matmul_precision,
        )

    def build_tokenizer(self, cfg: RunConfig) -> Any | None:
        """tiktoken gpt2 by default (reference models/gpt.py:210-212);
        ``model.extra.tokenizer: "byte"`` selects the offline byte-level
        tokenizer (no network egress at startup)."""
        from ..data.tokenizers import build_tokenizer

        return build_tokenizer(cfg.model.extra.get("tokenizer", "gpt2"))

    def validate_mesh(self, cfg: RunConfig, mesh: Any) -> None:
        """Mesh-dependent checks the Trainer runs before compiling.

        GQA's narrow K/V heads carry the same ``heads`` logical axis as
        queries, so they must divide over the ``tensor`` mesh axis or
        pjit fails with an opaque sharding error.
        """
        n_kv_heads = int(cfg.model.extra.get("n_kv_heads", 0))
        tp = int(mesh.shape.get("tensor", 1))
        if n_kv_heads and tp > 1 and n_kv_heads % tp != 0:
            raise ValueError(
                f"model.extra.n_kv_heads ({n_kv_heads}) must be divisible "
                f"by the mesh tensor axis ({tp}) — K/V heads shard over "
                "tensor parallelism like query heads do"
            )

    def compute_loss_components(
        self,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, jax.Array]:
        if getattr(model, "loss_impl", "dense") in ("chunked_ce", "fused_ce"):
            return self._chunked_loss_components(
                model, params, batch, rngs=rngs, deterministic=deterministic
            )
        return lm_loss_components(
            model, params, batch, rngs=rngs, deterministic=deterministic
        )

    @staticmethod
    def vocab_matrix(model: nn.Module, params: Params) -> jax.Array:
        """The (V, d) output-projection matrix, for losses that contract
        hidden states against it directly (ops/chunked_ce.py)."""
        if model.tie_embeddings:
            w_vocab = params["token_embedding"]["embedding"]
        else:
            w_vocab = params["lm_head"]["kernel"]
        # Trainer-held params are boxed with partitioning metadata
        # (nn.with_logical_partitioning); model.apply unboxes internally but
        # direct access must do it explicitly. No-op on plain arrays.
        w_vocab = nn.meta.unbox(w_vocab)
        if not model.tie_embeddings:
            w_vocab = w_vocab.T  # (d, V) -> (V, d)
        return w_vocab

    @classmethod
    def chunked_components_from_hidden(
        cls,
        model: nn.Module,
        params: Params,
        hidden: jax.Array,
        labels: jax.Array,
        attention_mask: jax.Array | None,
    ) -> tuple[jax.Array, jax.Array]:
        """Streamed/fused-CE components from already-computed hidden
        states — the single wiring point for every adapter's
        hidden-contraction loss path (gpt_moe reuses it after its
        mutable-collection apply). Dispatches on ``model.loss_impl``:
        fused_ce runs the Pallas kernel (ops/fused_ce.py), everything
        else the lax.scan streamer (ops/chunked_ce.py)."""
        if getattr(model, "loss_impl", "dense") == "fused_ce":
            from ..ops.fused_ce import fused_ce_components

            return fused_ce_components(
                hidden,
                cls.vocab_matrix(model, params),
                labels,
                attention_mask,
                block_t=getattr(model, "fused_ce_block_t", 256),
                block_v=getattr(model, "fused_ce_block_v", 512),
                z_loss=getattr(model, "z_loss", 0.0),
                interpret=bool(getattr(model, "pallas_interpret", False)),
            )
        from ..ops.chunked_ce import chunked_ce_components

        return chunked_ce_components(
            hidden,
            cls.vocab_matrix(model, params),
            labels,
            attention_mask,
            chunk=model.ce_chunk,
            z_loss=getattr(model, "z_loss", 0.0),
        )

    @classmethod
    def _chunked_loss_components(
        cls,
        model: nn.Module,
        params: Params,
        batch: Batch,
        *,
        rngs: dict[str, jax.Array] | None,
        deterministic: bool,
    ) -> tuple[jax.Array, jax.Array]:
        """Same loss as the dense path, streamed over vocab chunks
        (ops/chunked_ce.py) so [B,T,V] never materializes."""
        from ..models.base import validate_lm_batch

        input_ids, labels, attention_mask = validate_lm_batch(batch)
        hidden = model.apply(
            {"params": params},
            input_ids,
            attention_mask=attention_mask,
            deterministic=deterministic,
            rngs=rngs,
            return_hidden=True,
        )
        return cls.chunked_components_from_hidden(
            model, params, hidden, labels, attention_mask
        )


__all__ = ["GPT", "TransformerBlock", "CausalSelfAttention", "GPTAdapter", "dense_attention"]
