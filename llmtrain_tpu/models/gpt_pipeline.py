"""Pipeline-parallel GPT: stacked-block params scheduled with GPipe.

New capability beyond the reference (no model parallelism of any kind
there — SURVEY §2.3/§2.4). Same architecture family as ``models/gpt.py``
(learned token+position embeddings, pre-norm blocks, GELU MLP, final LN,
tied lm_head — behavior spec reference models/gpt.py:99-165) but built for
stage execution: every block parameter carries a LEADING layer dim
(logical axis ``"layers"`` → mesh ``pipeline``), blocks are applied by a
``lax.scan`` over that dim, and under a mesh with ``pipeline > 1`` the
stack runs through ``parallel/pipeline.gpipe_apply`` — microbatches
rotating across stages over ICI.

Scope (validated loudly): causal sequences with padding masks applied
INSIDE attention (reference gpt.py:60-74 — each stage tick receives its
microbatch's mask slice from the executor; ``model.extra.assume_packed``
drops the operand), no dropout inside pipelined blocks, and ``pipeline``
composes with
``data`` AND ``tensor`` (Megatron column/row splits inside each stage:
qkv/fc shard their output heads/width, out/proj their input, with the two
row-parallel psums written explicitly in the stage — shard_map is manual).
``fsdp``/``sequence`` must be 1.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..config.schemas import RunConfig
from ..registry.models import register_model
from .base import ModelAdapter, Params, lm_loss_components
from .gpt import dense_attention

_INIT_STD = 0.02


def _layernorm(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    """f32-statistics layernorm over the trailing dim."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    # eps matches models/gpt.py's flax LayerNorm (1e-6, docs/parity.md) so
    # pipeline<->gpt parameter conversion (interop/pipeline_convert.py) is
    # numerically exact.
    y = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def make_block_apply(
    *, attention: str, dtype: Any, tp_axis: str | None = None, window: int = 0
):
    """Functional pre-norm transformer block over stacked params.

    ``p`` leaves are ONE layer's slice (no leading layer dim); ``h`` is
    (B, T, D). Mirrors TransformerBlock (models/gpt.py:245-308) without
    module machinery so it can run under shard_map/scan. Shapes are read
    from the params, so the same code runs full-width or on a tensor-
    parallel shard (H/tp heads, F/tp mlp width): with ``tp_axis`` set the
    block inserts the two Megatron row-parallel psums (after out-proj and
    after mlp-proj; biases added once, after the psum).
    """

    def block_apply(
        p: dict[str, jax.Array], h: jax.Array, key_mask: jax.Array | None = None
    ) -> jax.Array:
        hn = _layernorm(h, p["ln1_scale"], p["ln1_bias"])
        # Kernels are head-major so tensor parallelism can shard whole
        # heads; local H may be a tp-shard of the global count. The fused
        # qkv layout is MHA; GQA splits into q_kernel/kv_kernel with
        # narrow K/V (layouts match models/gpt.py's projections).
        if "qkv_kernel" in p:
            qkv = jnp.einsum(
                "btd,dkhe->btkhe", hn.astype(dtype), p["qkv_kernel"].astype(dtype)
            ) + p["qkv_bias"].astype(dtype)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (B, T, Hl, Dh)
        else:
            q = jnp.einsum(
                "btd,dhe->bthe", hn.astype(dtype), p["q_kernel"].astype(dtype)
            ) + p["q_bias"].astype(dtype)
            kv = jnp.einsum(
                "btd,dkhe->btkhe", hn.astype(dtype), p["kv_kernel"].astype(dtype)
            ) + p["kv_bias"].astype(dtype)
            k, v = kv[:, :, 0], kv[:, :, 1]  # (B, T, Hkv_l, Dh)
        if attention == "flash":
            from ..ops.flash_attention import flash_attention

            # Narrow GQA K/V consumed natively (Pallas index maps on TPU,
            # grouped einsums in the blockwise fallback).
            att = flash_attention(
                q, k, v, attention_mask=key_mask, causal=True, window=window
            )
        else:
            if k.shape[2] != q.shape[2]:
                reps = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, reps, axis=2)
                v = jnp.repeat(v, reps, axis=2)
            att = dense_attention(q, k, v, attention_mask=key_mask, window=window)
        proj = jnp.einsum(
            "bthe,hed->btd", att.astype(dtype), p["out_kernel"].astype(dtype)
        )
        if tp_axis is not None:
            proj = jax.lax.psum(proj, tp_axis)
        attn_out = proj + p["out_bias"].astype(dtype)
        if key_mask is not None:
            # Zero padded rows' attention contribution (reference
            # gpt.py:73-74, same boolean compare as models/gpt.py —
            # mask values may be segment ids).
            attn_out = attn_out * (key_mask != 0)[:, :, None].astype(attn_out.dtype)
        h = h + attn_out

        hn = _layernorm(h, p["ln2_scale"], p["ln2_bias"])
        m = hn.astype(dtype) @ p["fc_kernel"].astype(dtype) + p["fc_bias"].astype(dtype)
        m = nn.gelu(m, approximate=False)
        mlp = m @ p["proj_kernel"].astype(dtype)
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
        h = h + mlp + p["proj_bias"].astype(dtype)
        return h

    return block_apply


def make_stage_fn(
    *, attention: str, dtype: Any, tp_axis: str | None = None, window: int = 0
):
    """Stage program: scan ``block_apply`` over this stage's layer slice.
    ``key_mask`` is the microbatch's (B, T) padding mask (or None)."""
    block_apply = make_block_apply(
        attention=attention, dtype=dtype, tp_axis=tp_axis, window=window
    )

    def stage_fn(
        stage_params: dict[str, jax.Array],
        h: jax.Array,
        key_mask: jax.Array | None = None,
    ) -> jax.Array:
        def body(h, layer_params):
            return block_apply(layer_params, h, key_mask), None

        h, _ = jax.lax.scan(body, h, stage_params)
        return h

    return stage_fn


class PipelineGPT(nn.Module):
    """Decoder-only GPT with a stacked, pipeline-shardable block stack."""

    vocab_size: int
    block_size: int
    d_model: int
    n_layers: int
    n_heads: int
    d_ff: int
    tie_embeddings: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    attention: str = "dense"
    n_microbatches: int = 4
    remat: bool = True
    # >1 selects the interleaved (Megatron-style) schedule: each stage
    # holds this many non-contiguous layer chunks and microbatches make
    # that many passes around the stage ring — bubble (S-1)/(v*M+S-1).
    n_virtual_chunks: int = 1
    # "chunked_ce" streams the LM loss over vocab chunks (ops/chunked_ce.py).
    # Works here because the lm_head applies OUTSIDE the stage shard_map,
    # on the gathered final hidden states.
    loss_impl: str = "dense"
    ce_chunk: int = 8192
    # PaLM z-loss coefficient (see models/gpt.py); 0 = off.
    z_loss: float = 0.0
    # Data is guaranteed packed (all-ones masks): skip the in-attention
    # mask (model.extra.assume_packed, same knob as models/gpt.py).
    assume_packed: bool = False
    # Sliding-window attention (model.extra.sliding_window, Mistral
    # semantics — see models/gpt.py); 0 = full causal.
    sliding_window: int = 0
    # Decode-cache storage dtype: the pipeline model never decodes
    # itself, but carries the knob so the decode-time conversion to the
    # plain GPT tree (interop/pipeline_convert.py via cli.py
    # _prepare_decode_model) preserves it.
    kv_cache_dtype: str = "model"
    # Grouped-query attention: K/V heads (0 = n_heads/MHA, 1 = MQA), the
    # same semantics and param naming family as models/gpt.py — flash
    # consumes the narrow K/V natively, dense broadcasts.
    n_kv_heads: int = 0

    def _stacked(
        self, name: str, shape: tuple[int, ...], init, axes: tuple[str, ...]
    ) -> jax.Array:
        """A per-layer-stacked parameter: leading dim n_layers on logical
        axis "layers" (→ mesh ``pipeline``); ``axes`` names the per-layer
        dims with the same logical vocabulary as models/gpt.py (so heads/
        mlp dims shard over ``tensor`` in the train state)."""
        return self.param(
            name,
            nn.with_logical_partitioning(init, ("layers", *axes)),
            (self.n_layers, *shape),
            self.param_dtype,
        )

    @nn.compact
    def __call__(
        self,
        input_ids: jax.Array,
        attention_mask: jax.Array | None = None,
        *,
        deterministic: bool = True,
        return_hidden: bool = False,
    ) -> jax.Array:
        del deterministic  # no dropout inside pipelined blocks (v1)
        # Padding masks are applied inside attention here too (reference
        # gpt.py:60-74 semantics): the executor hands each stage tick its
        # microbatch's mask slice (parallel/pipeline.py). assume_packed
        # drops the operand like the gpt flash path.
        if self.assume_packed:
            attention_mask = None
        _, seqlen = input_ids.shape
        if seqlen > self.block_size:
            raise ValueError(
                f"Input sequence length {seqlen} exceeds block size {self.block_size}."
            )

        embed_init = nn.initializers.normal(stddev=_INIT_STD)
        dense_init = nn.initializers.normal(stddev=_INIT_STD)
        scaled_init = nn.initializers.normal(
            stddev=_INIT_STD / math.sqrt(2 * self.n_layers)
        )

        token_embedding = nn.Embed(
            self.vocab_size,
            self.d_model,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            embedding_init=nn.with_logical_partitioning(embed_init, ("vocab", "embed")),
            name="token_embedding",
        )
        position_embedding = nn.Embed(
            self.block_size,
            self.d_model,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            embedding_init=nn.with_logical_partitioning(embed_init, ("position", "embed")),
            name="position_embedding",
        )
        x = token_embedding(input_ids) + position_embedding(
            jnp.arange(seqlen)[None, :]
        )
        x = nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        d, f, nh = self.d_model, self.d_ff, self.n_heads
        hd = d // nh
        ones, zeros = nn.initializers.ones_init(), nn.initializers.zeros_init()
        kvh = self.n_kv_heads or nh
        if kvh == nh:
            # Head-major fused qkv so tensor parallelism shards whole heads.
            attn_params = {
                "qkv_kernel": self._stacked(
                    "qkv_kernel", (d, 3, nh, hd), dense_init,
                    ("embed", "qkv", "heads", "kv"),
                ),
                "qkv_bias": self._stacked(
                    "qkv_bias", (3, nh, hd), zeros, ("qkv", "heads", "kv")
                ),
            }
        else:
            if nh % kvh != 0:
                raise ValueError(
                    f"n_heads ({nh}) must be divisible by n_kv_heads ({kvh})"
                )
            # Split projections, same per-layer shapes as models/gpt.py's
            # q_proj/kv_proj (the conversion in interop/pipeline_convert.py
            # maps them 1:1).
            attn_params = {
                "q_kernel": self._stacked(
                    "q_kernel", (d, nh, hd), dense_init, ("embed", "heads", "kv")
                ),
                "q_bias": self._stacked("q_bias", (nh, hd), zeros, ("heads", "kv")),
                "kv_kernel": self._stacked(
                    "kv_kernel", (d, 2, kvh, hd), dense_init,
                    ("embed", "qkv", "heads", "kv"),
                ),
                "kv_bias": self._stacked(
                    "kv_bias", (2, kvh, hd), zeros, ("qkv", "heads", "kv")
                ),
            }
        blocks = {
            "ln1_scale": self._stacked("ln1_scale", (d,), ones, ("embed",)),
            "ln1_bias": self._stacked("ln1_bias", (d,), zeros, ("embed",)),
            **attn_params,
            "out_kernel": self._stacked(
                "out_kernel", (nh, hd, d), scaled_init, ("heads", "kv", "embed")
            ),
            "out_bias": self._stacked("out_bias", (d,), zeros, ("embed",)),
            "ln2_scale": self._stacked("ln2_scale", (d,), ones, ("embed",)),
            "ln2_bias": self._stacked("ln2_bias", (d,), zeros, ("embed",)),
            "fc_kernel": self._stacked("fc_kernel", (d, f), dense_init, ("embed", "mlp")),
            "fc_bias": self._stacked("fc_bias", (f,), zeros, ("mlp",)),
            "proj_kernel": self._stacked("proj_kernel", (f, d), scaled_init, ("mlp", "embed")),
            "proj_bias": self._stacked("proj_bias", (d,), zeros, ("embed",)),
        }

        from ..parallel.pipeline import pipeline_degree
        from ..parallel.sharding import ambient_mesh

        mesh = ambient_mesh()
        n_stages = pipeline_degree(mesh)
        tp = int(mesh.shape.get("tensor", 1)) if mesh is not None else 1
        if n_stages > 1:
            from ..parallel.pipeline import BATCH_AXES, gpipe_apply

            for banned in ("fsdp", "sequence"):
                if int(mesh.shape.get(banned, 1)) != 1:
                    raise ValueError(
                        f"gpt_pipeline composes pipeline with data and tensor "
                        f"parallelism; mesh axis {banned!r} must be 1, got "
                        f"{mesh.shape[banned]}"
                    )
            if nh % tp != 0 or f % tp != 0:
                raise ValueError(
                    f"tensor parallelism needs n_heads ({nh}) and d_ff ({f}) "
                    f"divisible by the tensor axis size ({tp})"
                )
            if self.n_layers % (n_stages * self.n_virtual_chunks) != 0:
                raise ValueError(
                    f"n_layers {self.n_layers} must divide evenly into "
                    f"{n_stages} pipeline stages x {self.n_virtual_chunks} "
                    "virtual chunks"
                )
            dp = math.prod(int(mesh.shape.get(a, 1)) for a in BATCH_AXES)
            needed = dp * self.n_microbatches
            if x.shape[0] % needed != 0:
                # Batch-1 traces (the param-init probe, models/base.py:52)
                # fall back silently by design. A REAL batch must not: on a
                # pipeline:S mesh "without pipeline parallelism" means every
                # device materializes all S stages' layers — an OOM at the
                # sizes pipeline parallelism exists for, reached via a
                # warning. The Trainer pads eval batches up to
                # adapter.batch_divisor(), so this is only reachable from
                # custom callers.
                if x.shape[0] > 1:
                    raise ValueError(
                        f"gpt_pipeline: batch {x.shape[0]} is not divisible "
                        f"by data shards x microbatches ({needed}) on a "
                        f"{n_stages}-stage pipeline mesh; pad the batch with "
                        "zero-masked rows (Trainer eval does this via "
                        "ModelAdapter.batch_divisor) or adjust "
                        "model.extra.pipeline_microbatches"
                    )
                n_stages = 1
        if n_stages > 1:
            from jax.sharding import PartitionSpec as P

            tp_axis = "tensor" if tp > 1 else None
            stage_fn = make_stage_fn(
                attention=self.attention, dtype=self.dtype, tp_axis=tp_axis,
                window=self.sliding_window,
            )

            def _pspec(*tail):
                return P("pipeline", *tail)

            # Mirrors the logical axes above with "tensor" where heads/mlp
            # shard — shard_map is manual, so the specs must say it again.
            # Only when tp > 1: a size-1 (or absent) tensor axis must not
            # appear, or params become tensor-varying with no psum to
            # cancel it and the layer-scan carry types mismatch.
            tens = "tensor" if tp > 1 else None
            if kvh == nh:
                attn_specs = {
                    "qkv_kernel": _pspec(None, None, tens, None),
                    "qkv_bias": _pspec(None, tens, None),
                }
            else:
                if tp > 1 and kvh % tp != 0:
                    raise ValueError(
                        f"n_kv_heads ({kvh}) must be divisible by the mesh "
                        f"tensor axis ({tp}) — K/V heads shard over tensor "
                        "parallelism like query heads do"
                    )
                attn_specs = {
                    "q_kernel": _pspec(None, tens, None),
                    "q_bias": _pspec(tens, None),
                    "kv_kernel": _pspec(None, None, tens, None),
                    "kv_bias": _pspec(None, tens, None),
                }
            param_specs = {
                "ln1_scale": _pspec(None),
                "ln1_bias": _pspec(None),
                **attn_specs,
                "out_kernel": _pspec(tens, None, None),
                "out_bias": _pspec(None),
                "ln2_scale": _pspec(None),
                "ln2_bias": _pspec(None),
                "fc_kernel": _pspec(None, tens),
                "fc_bias": _pspec(tens),
                "proj_kernel": _pspec(tens, None),
                "proj_bias": _pspec(None),
            }
            x = gpipe_apply(
                stage_fn,
                blocks,
                x,
                mesh,
                n_microbatches=self.n_microbatches,
                remat_stage=self.remat,
                virtual_chunks=self.n_virtual_chunks,
                param_specs=param_specs,
                mask=attention_mask,
            )
        else:
            stage_fn = make_stage_fn(
                attention=self.attention, dtype=self.dtype,
                window=self.sliding_window,
            )
            fn = jax.checkpoint(stage_fn) if self.remat else stage_fn
            x = fn(blocks, x) if attention_mask is None else fn(blocks, x, attention_mask)

        ln_f_scale = self.param(
            "ln_f_scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("embed",)),
            (d,),
            self.param_dtype,
        )
        ln_f_bias = self.param(
            "ln_f_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(), ("embed",)),
            (d,),
            self.param_dtype,
        )
        x = _layernorm(x, ln_f_scale, ln_f_bias)

        if return_hidden:
            # Chunked-CE path: the loss contracts these against the vocab
            # matrix itself (GPTAdapter.chunked_components_from_hidden);
            # skipping the lm_head keeps [B,T,V] out of HBM. Init must run
            # with return_hidden=False so an untied lm_head still exists.
            return nn.with_logical_constraint(x, ("batch", "length", "act_embed"))

        if self.tie_embeddings:
            logits = token_embedding.attend(x)
        else:
            logits = nn.Dense(
                self.vocab_size,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(dense_init, ("embed", "vocab")),
                name="lm_head",
            )(x)
        return nn.with_logical_constraint(logits, ("batch", "length", "act_vocab"))


@register_model("gpt_pipeline")
class PipelineGPTAdapter(ModelAdapter):
    """Adapter for the pipeline-parallel GPT.

    ``model.extra`` knobs: ``tokenizer`` ("gpt2"/"byte"/"bpe:<path>", as
    for gpt), ``pipeline_microbatches`` (default 4; per-data-shard batch
    must divide by it when pipeline > 1), ``pipeline_virtual_chunks``
    (interleaved schedule), and ``loss_impl``/``ce_chunk`` (chunked
    cross-entropy, as for gpt).
    """

    supports_pipeline = True
    known_extra_keys = frozenset(
        {
            "tokenizer",
            "loss_impl",
            "ce_chunk",
            "z_loss",
            "assume_packed",
            "n_kv_heads",
            "pipeline_microbatches",
            "pipeline_virtual_chunks",
            "sliding_window",
            "kv_cache_dtype",
        }
    )

    def build_model(self, cfg: RunConfig) -> nn.Module:
        vocab_size = cfg.model.vocab_size
        if vocab_size is None:
            tokenizer = self.build_tokenizer(cfg)
            vocab_size = getattr(tokenizer, "n_vocab", None)
            if not isinstance(vocab_size, int) or vocab_size <= 0:
                raise ValueError("tokenizer must expose a positive integer n_vocab")
        if cfg.model.dropout != 0.0:
            raise ValueError(
                "gpt_pipeline does not support dropout (v1); set model.dropout to 0.0"
            )
        if cfg.model.attention not in ("dense", "flash"):
            raise ValueError(
                f"gpt_pipeline supports attention 'dense' or 'flash', "
                f"got {cfg.model.attention!r}"
            )
        loss_impl = cfg.model.extra.get("loss_impl", "dense")
        if loss_impl == "fused_ce":
            # The Pallas kernel contracts hidden states held on the last
            # stage only; the pipeline loss runs inside the per-microbatch
            # scan where the kernel's custom_vjp is not wired. Fail loudly
            # rather than silently training something else.
            raise ValueError(
                "model.extra.loss_impl 'fused_ce' is not supported with "
                "pipeline parallelism; use 'chunked_ce'"
            )
        if loss_impl not in ("dense", "chunked_ce"):
            raise ValueError(
                f"model.extra.loss_impl {loss_impl!r} unknown; "
                "expected 'dense' or 'chunked_ce'"
            )
        z_loss = float(cfg.model.extra.get("z_loss", 0.0))
        if z_loss < 0.0:
            raise ValueError(f"model.extra.z_loss must be >= 0, got {z_loss}")
        n_kv_heads = int(cfg.model.extra.get("n_kv_heads", 0))
        if n_kv_heads < 0:
            raise ValueError(
                f"model.extra.n_kv_heads must be >= 0, got {n_kv_heads}"
            )
        if n_kv_heads and cfg.model.n_heads % n_kv_heads != 0:
            raise ValueError(
                f"model.n_heads ({cfg.model.n_heads}) must be divisible by "
                f"model.extra.n_kv_heads ({n_kv_heads})"
            )
        sliding_window = int(cfg.model.extra.get("sliding_window", 0))
        if sliding_window < 0:
            raise ValueError(
                f"model.extra.sliding_window must be >= 0, got {sliding_window}"
            )
        kv_cache_dtype = str(cfg.model.extra.get("kv_cache_dtype", "model"))
        if kv_cache_dtype not in ("model", "int8"):
            # Same config-time check as GPTAdapter: the pipeline model
            # never decodes, so a typo would otherwise surface only at
            # serve/generate conversion time, after the training run.
            raise ValueError(
                f"model.extra.kv_cache_dtype {kv_cache_dtype!r} unknown; "
                "expected 'model' or 'int8'"
            )
        return PipelineGPT(
            vocab_size=vocab_size,
            block_size=cfg.model.block_size,
            d_model=cfg.model.d_model,
            n_layers=cfg.model.n_layers,
            n_heads=cfg.model.n_heads,
            d_ff=cfg.model.d_ff,
            tie_embeddings=cfg.model.tie_embeddings,
            dtype=jnp.dtype(cfg.model.dtype),
            param_dtype=jnp.dtype(cfg.model.param_dtype),
            attention=cfg.model.attention,
            n_microbatches=self._positive_extra(cfg, "pipeline_microbatches", 4),
            remat=cfg.model.remat,
            n_virtual_chunks=self._positive_extra(cfg, "pipeline_virtual_chunks", 1),
            loss_impl=loss_impl,
            ce_chunk=self._positive_extra(cfg, "ce_chunk", 8192),
            z_loss=z_loss,
            assume_packed=bool(cfg.model.extra.get("assume_packed", False)),
            n_kv_heads=n_kv_heads,
            sliding_window=sliding_window,
            kv_cache_dtype=kv_cache_dtype,
        )

    def build_tokenizer(self, cfg: RunConfig) -> Any | None:
        from ..data.tokenizers import build_tokenizer

        return build_tokenizer(cfg.model.extra.get("tokenizer", "gpt2"))

    def batch_divisor(self, cfg: RunConfig, mesh: Any) -> int:
        """data_shards × microbatches on pipeline meshes: the row count
        every applied batch must divide by for gpipe_apply to engage."""
        from ..parallel.pipeline import BATCH_AXES, pipeline_degree

        if pipeline_degree(mesh) <= 1:
            return 1
        dp = math.prod(int(mesh.shape.get(a, 1)) for a in BATCH_AXES)
        return dp * self._positive_extra(cfg, "pipeline_microbatches", 4)

    def validate_mesh(self, cfg: RunConfig, mesh: Any) -> None:
        """Fail at startup (not at trace) when the training batch cannot
        engage the pipeline: global rows (micro_batch_size × data shards)
        divide by data_shards × microbatches iff microbatches divides
        micro_batch_size."""
        from ..parallel.pipeline import pipeline_degree

        m = self._positive_extra(cfg, "pipeline_microbatches", 4)
        if pipeline_degree(mesh) > 1 and cfg.trainer.micro_batch_size % m != 0:
            raise ValueError(
                f"trainer.micro_batch_size ({cfg.trainer.micro_batch_size}) "
                f"must be divisible by model.extra.pipeline_microbatches "
                f"({m}) on a pipeline mesh"
            )
        n_kv_heads = int(cfg.model.extra.get("n_kv_heads", 0))
        tp = int(mesh.shape.get("tensor", 1)) if mesh is not None else 1
        if n_kv_heads and tp > 1 and n_kv_heads % tp != 0:
            raise ValueError(
                f"model.extra.n_kv_heads ({n_kv_heads}) must be divisible "
                f"by the mesh tensor axis ({tp}) — K/V heads shard over "
                "tensor parallelism like query heads do"
            )

    def compute_loss_components(
        self,
        model: nn.Module,
        params: Params,
        batch: dict,
        *,
        rngs: dict[str, jax.Array] | None = None,
        deterministic: bool = True,
    ) -> tuple[jax.Array, jax.Array]:
        if getattr(model, "loss_impl", "dense") == "chunked_ce":
            from .gpt import GPTAdapter

            # Shared wiring point: nothing in the chunked path is
            # GPT-module-specific (apply(return_hidden=True) + contract
            # against the vocab matrix).
            return GPTAdapter._chunked_loss_components(
                model, params, batch, rngs=rngs, deterministic=deterministic
            )
        return lm_loss_components(
            model, params, batch, rngs=rngs, deterministic=deterministic
        )


__all__ = ["PipelineGPT", "PipelineGPTAdapter"]
