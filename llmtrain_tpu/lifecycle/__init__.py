"""Train→canary→promote lifecycle (``llmtrain promote``).

Closes the loop the serving tier left open: training commits checkpoints
(atomic manifests), serving hot-swaps them (rolling reloads), but a
human still glued the two — and nothing protected live traffic from a
regressed checkpoint. This package is the supervisor in between:

* :mod:`~.watch` — polls a training run's manifest stream for new
  committed checkpoints (durable artifacts only, the goodput stance).
* :mod:`~.controller` — canaries each commit on one replica, scores it
  over a soak window (held-out eval loss + TTFT/per-token percentiles,
  optional A/B traffic split), then promotes fleet-wide or auto-rolls
  back — including rolling back a partially applied fleet swap.
* :mod:`~.ledger` — every decision is a durable ``promotions.jsonl``
  line, so a SIGKILLed promote resumes without double-promoting and the
  goodput ledger can attribute the run's promotion history.
"""

from .controller import PromotionController, RouterFleet
from .ledger import DECISIONS, TERMINAL_DECISIONS, PromotionLedger
from .watch import CheckpointWatcher

__all__ = [
    "CheckpointWatcher",
    "DECISIONS",
    "PromotionController",
    "PromotionLedger",
    "RouterFleet",
    "TERMINAL_DECISIONS",
]
