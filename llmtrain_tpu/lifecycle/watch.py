"""Checkpoint-stream watcher: durable-artifact polling, no IPC.

The promotion controller learns about new checkpoints the same way the
goodput ledger learns about everything — from durable files, never from
a live channel to the trainer. ``poll()`` wraps
:meth:`~..training.checkpoint.CheckpointManager.latest_valid_checkpoint`,
which is manifest-driven: a checkpoint exists the instant its
``step_N.manifest.json`` rename lands (atomic — a manifest published
mid-poll is either fully visible or not at all, never torn), and a run
dir holding only pre-manifest checkpoints is adopted by its first scan.

Training-liveness comes from the watchdog heartbeat file's mtime (the
same signal the k8s probes stat) plus ``report.json`` as the "run
finished cleanly" marker — so the watcher can tell "training is done"
from "training died mid-stream" without ever talking to the process.
"""

from __future__ import annotations

import re
import time
from pathlib import Path

from ..training.checkpoint import CheckpointManager

_STEP_RE = re.compile(r"step_(\d+)\.ckpt$")


class CheckpointWatcher:
    """Polls one training run's checkpoint dir for new committed steps."""

    def __init__(
        self,
        ckpt_dir: str | Path,
        *,
        run_dir: str | Path | None = None,
        manager: CheckpointManager | None = None,
    ) -> None:
        self.ckpt_dir = Path(ckpt_dir)
        # Heartbeat + report.json live in the run dir; by convention the
        # checkpoint dir is {run_dir}/checkpoints.
        self.run_dir = Path(run_dir) if run_dir is not None else self.ckpt_dir.parent
        self._manager = manager or CheckpointManager(self.ckpt_dir)

    @property
    def manager(self) -> CheckpointManager:
        return self._manager

    # -------------------------------------------------------------- stream

    def poll(self, *, after_step: int = -1) -> tuple[Path, int] | None:
        """Newest committed-and-verified checkpoint with step >
        ``after_step``, or None. Intermediate commits that landed while
        a previous candidate soaked are intentionally skipped — the
        stream's head is always the best candidate."""
        ckpt = self._manager.latest_valid_checkpoint()
        if ckpt is None:
            return None
        m = _STEP_RE.search(ckpt.name)
        if m is None:
            return None
        step = int(m.group(1))
        if step <= after_step:
            return None
        return ckpt, step

    # ------------------------------------------------------------ liveness

    def training_finished(self) -> bool:
        """The trainer wrote its end-of-run report — the stream is over."""
        return (self.run_dir / "report.json").is_file()

    def heartbeat_age_sec(self) -> float | None:
        """Age of the freshest watchdog heartbeat file (``heartbeat`` or
        per-rank ``heartbeat.rN``), None when the run never wrote one."""
        newest: float | None = None
        try:
            for path in self.run_dir.iterdir():
                if path.name == "heartbeat" or path.name.startswith("heartbeat."):
                    try:
                        mtime = path.stat().st_mtime
                    except OSError:
                        continue
                    if newest is None or mtime > newest:
                        newest = mtime
        except OSError:
            return None
        if newest is None:
            return None
        return max(0.0, time.time() - newest)

    def training_alive(self, *, stale_sec: float) -> bool:
        """True while the trainer's heartbeat is fresher than
        ``stale_sec``. No heartbeat at all counts dead — a static dir
        (adopted snapshot) drains its head commit and then promote
        exits, it does not wait forever."""
        age = self.heartbeat_age_sec()
        return age is not None and age <= stale_sec


__all__ = ["CheckpointWatcher"]
