"""Promotion controller: canary → score → promote/rollback, supervised.

The control loop ``llmtrain promote`` runs:

1. **Watch** — poll the training run's manifest stream for a commit
   newer than everything already decided (:mod:`~.watch`).
2. **Canary** — hot-swap the candidate into ONE designated replica via
   the router's single-replica reload; the placement layer excludes the
   canary from live traffic (or steers it a seeded A/B fraction,
   ``promote.traffic_split``).
3. **Score** — a soak window: seeded synthetic probes against the
   canary measure TTFT/per-token percentiles; the same probes against a
   reference replica give the baseline side of the A/B; held-out eval
   loss comes from the existing eval path (``Trainer.evaluate``).
   Gates are regression DELTAS: failed requests, eval-loss delta,
   SLO-percentile slowdown factors.
4. **Decide** — promote fleet-wide (``rolling_reload``) or roll the
   canary back to the promoted baseline. A PARTIALLY applied fleet swap
   (a replica failing its reload mid-roll) triggers a fleet-wide
   rollback so the fleet never settles mixed-epoch (the router's
   ``epoch_divergence`` gauge is the observable for this state).

Every decision is a durable :class:`~.ledger.PromotionLedger` line plus
a telemetry instant plus ``promote/*`` gauges (``llmtrain_promote_*``
in Prometheus). The controller owns NO threads and does no I/O beyond
its collaborators — watcher, fleet, evaluator, params loader and clock
are all injected, so the whole decision surface unit-tests with fakes.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from ..config.schemas import PromoteConfig
from ..serving.loadgen import build_requests, percentiles
from ..telemetry.tracing import new_trace_id
from ..utils.logging import get_logger
from .ledger import PromotionLedger
from .watch import CheckpointWatcher

logger = get_logger()


@dataclass
class _Baseline:
    """The currently promoted identity every rollback restores."""

    params: Any
    step: int
    checkpoint: str | None
    eval_loss: float | None = None


@dataclass
class PromotionResult:
    """What ``run()`` returns; the CLI maps ``status`` to the exit
    taxonomy (training_finished/max_promotions → 0, training_dead →
    EXIT_TRAIN_FAILURE)."""

    status: str
    promotions: int = 0
    rollbacks: int = 0
    aborts: int = 0
    last_promoted_step: int | None = None
    ledger_summary: dict[str, Any] = field(default_factory=dict)


class RouterFleet:
    """Fleet adapter over a :class:`~..serving.router.ReplicaRouter`.

    The controller only ever talks to this surface (swap one replica,
    swap the fleet, split traffic, soak) — tests substitute a fake with
    the same four verbs.
    """

    def __init__(
        self,
        router: Any,
        *,
        vocab_size: int,
        prompt_tokens_min: int = 4,
        prompt_tokens_max: int = 16,
        max_new_tokens: int = 8,
        eos_token_id: int | None = None,
    ) -> None:
        self.router = router
        self.vocab_size = int(vocab_size)
        self.prompt_tokens_min = int(prompt_tokens_min)
        self.prompt_tokens_max = int(prompt_tokens_max)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id

    @property
    def replica_count(self) -> int:
        return len(self.router.replicas)

    def canary_swap(
        self, idx: int, params: Any, step: int | None, checkpoint: str | None
    ) -> None:
        self.router.reload_replica(
            idx, params=params, step=step, checkpoint=checkpoint
        )

    def fleet_swap(
        self, params: Any, step: int | None, checkpoint: str | None
    ) -> list[dict[str, Any]]:
        return self.router.rolling_reload(
            params=params, step=step, checkpoint=checkpoint
        )

    def set_traffic_split(self, idx: int, frac: float, seed: int) -> None:
        self.router.set_canary(idx, traffic_frac=frac, seed=seed)

    def clear_traffic_split(self) -> None:
        self.router.clear_canary()

    def param_steps(self) -> list[int | None]:
        return [
            rep.get("param_step")
            for rep in self.router.stats()["router"]["replicas"]
        ]

    def soak(
        self, idx: int, *, requests: int, seed: int, timeout_sec: float
    ) -> dict[str, Any]:
        """Seeded probe burst against ONE replica; server-side TTFT and
        inter-token gaps aggregated the same way the loadgen SLO block
        is (ServerStats semantics, measured per-replica)."""
        reqs = build_requests(
            num_requests=requests,
            seed=seed,
            vocab_size=self.vocab_size,
            prompt_tokens_min=self.prompt_tokens_min,
            prompt_tokens_max=self.prompt_tokens_max,
            max_new_tokens=self.max_new_tokens,
            eos_token_id=self.eos_token_id,
        )
        replica = self.router.replicas[idx]
        for req in reqs:
            replica.submit(req)
        deadline = time.monotonic() + timeout_sec
        for req in reqs:
            if not req.done.wait(timeout=max(0.0, deadline - time.monotonic())):
                req.abandon()
        for req in reqs:
            req.done.wait(timeout=30.0)
        completed = [r for r in reqs if r.finish_reason in ("eos", "length")]
        failed = [r for r in reqs if r.finish_reason == "error"]
        ttft = [r.ttft_ms for r in completed if r.ttft_ms is not None]
        per_token: list[float] = []
        for r in completed:
            for a, b in zip(r.token_times, r.token_times[1:]):
                per_token.append((b - a) * 1e3)
        ttft_pct = percentiles(ttft)
        tok_pct = percentiles(per_token)
        return {
            "requests": len(reqs),
            "completed": len(completed),
            "failed": len(failed),
            "timed_out": len(reqs) - len(completed) - len(failed),
            "ttft_p50_ms": ttft_pct["p50"],
            "ttft_p95_ms": ttft_pct["p95"],
            "per_token_p50_ms": tok_pct["p50"],
            "per_token_p99_ms": tok_pct["p99"],
        }


class PromotionController:
    """The decision loop. Pure orchestration over injected collaborators:

    * ``watcher`` — :class:`CheckpointWatcher` (or fake): ``poll``,
      ``training_finished``, ``training_alive``.
    * ``fleet`` — :class:`RouterFleet` (or fake): ``replica_count``,
      ``canary_swap``, ``fleet_swap``, ``set_traffic_split``,
      ``clear_traffic_split``, ``soak``, ``param_steps``.
    * ``load_params`` — checkpoint path → inference params pytree.
    * ``evaluator`` — checkpoint path → held-out eval loss (None skips
      the eval gate).
    * ``ledger`` — :class:`PromotionLedger` on the watched run dir.
    """

    def __init__(
        self,
        *,
        cfg: PromoteConfig,
        watcher: CheckpointWatcher | Any,
        fleet: Any,
        ledger: PromotionLedger,
        baseline_params: Any,
        baseline_step: int = -1,
        baseline_checkpoint: str | None = None,
        load_params: Callable[[Path], Any] | None = None,
        evaluator: Callable[[Path], float | None] | None = None,
        registry: Any | None = None,
        timeline: Any | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ) -> None:
        self.cfg = cfg
        self.watcher = watcher
        self.fleet = fleet
        self.ledger = ledger
        self.registry = registry
        self.timeline = timeline
        self._load_params = load_params or (lambda p: p)
        self._evaluator = evaluator
        self._clock = clock
        self._sleep = sleep
        self.baseline = _Baseline(
            params=baseline_params,
            step=int(baseline_step),
            checkpoint=baseline_checkpoint,
        )
        self.promotions = 0
        self.rollbacks = 0
        self.aborts = 0
        # Promotion-cycle trace id: minted per candidate so the cycle's
        # decision instants and canary-window span correlate with the
        # serving traces that overlapped the soak (llmtrain trace show).
        self._cycle_trace_id: str | None = None
        if cfg.canary_replica >= fleet.replica_count:
            raise ValueError(
                f"promote.canary_replica ({cfg.canary_replica}) is out of "
                f"range for a {fleet.replica_count}-replica fleet"
            )

    # ----------------------------------------------------------- telemetry

    def _instant(self, decision: str, step: int, **args: Any) -> None:
        if self.timeline is not None:
            if self._cycle_trace_id is not None:
                args.setdefault("trace_id", self._cycle_trace_id)
            self.timeline.instant(
                f"promote/{decision}", cat="promote", step=step, **args
            )

    def _publish(self, **extra: float) -> None:
        if self.registry is None:
            return
        gauges = {
            "promote/promotions_total": float(self.promotions),
            "promote/rollbacks_total": float(self.rollbacks),
            "promote/aborts_total": float(self.aborts),
            "promote/last_promoted_step": float(self.baseline.step),
        }
        for name, value in extra.items():
            gauges[f"promote/{name}"] = float(value)
        self.registry.publish(gauges)

    # ---------------------------------------------------------------- loop

    def run(self) -> PromotionResult:
        """Watch → canary → decide until the stream ends. Resumes from
        the ledger: steps with a terminal decision are never re-judged,
        the newest ``promote`` entry re-anchors the baseline step."""
        decided = self.ledger.decided_steps()
        floor = max([self.baseline.step, *decided], default=self.baseline.step)
        pending = self.ledger.pending_canary()
        if pending is not None:
            # A killed promote left this candidate mid-judgement; re-open
            # its window (the second canary_start is the resume marker).
            floor = min(floor, int(pending["step"]) - 1)
        last_progress = self._clock()
        while True:
            if self.cfg.max_promotions and self.promotions >= self.cfg.max_promotions:
                return self._result("max_promotions")
            polled = self.watcher.poll(after_step=floor)
            if polled is None:
                if self.watcher.training_finished():
                    return self._result("training_finished")
                if self.watcher.training_alive(
                    stale_sec=self.cfg.idle_timeout_sec
                ):
                    last_progress = self._clock()
                elif self._clock() - last_progress > self.cfg.idle_timeout_sec:
                    return self._result("training_dead")
                self._sleep(self.cfg.poll_sec)
                continue
            ckpt, step = polled
            last_progress = self._clock()
            self._process_candidate(Path(ckpt), int(step))
            floor = max(floor, int(step))

    def _result(self, status: str) -> PromotionResult:
        logger.info(
            "promote: %s (promotions=%d rollbacks=%d aborts=%d)",
            status, self.promotions, self.rollbacks, self.aborts,
        )
        self._publish()
        return PromotionResult(
            status=status,
            promotions=self.promotions,
            rollbacks=self.rollbacks,
            aborts=self.aborts,
            last_promoted_step=(
                self.baseline.step if self.baseline.step >= 0 else None
            ),
            ledger_summary=self.ledger.summary(),
        )

    # ----------------------------------------------------------- one cycle

    def _process_candidate(self, ckpt: Path, step: int) -> None:
        self._cycle_trace_id = new_trace_id()
        win_t0 = time.perf_counter()
        try:
            self._run_cycle(ckpt, step)
        finally:
            if self.timeline is not None:
                try:
                    # The whole candidate cycle (swap + soak + decision)
                    # as one span, visible next to serving traces in the
                    # merged Perfetto view.
                    self.timeline.record(
                        "promote/canary_window",
                        t0=win_t0,
                        t1=time.perf_counter(),
                        cat="promote",
                        step=step,
                        checkpoint=str(ckpt),
                        trace_id=self._cycle_trace_id,
                    )
                except Exception:  # noqa: BLE001 — telemetry best-effort
                    pass
            self._cycle_trace_id = None

    def _run_cycle(self, ckpt: Path, step: int) -> None:
        cfg = self.cfg
        idx = cfg.canary_replica
        self.ledger.append("canary_start", step=step, checkpoint=str(ckpt))
        self._instant("canary_start", step, checkpoint=str(ckpt))
        if self.registry is not None:
            # Counter convention: no _total suffix here — the Prometheus
            # renderer appends it (→ llmtrain_promote_candidates_total).
            self.registry.inc("promote/candidates")
        self._publish(canary_step=step, canary_active=1.0)
        logger.info("promote: canarying step %d (%s)", step, ckpt.name)

        try:
            params = self._load_params(ckpt)
        except Exception as exc:  # noqa: BLE001 — a bad payload is a decision
            self._abort(step, ckpt, f"params load failed: {exc}")
            return
        # Exclude the canary from live placement (or A/B a seeded
        # fraction onto it) for the whole soak window.
        self.fleet.set_traffic_split(idx, cfg.traffic_split, cfg.soak_seed)
        try:
            try:
                self.fleet.canary_swap(idx, params, step, str(ckpt))
            except Exception as exc:  # noqa: BLE001
                self._abort(step, ckpt, f"canary swap failed: {exc}")
                return
            reason, scores = self._score(ckpt, step, idx)
            # Decide INSIDE the split window: on a rollback the canary
            # must be restored to the baseline before it rejoins live
            # placement, or a regressed candidate briefly serves traffic.
            if reason is None:
                self._promote(ckpt, step, params, scores)
            else:
                self._rollback_canary(ckpt, step, idx, reason, scores)
        finally:
            self.fleet.clear_traffic_split()

    def _abort(self, step: int, ckpt: Path, reason: str) -> None:
        self.aborts += 1
        self.ledger.append("abort", step=step, checkpoint=str(ckpt), reason=reason)
        self._instant("abort", step, reason=reason)
        self._publish(canary_active=0.0)
        logger.warning("promote: step %d aborted: %s", step, reason)

    # ------------------------------------------------------------- scoring

    def _score(
        self, ckpt: Path, step: int, idx: int
    ) -> tuple[str | None, dict[str, Any]]:
        """Soak + eval the canary; first failing gate wins. Returns
        (None, scores) on pass, (reason, scores) on regression."""
        cfg = self.cfg
        scores: dict[str, Any] = {}
        canary = self.fleet.soak(
            idx,
            requests=cfg.soak_requests,
            seed=cfg.soak_seed,
            timeout_sec=cfg.soak_timeout_sec,
        )
        scores["canary"] = canary
        bad = int(canary.get("failed", 0)) + int(canary.get("timed_out", 0))
        if bad > cfg.allow_failed_requests:
            return f"canary_request_failures: {bad}", scores

        if self._evaluator is not None:
            try:
                cand_loss = self._evaluator(ckpt)
            except Exception as exc:  # noqa: BLE001 — eval crash = regression
                return f"eval failed: {exc}", scores
            if cand_loss is not None:
                scores["eval_loss"] = float(cand_loss)
                base_loss = self._baseline_eval_loss()
                if base_loss is not None:
                    delta = float(cand_loss) - base_loss
                    scores["baseline_eval_loss"] = base_loss
                    scores["eval_loss_delta"] = round(delta, 6)
                    self._publish(last_eval_loss_delta=delta)
                    if delta > cfg.max_eval_loss_delta:
                        return (
                            f"eval_regression: delta {delta:.4f} > "
                            f"{cfg.max_eval_loss_delta}",
                            scores,
                        )

        # SLO side of the A/B: the same seeded probes against a
        # reference replica still serving the promoted baseline.
        ref_idx = next(
            (i for i in range(self.fleet.replica_count) if i != idx), None
        )
        if ref_idx is not None:
            reference = self.fleet.soak(
                ref_idx,
                requests=cfg.soak_requests,
                seed=cfg.soak_seed,
                timeout_sec=cfg.soak_timeout_sec,
            )
            scores["reference"] = reference
            for metric, bound in (
                ("ttft_p95_ms", cfg.ttft_p95_slowdown),
                ("per_token_p99_ms", cfg.per_token_p99_slowdown),
            ):
                if bound is None:
                    continue
                c, r = canary.get(metric), reference.get(metric)
                if c is not None and r is not None and r > 0 and c / r > bound:
                    return (
                        f"slo_regression: {metric} {c:.1f}ms vs "
                        f"{r:.1f}ms baseline (> {bound}x)",
                        scores,
                    )
        return None, scores

    def _baseline_eval_loss(self) -> float | None:
        if self.baseline.eval_loss is not None:
            return self.baseline.eval_loss
        if self._evaluator is None or self.baseline.checkpoint is None:
            return None
        try:
            loss = self._evaluator(Path(self.baseline.checkpoint))
        except Exception:  # noqa: BLE001 — no baseline, no eval gate
            return None
        if loss is not None:
            self.baseline.eval_loss = float(loss)
        return self.baseline.eval_loss

    # ------------------------------------------------------------ outcomes

    def _promote(
        self, ckpt: Path, step: int, params: Any, scores: dict[str, Any]
    ) -> None:
        results = self.fleet.fleet_swap(params, step, str(ckpt))
        failed = [r for r in results if "error" in r]
        if failed:
            # A partially applied fleet swap: some replicas admitted the
            # candidate, some did not (epoch_divergence > 0). Converge
            # DOWN: roll every replica back to the promoted baseline.
            restore = self.fleet.fleet_swap(
                self.baseline.params,
                self.baseline.step,
                self.baseline.checkpoint,
            )
            scores["fleet_swap"] = results
            scores["fleet_restore"] = restore
            self.rollbacks += 1
            self.ledger.append(
                "rollback",
                step=step,
                checkpoint=str(ckpt),
                reason=(
                    "partial_fleet_swap: "
                    + ", ".join(r["replica"] for r in failed)
                ),
                scores=scores,
            )
            self._instant("rollback", step, reason="partial_fleet_swap")
            if self.registry is not None:
                self.registry.inc("promote/rollbacks_total")
            self._publish(canary_active=0.0)
            logger.warning(
                "promote: step %d fleet swap failed on %d replica(s); "
                "rolled the fleet back to step %d",
                step, len(failed), self.baseline.step,
            )
            return
        scores["fleet_swap"] = results
        self.promotions += 1
        self.baseline = _Baseline(
            params=params,
            step=step,
            checkpoint=str(ckpt),
            eval_loss=scores.get("eval_loss"),
        )
        self.ledger.append(
            "promote", step=step, checkpoint=str(ckpt), scores=scores
        )
        self._instant("promote", step, checkpoint=str(ckpt))
        self._publish(canary_active=0.0)
        logger.info("promote: step %d promoted fleet-wide", step)

    def _rollback_canary(
        self,
        ckpt: Path,
        step: int,
        idx: int,
        reason: str,
        scores: dict[str, Any],
    ) -> None:
        extra: dict[str, Any] = {}
        try:
            self.fleet.canary_swap(
                idx,
                self.baseline.params,
                self.baseline.step,
                self.baseline.checkpoint,
            )
        except Exception as exc:  # noqa: BLE001 — record, don't crash the loop
            extra["canary_restore_error"] = str(exc)
            logger.error(
                "promote: restoring the canary to step %d failed: %s",
                self.baseline.step, exc,
            )
        self.rollbacks += 1
        self.ledger.append(
            "rollback",
            step=step,
            checkpoint=str(ckpt),
            reason=reason,
            scores=scores,
            **extra,
        )
        self._instant("rollback", step, reason=reason)
        self._publish(canary_active=0.0)
        logger.warning("promote: step %d rolled back: %s", step, reason)


__all__ = ["PromotionController", "PromotionResult", "RouterFleet"]
