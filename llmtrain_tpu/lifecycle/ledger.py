"""Durable promotion ledger: ``promotions.jsonl`` in the watched run dir.

Same durability stance as the checkpoint manifests and the goodput
ledger: the ONLY record of what the promotion controller decided is an
append-only JSONL file, fsynced per line, living next to the training
run's other artifacts. A promote process SIGKILLed mid-decision leaves
at worst one torn trailing line (skipped on replay); re-running
``llmtrain promote`` replays the ledger and resumes after the last
terminal decision instead of double-promoting.

Entry schema (one JSON object per line)::

    {"seq": 3, "ts_unix": 1770000000.0, "decision": "promote",
     "step": 200, "checkpoint": ".../step_000200.ckpt",
     "reason": null, "scores": {"eval_loss": 2.1, ...}}

``decision`` is one of :data:`DECISIONS`; ``canary_start`` opens a
candidate's window and exactly one of the :data:`TERMINAL_DECISIONS`
closes it.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any

DECISIONS = ("canary_start", "promote", "rollback", "abort")
TERMINAL_DECISIONS = frozenset({"promote", "rollback", "abort"})


class PromotionLedger:
    """Append-only JSONL decision log with crash-safe replay."""

    def __init__(self, path: str | Path) -> None:
        self._path = Path(path)
        self._next_seq = 0
        for entry in self.entries():
            self._next_seq = max(self._next_seq, int(entry.get("seq", -1)) + 1)

    @property
    def path(self) -> Path:
        return self._path

    # ------------------------------------------------------------- writing

    def append(
        self,
        decision: str,
        *,
        step: int,
        checkpoint: str | None = None,
        reason: str | None = None,
        scores: dict[str, Any] | None = None,
        **extra: Any,
    ) -> dict[str, Any]:
        """Write one decision line (fsync before returning — the entry
        must survive a SIGKILL that lands right after the decision)."""
        if decision not in DECISIONS:
            raise ValueError(f"unknown promotion decision {decision!r}")
        entry: dict[str, Any] = {
            "seq": self._next_seq,
            "ts_unix": time.time(),
            "decision": decision,
            "step": int(step),
            "checkpoint": checkpoint,
            "reason": reason,
            "scores": scores or {},
        }
        entry.update(extra)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        line = json.dumps(entry, sort_keys=True)
        with open(self._path, "a", encoding="utf-8") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._next_seq += 1
        return entry

    # ------------------------------------------------------------- reading

    def entries(self) -> list[dict[str, Any]]:
        """Parsed ledger lines, oldest first. An unparseable line (the
        torn tail a SIGKILL can leave) is skipped, not fatal."""
        try:
            raw = self._path.read_text(encoding="utf-8")
        except OSError:
            return []
        out = []
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                continue
            if isinstance(entry, dict) and entry.get("decision") in DECISIONS:
                out.append(entry)
        return out

    def last_promoted(self) -> dict[str, Any] | None:
        """The newest ``promote`` entry — the fleet's baseline on resume."""
        for entry in reversed(self.entries()):
            if entry["decision"] == "promote":
                return entry
        return None

    def decided_steps(self) -> set[int]:
        """Steps with a TERMINAL decision. Replay skips these: a step
        already promoted/rolled-back/aborted is never re-canaried, which
        is what makes re-running promote after a SIGKILL idempotent."""
        return {
            int(e["step"])
            for e in self.entries()
            if e["decision"] in TERMINAL_DECISIONS
        }

    def pending_canary(self) -> dict[str, Any] | None:
        """A ``canary_start`` not yet closed by a terminal decision for
        the same step — the candidate a killed promote was judging."""
        pending: dict[int, dict[str, Any]] = {}
        for entry in self.entries():
            step = int(entry["step"])
            if entry["decision"] == "canary_start":
                pending[step] = entry
            elif entry["decision"] in TERMINAL_DECISIONS:
                pending.pop(step, None)
        if not pending:
            return None
        return pending[max(pending)]

    def summary(self) -> dict[str, Any]:
        """Counts + last promoted step, the shape the goodput ledger and
        the CLI report embed."""
        entries = self.entries()
        counts = {d: 0 for d in DECISIONS}
        for e in entries:
            counts[e["decision"]] += 1
        promoted = self.last_promoted()
        return {
            "path": str(self._path),
            "entries": len(entries),
            "decisions": counts,
            "last_promoted_step": promoted["step"] if promoted else None,
            "last_promoted_checkpoint": (
                promoted["checkpoint"] if promoted else None
            ),
        }


__all__ = ["DECISIONS", "TERMINAL_DECISIONS", "PromotionLedger"]
