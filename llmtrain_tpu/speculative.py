"""Speculative decoding: draft-and-verify over two KV-cache models.

Beyond-reference serving capability (the reference generates eagerly per
token from one model, notebooks/trained_vs_random_completion.ipynb). A
small DRAFT model proposes ``gamma`` tokens autoregressively; the TARGET
model scores all of them in ONE forward; the longest agreeing prefix is
accepted and the first disagreement is replaced by the target's own
token. Per target forward the decode advances by 1..gamma+1 positions,
so target-model latency per token drops by up to (gamma+1)x when the
draft agrees — and the output is EXACT:

* ``temperature == 0``: acceptance is argmax equality, and the result is
  bit-identical to plain greedy decoding from the target alone (pinned
  by tests for dense, GQA, rolling-window, and llama models).
* ``temperature > 0``: standard speculative rejection sampling
  (Leviathan et al. / Chen et al., PAPERS.md): draft token x with
  draft prob q(x) and target prob p(x) is accepted w.p. min(1, p/q);
  on rejection the replacement is drawn from norm(max(p - q, 0)). The
  marginal distribution of every emitted token equals sampling from the
  target alone — same temperature/top-k/top-p filtering applied to both
  models' logits.

TPU-first mechanics: the whole loop is ONE jit program — a
``lax.while_loop`` whose carry is (token buffer, position, both cache
pytrees, rng, step counter). Acceptance length is data-dependent, but
shapes never are: the target always scores gamma+1 positions, the buffer
write is always gamma+1 wide (garbage beyond the accepted prefix is
overwritten by later iterations), and cache rollback is CURSOR-ONLY —
stale K/V slots beyond the cursor are unreachable (causal masking
excludes positions > query) and are overwritten in order before any
query can see them, for both the linear and the rolling (windowed)
cache layouts (models/gpt.py:_decode_attention).

Scope: batch size 1 (per-row acceptance lengths would need per-row
cursors; validated loudly). ``eos_token_id`` stops at the first emitted
eos and eos-fills the tail — exactly the plain path's behavior
(generation.py force-fills eos after the first one), so exactness
holds with early stopping too.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


def _set_cursor(cache: Any, value: jax.Array) -> Any:
    """Return ``cache`` with every cursor leaf set to ``value``.

    Cursor leaves: per-layer ``cache_index`` and GPT's model-level
    ``position_index`` (models/gpt.py) — scalar int32 counters.
    """

    def set_leaf(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cache_index", "position_index"):
            return jnp.asarray(value, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(set_leaf, cache)


def _filtered_logprobs(
    logits: jax.Array, *, temperature: float, top_k: int | None, top_p: float | None
) -> jax.Array:
    """Log-probs after the SAME temperature/top-k/top-p filter the plain
    sampler applies — shared implementation (generation.filter_logits),
    so the exactness contract cannot drift between the two modules."""
    from .generation import filter_logits

    scaled = filter_logits(
        logits.astype(jnp.float32) / temperature, top_k=top_k, top_p=top_p
    )
    return jax.nn.log_softmax(scaled, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "draft_model", "max_new_tokens", "gamma", "temperature",
        "top_k", "top_p", "eos_token_id",
    ),
)
def _speculative_jit(
    model: Any,
    params: Any,
    cache: Any,
    draft_model: Any,
    draft_params: Any,
    draft_cache: Any,
    prompt: jax.Array,  # (1, Tp)
    rng: jax.Array,
    *,
    max_new_tokens: int,
    gamma: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    eos_token_id: int | None,
) -> jax.Array:
    tp = prompt.shape[1]
    total = tp + max_new_tokens
    # Room for one full overshooting iteration past `total`.
    buf = jnp.zeros((1, total + gamma + 1), prompt.dtype)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))

    def apply(m, p, c, tokens):
        logits, mutated = m.apply(
            {"params": p, "cache": c}, tokens, deterministic=True,
            mutable=["cache"],
        )
        return mutated["cache"], logits.astype(jnp.float32)

    # Establish the loop invariant (caches hold tokens 0..n-2, cursor
    # n-1, with n = tp): prefill both models on the prompt MINUS its
    # last token, which the first iteration feeds as its context token.
    if tp > 1:
        cache, _ = apply(model, params, cache, prompt[:, :-1])
        draft_cache, _ = apply(
            draft_model, draft_params, draft_cache, prompt[:, :-1]
        )

    greedy = temperature == 0.0

    def body(carry):
        buf, n, cache, draft_cache, it = carry
        step_rng = jax.random.fold_in(rng, it)

        # --- draft: gamma tokens; sampling mode also carries the FULL
        # filtered q vector per step (gamma, V) — the rejection-sampling
        # leftover distribution norm(max(p - q, 0)) needs it.
        def draft_step(state, j):
            dcache, tok = state
            dcache, logits = apply(
                draft_model, draft_params, dcache, tok[:, None]
            )
            logit = logits[:, 0]  # (1, V)
            if greedy:
                nxt = jnp.argmax(logit, axis=-1)
                aux = jnp.zeros((1,))
            else:
                lq = _filtered_logprobs(
                    logit, temperature=temperature, top_k=top_k, top_p=top_p
                )
                nxt = jax.random.categorical(
                    jax.random.fold_in(step_rng, j), lq, axis=-1
                )
                aux = lq[0]
            return (dcache, nxt.astype(tok.dtype)), (nxt[0], aux)

        tok_in = jax.lax.dynamic_slice(buf, (0, n - 1), (1, 1))[:, 0]
        (draft_cache, last_tok), (drafts, q_aux) = jax.lax.scan(
            draft_step, (draft_cache, tok_in), jnp.arange(gamma)
        )  # drafts: (gamma,); q_aux: (gamma, V) logprobs (or (gamma, 1))
        # One extra draft forward feeds d_{gamma-1} so its K/V exists in
        # the draft cache: without it a fully-accepted iteration advances
        # the cursor past a position that was never written, leaving a
        # PERMANENT zero-K/V hole every later draft query attends —
        # output stays exact (acceptance uses the actual q) but the
        # acceptance rate decays. Logits are discarded.
        draft_cache, _ = apply(
            draft_model, draft_params, draft_cache, last_tok[:, None]
        )

        # --- target: ONE forward over [context token, d_0..d_{gamma-1}].
        seq = jnp.concatenate(
            [tok_in.astype(buf.dtype), drafts.astype(buf.dtype)]
        )[None, :]  # (1, gamma+1)
        cache, t_logits = apply(model, params, cache, seq)  # (1, gamma+1, V)

        if greedy:
            t_pred = jnp.argmax(t_logits[0], axis=-1)  # (gamma+1,)
            match = drafts == t_pred[:gamma]
            accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32)))
            # t_pred[j] == drafts[j] for j < accepted, and t_pred[accepted]
            # is the correction — one write covers both.
            out_tokens = t_pred
        else:
            lp = _filtered_logprobs(
                t_logits, temperature=temperature, top_k=top_k, top_p=top_p
            )[0]  # (gamma+1, V)
            p_chosen = jnp.take_along_axis(
                lp[:gamma], drafts[:, None], axis=-1
            )[:, 0]
            q_chosen = jnp.take_along_axis(q_aux, drafts[:, None], axis=-1)[:, 0]
            # Accept d_j w.p. min(1, p/q); a draft token the target filter
            # removed (p = -inf) is always rejected.
            uniforms = jax.random.uniform(
                jax.random.fold_in(step_rng, gamma + 1), (gamma,)
            )
            ratio = jnp.exp(jnp.minimum(p_chosen - q_chosen, 0.0))
            ratio = jnp.where(jnp.isfinite(p_chosen), ratio, 0.0)
            ok = uniforms < ratio
            accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
            # Replacement at the first rejection: norm(max(p - q, 0));
            # padding q with zeros at j = gamma makes the all-accepted
            # case a fresh draw from p_gamma via the same expression.
            p_all = jnp.exp(lp)  # (gamma+1, V)
            q_all = jnp.concatenate(
                [jnp.exp(q_aux), jnp.zeros((1, q_aux.shape[-1]))], axis=0
            )
            leftover = jnp.clip(p_all - q_all, 0.0, None)  # (gamma+1, V)
            row = leftover[accepted]
            norm = jnp.sum(row)
            # norm == 0 only when p <= q everywhere (then rejection had
            # probability 0); numerical guard falls back to p.
            row = jnp.where(norm > 0, row / jnp.maximum(norm, 1e-38),
                            p_all[accepted])
            correction = jax.random.categorical(
                jax.random.fold_in(step_rng, gamma + 2),
                jnp.log(row + 1e-38),
            ).astype(drafts.dtype)
            base = jnp.concatenate([drafts, drafts[:1]])  # (gamma+1,)
            out_tokens = jnp.where(
                jnp.arange(gamma + 1) == accepted, correction, base
            )

        # --- write to positions n..n+gamma; only n..n+accepted are valid
        # (later iterations overwrite the rest); advance by accepted+1.
        buf = jax.lax.dynamic_update_slice(
            buf, out_tokens[None].astype(buf.dtype), (0, n)
        )
        n_new = n + accepted + 1
        if eos_token_id is not None:
            # Stop at the FIRST emitted eos: clamp the advance so n_new
            # points one past it. Exactness holds because the plain path
            # force-fills eos after the first one regardless of context
            # (generation.py:104-106) — the post-loop fill below emits
            # the same tail.
            emitted = jnp.arange(gamma + 1) <= accepted
            is_eos = emitted & (out_tokens == eos_token_id)
            first = jnp.argmax(is_eos)  # 0 if none — guarded by any()
            n_new = jnp.where(jnp.any(is_eos), n + first + 1, n_new)
        cache = _set_cursor(cache, n_new - 1)
        draft_cache = _set_cursor(draft_cache, n_new - 1)
        return buf, n_new, cache, draft_cache, it + 1

    def cond(carry):
        buf, n, _, _, _ = carry
        going = n < total
        if eos_token_id is not None:
            # n-1 is the last emitted token; eos there ends the loop.
            last = jax.lax.dynamic_slice(buf, (0, n - 1), (1, 1))[0, 0]
            going = going & ((n <= tp) | (last != eos_token_id))
        return going

    buf, n, _, _, iterations = jax.lax.while_loop(
        cond, body, (buf, jnp.asarray(tp, jnp.int32), cache, draft_cache,
                     jnp.asarray(0, jnp.int32))
    )
    if eos_token_id is not None:
        # eos-fill the tail beyond the stop point, like the plain path.
        pos = jnp.arange(buf.shape[1])
        buf = jnp.where(pos[None, :] >= n, jnp.asarray(eos_token_id, buf.dtype), buf)
    return buf[:, :total], n, iterations


def speculative_generate(
    model: Any,
    params: Any,
    draft_model: Any,
    draft_params: Any,
    prompt: np.ndarray | jax.Array,
    *,
    max_new_tokens: int,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_token_id: int | None = None,
    rng: jax.Array | None = None,
    return_stats: bool = False,
) -> np.ndarray | tuple[np.ndarray, dict]:
    """Draft-and-verify decode; returns (1, Tp + max_new_tokens) tokens.

    ``model``/``draft_model`` are TRAINING-mode modules exposing
    ``for_decoding()`` (GPT/Llama families); both must share the
    tokenizer/vocab. ``gamma`` is the draft lookahead per target forward.
    ``return_stats=True`` also returns ``{"target_forwards": k,
    "mean_accepted": a}`` — k is the number of verify iterations (=
    target forwards after prefill) and a the mean accepted drafts per
    iteration (gamma when the draft always agrees).
    """
    ids = np.asarray(prompt)
    if ids.ndim != 2 or ids.shape[0] != 1:
        raise ValueError(
            f"speculative decoding supports batch size 1, got shape {ids.shape}"
        )
    if max_new_tokens <= 0:
        out = ids.copy()
        return (out, {"target_forwards": 0, "mean_accepted": 0.0}) if return_stats else out
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    # Same out-of-band conventions as generate() (generation.py:283-289):
    # a library caller passing top_k=0 must mean "disabled", not reach
    # lax.top_k(x, 0) inside filter_logits under jit.
    if top_k is not None and top_k <= 0:
        top_k = None
    if top_p is not None and (top_p <= 0.0 or top_p >= 1.0):
        top_p = None
    for m, label in ((model, "model"), (draft_model, "draft_model")):
        if not hasattr(m, "for_decoding"):
            raise ValueError(f"{label} must expose for_decoding() for KV caching")
    total = ids.shape[1] + max_new_tokens
    for m, label in ((model, "target"), (draft_model, "draft")):
        if total + gamma + 1 > m.block_size:
            raise ValueError(
                f"prompt+max_new_tokens+gamma ({total + gamma + 1}) exceeds the "
                f"{label} model's block_size ({m.block_size})"
            )
    if rng is None:
        rng = jax.random.key(0)

    def zero_cache(m):
        # ring_slack=gamma+1: a windowed model's rolling cache needs the
        # slack so rolled-back speculative writes cannot evict live
        # window entries (CausalSelfAttention.ring_slack).
        dm = m.for_decoding(cache_len=total + gamma + 1, ring_slack=gamma + 1)
        shapes = jax.eval_shape(
            lambda: dm.init(
                jax.random.key(0), jnp.zeros((1, 1), jnp.int32),
                deterministic=True,
            )
        )
        return dm, jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
        )

    decode_model, cache = zero_cache(model)
    decode_draft, draft_cache = zero_cache(draft_model)
    out, final_n, iterations = _speculative_jit(
        decode_model,
        params,
        cache,
        decode_draft,
        draft_params,
        draft_cache,
        jnp.asarray(ids),
        rng,
        max_new_tokens=max_new_tokens,
        gamma=gamma,
        temperature=float(temperature),
        top_k=top_k,
        top_p=top_p,
        eos_token_id=eos_token_id,
    )
    tokens = np.asarray(jax.device_get(out))
    if return_stats:
        k = int(jax.device_get(iterations))
        # ACTUAL emitted count (eos may stop early; the final iteration's
        # trimmed overshoot slightly underestimates acceptance, < 1/k).
        emitted = min(int(jax.device_get(final_n)) - ids.shape[1], max_new_tokens)
        mean_accepted = emitted / k - 1.0 if k else 0.0
        return tokens, {
            "target_forwards": k,
            "mean_accepted": round(mean_accepted, 4),
        }
    return tokens


__all__ = ["speculative_generate"]
