"""``python -m llmtrain_tpu`` entry point (reference src/llmtrain/__main__.py)."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
