"""gpt_pipeline ↔ gpt parameter conversion (interop/pipeline_convert.py).

Pipeline-trained checkpoints unlock the rest of the toolchain through
this conversion: reference-format torch export, KV-cache generation via
the gpt tree, and import back into a pipeline config. The math oracle is
logits equality — the two modules implement the same architecture (LN
eps 1e-6 aligned), so conversion must be numerically exact.
"""

from __future__ import annotations

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml
from flax.linen import meta as nn_meta

from llmtrain_tpu.interop import (
    gpt_params_to_pipeline,
    is_pipeline_tree,
    pipeline_params_to_gpt,
)
from llmtrain_tpu.models.gpt import GPT
from llmtrain_tpu.models.gpt_pipeline import PipelineGPT

DIMS = dict(vocab_size=64, block_size=16, d_model=32, n_layers=4, n_heads=4, d_ff=64)


def _pipeline_params(tie=True):
    model = PipelineGPT(tie_embeddings=tie, **DIMS)
    params = nn_meta.unbox(
        model.init(jax.random.key(0), jnp.zeros((1, 16), jnp.int32))
    )["params"]
    return model, params


class TestConversion:
    @pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
    def test_roundtrip_identity(self, tie):
        _, params = _pipeline_params(tie)
        back = gpt_params_to_pipeline(pipeline_params_to_gpt(params))
        for (pa, va), (pb, vb) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(back),
            strict=True,
        ):
            assert pa == pb
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))

    @pytest.mark.parametrize("tie", [True, False], ids=["tied", "untied"])
    def test_converted_params_drive_gpt_to_same_logits(self, tie):
        pipe, params = _pipeline_params(tie)
        gpt = GPT(dropout=0.0, tie_embeddings=tie, **DIMS)
        converted = pipeline_params_to_gpt(params)
        ids = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (2, 16)), jnp.int32
        )
        a = pipe.apply({"params": params}, ids)
        b = gpt.apply({"params": converted}, ids, deterministic=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def test_is_pipeline_tree(self):
        _, params = _pipeline_params()
        assert is_pipeline_tree(params)
        assert not is_pipeline_tree(pipeline_params_to_gpt(params))

    def test_abstract_template_conversion(self):
        """ShapeDtypeStruct trees convert too — the import-checkpoint path
        maps torch weights through a gpt-shaped abstract template."""
        _, params = _pipeline_params()
        abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), params
        )
        gpt_tpl = pipeline_params_to_gpt(abstract)
        assert gpt_tpl["block_0"]["attn"]["qkv_proj"]["kernel"].shape == (32, 3, 4, 8)
        assert isinstance(
            gpt_tpl["block_0"]["attn"]["qkv_proj"]["kernel"], jax.ShapeDtypeStruct
        )

    def test_cached_decode_via_conversion_matches_pipeline_reforward(self):
        """Greedy KV-cache decoding through the converted GPT equals the
        pipeline model's own re-forward decoding — the generate CLI's
        conversion path is exact."""
        from llmtrain_tpu.generation import generate

        pipe, params = _pipeline_params(True)
        gpt = GPT(dropout=0.0, tie_embeddings=True, **DIMS)
        converted = pipeline_params_to_gpt(params)
        prompt = np.asarray([[3, 1, 4, 1, 5]], np.int32)
        cached = generate(
            gpt, converted, prompt, max_new_tokens=8, temperature=0.0,
            use_cache=True,
        )
        windowed = generate(
            pipe, params, prompt, max_new_tokens=8, temperature=0.0,
            use_cache=False,
        )
        np.testing.assert_array_equal(cached, windowed)

    def test_gqa_roundtrip_and_logits(self):
        """The split q/kv (GQA) layout converts both ways and drives the
        GQA GPT to the pipeline model's exact logits."""
        pipe = PipelineGPT(tie_embeddings=True, n_kv_heads=2, **DIMS)
        params = nn_meta.unbox(
            pipe.init(jax.random.key(1), jnp.zeros((1, 16), jnp.int32))
        )["params"]
        assert "q_kernel" in params and "qkv_kernel" not in params
        assert is_pipeline_tree(params)

        converted = pipeline_params_to_gpt(params)
        assert "q_proj" in converted["block_0"]["attn"]
        back = gpt_params_to_pipeline(converted)
        for (pa, va), (pb, vb) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(back),
            strict=True,
        ):
            assert pa == pb
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))

        gpt = GPT(dropout=0.0, tie_embeddings=True, n_kv_heads=2, **DIMS)
        ids = jnp.asarray(
            np.random.default_rng(9).integers(0, 64, (2, 16)), jnp.int32
        )
        a = pipe.apply({"params": params}, ids)
        b = gpt.apply({"params": converted}, ids, deterministic=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
class TestPipelineExportCLI:
    def test_pipeline_train_export_reference_load_import_eval(self, tmp_path):
        """Full loop for a pipeline-trained run: train -> export (auto
        conversion) -> strict-load into the REAL reference torch GPT where
        available -> import back into the pipeline config -> eval matches
        the source checkpoint exactly."""
        cfg = {
            "run": {"name": "ppconv", "seed": 0, "device": "cpu"},
            "model": {
                "name": "gpt_pipeline",
                "block_size": 16,
                "d_model": 32,
                "n_layers": 4,
                "n_heads": 4,
                "d_ff": 64,
                "dropout": 0.0,
                "vocab_size": 64,
                "extra": {"tokenizer": "byte", "pipeline_microbatches": 2},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 2,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 2,
                "save_every_steps": 2,
            },
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(tmp_path / "runs")},
        }
        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump(cfg, sort_keys=False))

        def run(argv):
            return subprocess.run(
                [sys.executable, "-m", "llmtrain_tpu", *argv],
                capture_output=True, text=True, timeout=300,
            )

        train = run(["train", "--config", str(cfg_path), "--run-id", "src", "--json"])
        assert train.returncode == 0, train.stderr

        pt = tmp_path / "model.pt"
        exp = run(["export-checkpoint", "--config", str(cfg_path), "--from", "src",
                   "--output", str(pt), "--json"])
        assert exp.returncode == 0, exp.stderr

        torch = pytest.importorskip("torch")
        sd = torch.load(pt, weights_only=True)
        assert "blocks.0.attn.qkv_proj.weight" in sd  # per-layer, not stacked

        import os
        ref_src = os.environ.get("LLMTRAIN_REFERENCE_SRC", "/root/reference/src")
        if os.path.isdir(ref_src):
            sys.path.insert(0, ref_src)
            try:
                from llmtrain.models.gpt import GPT as RefGPT  # type: ignore

                ref = RefGPT(vocab_size=64, block_size=16, d_model=32,
                             n_layers=4, n_heads=4, d_ff=64, dropout=0.0,
                             tie_embeddings=True)
                missing, unexpected = ref.load_state_dict(sd, strict=True)
                assert not missing and not unexpected
            finally:
                sys.path.remove(ref_src)

        imported = tmp_path / "imported"
        imp = run(["import-checkpoint", "--config", str(cfg_path), "--input", str(pt),
                   "--output", str(imported), "--json"])
        assert imp.returncode == 0, imp.stderr

        gen = run(["generate", "--config", str(cfg_path), "--from", "src",
                   "--prompt-ids", "1,2,3", "--max-new-tokens", "4",
                   "--temperature", "0", "--json"])
        assert gen.returncode == 0, gen.stderr
        assert len(json.loads(gen.stdout)["output_ids"]) == 7
        assert "converted to the gpt tree" in gen.stderr

        ev_src = run(["eval", "--config", str(cfg_path), "--from", "src", "--json"])
        ev_imp = run(["eval", "--config", str(cfg_path), "--from", str(imported), "--json"])
        assert ev_src.returncode == 0 and ev_imp.returncode == 0, ev_imp.stderr
        src_loss = json.loads(ev_src.stdout)["metrics"]["val/loss"]
        imp_loss = json.loads(ev_imp.stdout)["metrics"]["val/loss"]
        assert abs(src_loss - imp_loss) < 1e-6
