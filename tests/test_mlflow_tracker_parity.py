"""Execute tracking/mlflow.py for real + assert backend parity — UNCONDITIONALLY.

The reference runs its tracker against real persistence in every test run
(reference tests/test_cli.py:628-704). This image has no mlflow, so the
real round-trip (tests/test_mlflow_roundtrip.py) only runs in the k8s
image — leaving MLflowTracker dead code here, and nothing asserting the
two backends record a run identically. These tests close both gaps with
``tests/fake_mlflow.py`` injected as ``sys.modules["mlflow"]``: every
line of the tracker executes (lazy import, experiment setup, tag-based
run-join search, param flattening, metric steps, artifact logging,
status transitions), and a parity test drives the SAME call sequence
through ``SqliteTracker`` and ``MLflowTracker`` and compares what each
store read back.
"""

from __future__ import annotations

import math
import sys

import pytest

import fake_mlflow
from llmtrain_tpu.tracking import SqliteTracker
from llmtrain_tpu.tracking.mlflow import MLflowTracker, _flatten_params
from llmtrain_tpu.tracking.sqlite import read_metrics, read_params, read_runs


@pytest.fixture()
def mlflow_fake(monkeypatch):
    fake_mlflow.reset()
    monkeypatch.setitem(sys.modules, "mlflow", fake_mlflow)
    yield fake_mlflow
    fake_mlflow.reset()


PARAMS = {
    "model": {"d_model": 64, "dropout": 0.1, "mesh": [2, 4]},
    "trainer": {"lr": 3e-4},
    "run_name": "parity",
}


def _drive(tracker, run_id: str, artifact: str) -> None:
    """The call sequence cli.py/trainer.py issue over a training run."""
    tracker.start_run(run_id)
    tracker.log_params(PARAMS)
    tracker.log_metrics({"train/loss": 2.5, "train/lr": 3e-4}, step=1)
    tracker.log_metrics({"train/loss": 2.25}, step=2)
    tracker.log_metrics({"val/loss": float("nan")}, step=2)
    tracker.log_artifact(artifact, artifact_path="configs")
    tracker.end_run("FINISHED")
    # The --auto-resume relaunch: same framework run id must CONTINUE the
    # run (join), then extend its metric history.
    tracker.start_run(run_id)
    tracker.log_metrics({"train/loss": 2.0}, step=3)
    tracker.end_run("FINISHED")


class TestMLflowTrackerExecutes:
    def test_full_protocol_and_run_join(self, mlflow_fake, tmp_path):
        art = tmp_path / "config.yaml"
        art.write_text("x: 1\n")
        t = MLflowTracker("sqlite:///mlflow.db", "exp", run_name="parity")
        _drive(t, "run-abc", str(art))

        store = mlflow_fake._stores["sqlite:///mlflow.db"]
        assert len(store.runs) == 1, "relaunch must join, not open a second run"
        (run,) = store.runs.values()
        assert run.tags["llmtrain.run_id"] == "run-abc"
        assert run.status == "FINISHED"
        assert run.params == {
            k: str(v) for k, v in _flatten_params(PARAMS).items()
        }
        assert [(m["key"], m["step"]) for m in run.metrics] == [
            ("train/loss", 1),
            ("train/lr", 1),
            ("train/loss", 2),
            ("val/loss", 2),
            ("train/loss", 3),
        ]
        assert run.artifacts == [(str(art), "configs")]

    def test_missing_mlflow_raises_clear_error(self, monkeypatch):
        monkeypatch.setitem(sys.modules, "mlflow", None)  # import -> ImportError
        t = MLflowTracker("sqlite:///x.db", "exp")
        with pytest.raises(RuntimeError, match=r"mlflow is not installed"):
            t.start_run("r")

    def test_quoted_run_id_skips_join_search(self, mlflow_fake):
        """A hand-picked --run-id with quotes cannot be escaped in MLflow
        filter strings; the tracker must start fresh, not crash."""
        t = MLflowTracker("sqlite:///q.db", "exp")
        t.start_run("it's-a-run")
        t.end_run()
        t.start_run("it's-a-run")  # join skipped -> second run
        t.end_run()
        assert len(mlflow_fake._stores["sqlite:///q.db"].runs) == 2

    def test_search_failure_starts_fresh(self, mlflow_fake, monkeypatch):
        def boom(**kwargs):
            raise Exception("backend down")

        monkeypatch.setattr(mlflow_fake, "search_runs", boom)
        t = MLflowTracker("sqlite:///f.db", "exp")
        t.start_run("r1")  # fresh experiment: search not reached
        t.end_run()
        t.start_run("r1")  # search raises -> fresh run, no crash
        t.end_run()
        assert len(mlflow_fake._stores["sqlite:///f.db"].runs) == 2


def test_build_tracker_rejects_native_owned_db_for_mlflow(
    mlflow_fake, tmp_path, monkeypatch
):
    """The reverse of the native backend's foreign-schema sniff: an image
    that GAINS the mlflow extra must not point MLflow at a DB the native
    backend created (auto would silently swap backends on the shared k8s
    URI)."""
    from types import SimpleNamespace

    import llmtrain_tpu.tracking as tracking

    db = tmp_path / "native.db"
    t = SqliteTracker(f"sqlite:///{db}", "exp")
    t.start_run("r1")
    t.end_run()

    monkeypatch.setattr(tracking, "_mlflow_available", lambda: True)
    cfg = SimpleNamespace(
        tracking_uri=f"sqlite:///{db}", experiment="exp", run_name=None,
        backend="auto",
    )
    with pytest.raises(RuntimeError, match="native SQLite backend"):
        tracking.build_tracker(cfg, "r2")
    # A fresh path (no file yet) is fine for mlflow.
    cfg2 = SimpleNamespace(
        tracking_uri=f"sqlite:///{tmp_path}/new.db", experiment="exp",
        run_name=None, backend="mlflow",
    )
    assert isinstance(tracking.build_tracker(cfg2, "r2"), MLflowTracker)


class TestBackendParity:
    """The same call sequence through both backends reads back identically."""

    def test_params_metrics_and_join_parity(self, mlflow_fake, tmp_path):
        art = tmp_path / "config.yaml"
        art.write_text("x: 1\n")
        db = tmp_path / "native.db"

        _drive(SqliteTracker(f"sqlite:///{db}", "exp"), "run-p", str(art))
        _drive(MLflowTracker("sqlite:///fake.db", "exp"), "run-p", str(art))

        # One run each, despite the relaunch — identical join semantics.
        native_runs = read_runs(db, "exp")
        fake_runs = list(mlflow_fake._stores["sqlite:///fake.db"].runs.values())
        assert len(native_runs) == len(fake_runs) == 1
        assert native_runs[0]["status"] == fake_runs[0].status == "FINISHED"

        # Params: identical keys AND identical stringified values.
        assert read_params(db, "run-p") == fake_runs[0].params

        # Metrics: identical (key, value, step) history, in order; NaN
        # round-trips on both (NULL column native, float('nan') fake).
        native = [
            (m["key"], m["value"], m["step"]) for m in read_metrics(db, "run-p")
        ]
        fake = [
            (m["key"], m["value"], m["step"]) for m in fake_runs[0].metrics
        ]
        assert len(native) == len(fake) == 5
        for (nk, nv, ns), (fk, fv, fs) in zip(native, fake, strict=True):
            assert nk == fk and ns == fs
            assert (math.isnan(nv) and math.isnan(fv)) or nv == fv

        # Both carry the framework run id as the join tag.
        assert fake_runs[0].tags["llmtrain.run_id"] == "run-p"
        with __import__("sqlite3").connect(db) as conn:
            tags = dict(conn.execute("SELECT key, value FROM tags"))
        assert tags["llmtrain.run_id"] == "run-p"
