"""Continuous-batching serving subsystem (llmtrain_tpu/serving/).

The contracts docs/serving.md promises, pinned:

* the paged KV pool's free-list/reservation invariants (admission is the
  ONLY place allocation can fail);
* batched paged decode emits token-ids **bitwise identical** to
  sequential single-request ``generate()`` for identical seeds/sampling
  params — greedy AND sampled (per-request temperature/top-k/top-p);
* the decode loop compiles once per shape bucket and the total program
  count stays within the configured budget;
* continuous batching holds >= 2 sequences in flight and retires
  finishers without draining the batch;
* the speculative scheduler policy is token-identical to ``generate()``
  under greedy sampling;
* the seeded open-loop load harness emits the p50/p95/p99 SLO block the
  telemetry report consumes.

Everything runs the tiny GPT (1-2 layers, 32-wide) so the tier-1 gate
stays cheap; the longer soak is ``@pytest.mark.slow`` (make
verify-serving runs it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.generation import generate
from llmtrain_tpu.models.gpt import GPT
from llmtrain_tpu.serving import (
    ContinuousBatchingScheduler,
    PagedDecodeEngine,
    PagedKVPool,
    ServeRequest,
    bucket_for,
    build_requests,
    percentiles,
    run_loadgen,
)
from llmtrain_tpu.telemetry.registry import MetricsRegistry

VOCAB = 32
BLOCK = 32


@pytest.fixture(scope="module")
def tiny_model():
    # 1 layer: the pool/engine/scheduler logic is layer-count-uniform
    # (per-layer cache vars are created by the same code path), and the
    # tier-1 gate runs this file serially against a tight time budget.
    model = GPT(
        vocab_size=VOCAB,
        block_size=BLOCK,
        d_model=32,
        n_layers=1,
        n_heads=2,
        d_ff=64,
        dropout=0.0,
        tie_embeddings=True,
    )
    params = nn_meta.unbox(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    )
    return model, params


def _engine(model, params, **kw):
    defaults = dict(
        block_tokens=8,
        max_batch_slots=4,
        prompt_buckets=[8, 16, BLOCK],
        batch_buckets=[2, 4],
    )
    return PagedDecodeEngine(model, params, **{**defaults, **kw})


def _drain(scheduler, requests, max_steps=500):
    """Run the scheduler loop inline (no thread) until every request is
    done — deterministic, and failures surface as assertions rather than
    a wedged background thread."""
    steps = 0
    while not all(r.done.is_set() for r in requests):
        scheduler.step()
        steps += 1
        assert steps < max_steps, "scheduler failed to finish the batch"
    return steps


def _reference(model, params, req: ServeRequest) -> list[int]:
    """What sequential single-request generate() emits for this request."""
    out = generate(
        model,
        params,
        req.prompt_ids[None, :],
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature,
        top_k=req.top_k,
        top_p=req.top_p,
        eos_token_id=req.eos_token_id,
        rng=jax.random.key(req.seed),
    )
    ref = [int(t) for t in np.asarray(out)[0, req.prompt_ids.shape[0]:]]
    if req.eos_token_id is not None and req.eos_token_id in ref:
        ref = ref[: ref.index(req.eos_token_id) + 1]
    return ref


class TestPagedKVPool:
    def test_sizing_and_reservation_accounting(self):
        pool = PagedKVPool(num_blocks=9, block_tokens=4)
        assert pool.blocks_needed(1) == 1
        assert pool.blocks_needed(4) == 1
        assert pool.blocks_needed(5) == 2
        assert pool.available_blocks == 8  # block 0 is the null block
        t1 = pool.try_reserve(10)  # 3 blocks
        assert t1 is not None and pool.available_blocks == 5
        t2 = pool.try_reserve(20)  # 5 blocks
        assert t2 is not None and pool.available_blocks == 0
        assert pool.try_reserve(1) is None  # admission is the only "no"
        pool.release(t1)
        assert pool.available_blocks == 3
        pool.release(t2)
        assert pool.available_blocks == 8
        assert pool.allocated_blocks == 0

    def test_grow_is_lazy_and_bounded_by_reservation(self):
        pool = PagedKVPool(num_blocks=9, block_tokens=4)
        table = pool.try_reserve(12)  # 3 blocks reserved
        assert table.allocated == 0  # nothing bound at admission
        pool.grow(table, 4)
        assert table.allocated == 1
        pool.grow(table, 4)  # idempotent
        assert table.allocated == 1
        pool.grow(table, 12)
        assert table.allocated == 3
        with pytest.raises(ValueError, match="admission sizing bug"):
            pool.grow(table, 13)  # beyond the reservation
        assert 0 not in table.blocks  # the null block is never handed out

    def test_release_guards_double_free(self):
        pool = PagedKVPool(num_blocks=5, block_tokens=2)
        table = pool.try_reserve(4)
        pool.grow(table, 4)
        pool.release(table)
        with pytest.raises(ValueError, match="released or foreign"):
            pool.release(table)
        with pytest.raises(ValueError, match="released or foreign"):
            pool.grow(table, 2)

    def test_padded_table_and_stats(self):
        pool = PagedKVPool(num_blocks=9, block_tokens=4)
        table = pool.try_reserve(8)
        pool.grow(table, 8)
        padded = table.padded(4)
        assert len(padded) == 4
        assert padded[2:] == [0, 0]  # null-block padding
        stats = pool.stats()
        assert stats["allocated_blocks"] == 2
        assert stats["reserved_blocks"] == 2
        assert stats["active_sequences"] == 1
        assert 0.0 < stats["utilization"] <= 1.0

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="num_blocks"):
            PagedKVPool(num_blocks=1, block_tokens=4)
        with pytest.raises(ValueError, match="block_tokens"):
            PagedKVPool(num_blocks=4, block_tokens=0)


class TestBuckets:
    def test_bucket_for(self):
        assert bucket_for(1, [2, 4, 8]) == 2
        assert bucket_for(3, [2, 4, 8]) == 4
        assert bucket_for(8, [2, 4, 8]) == 8
        with pytest.raises(ValueError, match="exceeds"):
            bucket_for(9, [2, 4, 8])

    def test_engine_bucket_validation(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match="prompt bucket"):
            _engine(model, params, prompt_buckets=[8, 2 * BLOCK])
        with pytest.raises(ValueError, match="must equal"):
            _engine(model, params, batch_buckets=[2, 3])


class TestBatchedParity:
    def test_greedy_bitwise_parity_mixed_lengths(self, tiny_model):
        """The acceptance contract: >= 2 sequences concurrently in flight,
        batched output token-ids bitwise identical to sequential
        generate(), compile count within the bucket budget."""
        model, params = tiny_model
        engine = _engine(model, params)
        scheduler = ContinuousBatchingScheduler(engine, registry=MetricsRegistry(None))
        rng = np.random.default_rng(7)
        requests = [
            ServeRequest(
                prompt_ids=rng.integers(0, VOCAB, size=tp).astype(np.int32),
                max_new_tokens=mnt,
                seed=int(rng.integers(0, 2**31 - 1)),
            )
            for tp, mnt in ((3, 6), (9, 4), (5, 8))
        ]
        for req in requests:
            scheduler.submit(req)
        _drain(scheduler, requests)

        assert scheduler.peak_occupancy >= 2  # genuinely batched
        for req in requests:
            assert req.finish_reason == "length"
            assert req.tokens == _reference(model, params, req)
        # Finished sequences returned their blocks to the pool.
        stats = engine.pool.stats()
        assert stats["active_sequences"] == 0
        assert stats["allocated_blocks"] == 0
        assert engine.compile_stats()["within_budget"]

    @pytest.mark.slow  # tier-1 pins greedy parity; `make verify-serving`
    # (and the k8s e2e's serve-bench) still run this sampled variant.
    def test_sampled_parity_per_request_knobs(self, tiny_model):
        """Sampled rows replay generate()'s exact per-request recipe even
        when temperature/top-k/top-p DIFFER across the in-flight batch."""
        model, params = tiny_model
        engine = _engine(model, params)
        scheduler = ContinuousBatchingScheduler(engine)
        requests = [
            ServeRequest(
                prompt_ids=np.asarray([1, 2, 3], np.int32),
                max_new_tokens=5,
                temperature=0.8,
                top_k=5,
                seed=11,
            ),
            ServeRequest(
                prompt_ids=np.asarray([4, 5, 6, 7, 8], np.int32),
                max_new_tokens=5,
                temperature=1.3,
                top_p=0.9,
                seed=22,
            ),
            ServeRequest(
                prompt_ids=np.asarray([9, 10], np.int32),
                max_new_tokens=5,
                temperature=0.0,  # greedy row in the same batch
                seed=33,
            ),
        ]
        for req in requests:
            scheduler.submit(req)
        _drain(scheduler, requests)
        assert scheduler.peak_occupancy >= 2
        for req in requests:
            assert req.tokens == _reference(model, params, req), req.request_id

    def test_eos_retires_without_draining_the_batch(self, tiny_model):
        """A finisher leaves per-step while the other sequence keeps
        decoding — continuous batching, not drain-and-refill."""
        model, params = tiny_model
        engine = _engine(model, params)
        scheduler = ContinuousBatchingScheduler(engine)
        short = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3], np.int32), max_new_tokens=2, seed=0
        )
        long = ServeRequest(
            prompt_ids=np.asarray([4, 5, 6], np.int32), max_new_tokens=7, seed=0
        )
        scheduler.submit(short)
        scheduler.submit(long)
        steps = 0
        while not short.done.is_set():
            scheduler.step()
            steps += 1
            assert steps < 50
        # The long request is still mid-flight after the short one retired.
        assert not long.done.is_set()
        assert len(scheduler._active) == 1
        _drain(scheduler, [long])
        assert short.tokens == _reference(model, params, short)
        assert long.tokens == _reference(model, params, long)

    def test_pool_exhaustion_queues_instead_of_evicting(self, tiny_model):
        """Admission control: a request the pool cannot guarantee stays
        queued (FIFO) and joins when a finisher frees its budget."""
        model, params = tiny_model
        # Pool sized for ONE worst-case sequence: 1 null + 2 blocks.
        engine = _engine(
            model, params, num_blocks=3, max_batch_slots=2, batch_buckets=[2]
        )
        scheduler = ContinuousBatchingScheduler(engine)
        a = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3, 4], np.int32),
            max_new_tokens=12,  # reserves ceil(16/8)=2 blocks — whole pool
            seed=0,
        )
        b = ServeRequest(
            prompt_ids=np.asarray([5, 6], np.int32), max_new_tokens=4, seed=0
        )
        scheduler.submit(a)
        scheduler.submit(b)
        scheduler.step()
        assert len(scheduler._active) == 1  # b is queued, not admitted
        assert scheduler.stats()["queue_depth"] == 1
        _drain(scheduler, [a, b])
        assert a.finish_reason == "length" and b.finish_reason == "length"
        assert b.tokens == _reference(model, params, b)

    def test_never_fitting_request_fails_instead_of_wedging_the_queue(
        self, tiny_model
    ):
        """A request this engine can NEVER serve (oversized for the
        context, the prompt buckets, or the whole pool) must fail alone —
        try_reserve can only say 'not yet', so without the
        validate_request guard it would sit at the FIFO head forever and
        starve everything behind it."""
        model, params = tiny_model
        # Pool capacity: 2 blocks = 16 positions total.
        engine = _engine(
            model, params, num_blocks=3, max_batch_slots=2, batch_buckets=[2]
        )
        assert "block_size" in engine.validate_request(4, BLOCK)
        # (the prompt-bucket reason is pinned at the HTTP boundary in
        # tests/test_serving.py — a 400, not a late 500)
        assert "pool" in engine.validate_request(4, 20)  # needs 3 > 2
        assert engine.validate_request(4, 12) is None  # exactly fits
        never = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3, 4], np.int32),
            max_new_tokens=20,  # 24 <= block_size, but needs 3 pool blocks
            seed=0,
        )
        behind = ServeRequest(
            prompt_ids=np.asarray([5, 6], np.int32), max_new_tokens=3, seed=0
        )
        scheduler = ContinuousBatchingScheduler(engine)
        scheduler.submit(never)
        scheduler.submit(behind)
        _drain(scheduler, [never, behind])
        assert never.finish_reason == "error"
        assert "pool" in never.error
        assert behind.finish_reason == "length"  # not starved


class TestFailureContainment:
    def test_abandonment_shedding_and_donated_cache_recovery(self, tiny_model):
        """One engine/scheduler, two containment contracts (a single test
        so tier-1 pays the prefill/decode compiles once):

        1. A waiter that gave up (HTTP 503 timeout, lapsed loadgen
           deadline) must not keep consuming device time: an abandoned
           queued request is skipped without prefill, an abandoned
           in-flight one is evicted with its blocks released, and traffic
           behind both is unaffected.
        2. The prefill/decode jits donate the cache, so a call failing at
           RUNTIME has already deleted it. The engine must rebuild a
           zeroed cache (not leave every later request dying on 'Array
           has been deleted'), the scheduler must fail the in-flight
           sequences whose KV went with it — and must itself survive the
           decode exception (it used to escape step() and kill the loop
           thread)."""
        model, params = tiny_model
        engine = _engine(model, params)
        scheduler = ContinuousBatchingScheduler(engine)

        # --- 1: abandoned requests are shed, queued and in flight.
        flying = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3], np.int32), max_new_tokens=8, seed=0
        )
        scheduler.submit(flying)
        scheduler.step()  # admitted: prefill + one decode advance
        assert not flying.done.is_set()
        tokens_at_shed = len(flying.tokens)
        assert tokens_at_shed >= 1
        queued = ServeRequest(
            prompt_ids=np.asarray([4, 5], np.int32), max_new_tokens=4, seed=0
        )
        survivor = ServeRequest(
            prompt_ids=np.asarray([6, 7], np.int32), max_new_tokens=4, seed=0
        )
        flying.abandon()
        queued.abandon()
        scheduler.submit(queued)
        scheduler.submit(survivor)
        _drain(scheduler, [flying, queued, survivor])
        assert flying.finish_reason == "abandoned"
        assert queued.finish_reason == "abandoned"
        assert queued.tokens == []  # never prefilled
        assert len(flying.tokens) == tokens_at_shed  # never advanced again
        assert survivor.tokens == _reference(model, params, survivor)
        stats = engine.pool.stats()
        assert stats["allocated_blocks"] == 0 and stats["active_sequences"] == 0

        # --- 2: runtime failure consumes the donated cache; recover.
        victim = ServeRequest(
            prompt_ids=np.asarray([1, 2, 3], np.int32), max_new_tokens=6, seed=0
        )
        scheduler.submit(victim)
        scheduler.step()
        assert len(scheduler._active) == 1
        real_decode = engine._decode_jit

        def exploding_decode(params_, cache, *rest):
            for leaf in jax.tree.leaves(cache):
                leaf.delete()  # what donation does on a runtime failure
            raise RuntimeError("injected device failure")

        engine._decode_jit = exploding_decode
        scheduler.step()  # must not raise
        assert victim.done.is_set() and victim.finish_reason == "error"
        assert "injected device failure" in victim.error
        assert engine.cache_epoch == 1  # rebuilt, not left deleted
        engine._decode_jit = real_decode
        after = ServeRequest(
            prompt_ids=np.asarray([4, 5, 6], np.int32), max_new_tokens=4, seed=1
        )
        scheduler.submit(after)
        _drain(scheduler, [after])
        assert after.tokens == _reference(model, params, after)
        assert engine.pool.stats()["allocated_blocks"] == 0


class TestCompileBudget:
    def test_decode_compiles_once_per_bucket(self, tiny_model):
        """Repeating a bucket shape must NOT grow the program count —
        unbounded recompilation is how a JAX server falls over."""
        model, params = tiny_model
        engine = _engine(model, params)
        scheduler = ContinuousBatchingScheduler(engine)

        def burst(seed):
            reqs = [
                ServeRequest(
                    prompt_ids=np.asarray([seed, 2, 3], np.int32),
                    max_new_tokens=3,
                    seed=seed,
                ),
                ServeRequest(
                    prompt_ids=np.asarray([seed, 5], np.int32),
                    max_new_tokens=3,
                    seed=seed,
                ),
            ]
            for r in reqs:
                scheduler.submit(r)
            _drain(scheduler, reqs)

        burst(1)
        first = engine.compile_stats()
        burst(2)  # same shapes again
        second = engine.compile_stats()
        assert second["prefill_programs"] == first["prefill_programs"]
        assert second["decode_programs"] == first["decode_programs"]
        assert second["within_budget"]
        assert (
            second["prefill_programs"] + second["decode_programs"]
            <= second["budget"]
        )
        # The used shapes are real buckets, not raw request shapes.
        assert set(second["prefill_shapes_used"]) <= set(engine.prompt_buckets)
        assert set(second["decode_shapes_used"]) <= set(engine.batch_buckets)


class TestSpeculativePolicy:
    def test_speculative_greedy_token_identical_to_generate(self, tiny_model):
        """Speculative decoding as a scheduler policy: same queue, same
        SLO accounting, token-identical output under greedy sampling."""
        model, params = tiny_model
        scheduler = ContinuousBatchingScheduler(
            None,
            policy="speculative",
            model=model,
            params=params,
            draft_model=model,  # self-draft: always accepted, still exact
            draft_params=params,
            gamma=3,
            registry=MetricsRegistry(None),
        )
        requests = [
            ServeRequest(
                prompt_ids=np.asarray([1, 2, 3], np.int32),
                max_new_tokens=6,
                seed=0,
            ),
            ServeRequest(
                prompt_ids=np.asarray([7, 8], np.int32),
                max_new_tokens=4,
                seed=0,
            ),
        ]
        for req in requests:
            scheduler.submit(req)
        _drain(scheduler, requests)
        for req in requests:
            assert req.finish_reason == "length"
            assert req.tokens == _reference(model, params, req)
        assert scheduler.stats()["policy"] == "speculative"
        assert scheduler.peak_occupancy == 1  # batch-1 by contract

    def test_policy_validation(self, tiny_model):
        model, params = tiny_model
        with pytest.raises(ValueError, match="unknown"):
            ContinuousBatchingScheduler(None, policy="warp")
        with pytest.raises(ValueError, match="PagedDecodeEngine"):
            ContinuousBatchingScheduler(None, policy="paged")
        with pytest.raises(ValueError, match="draft_model"):
            ContinuousBatchingScheduler(
                None, policy="speculative", model=model, params=params
            )


class TestLoadgen:
    def test_percentiles(self):
        assert percentiles([])["p50"] is None
        pct = percentiles([float(i) for i in range(1, 101)])
        assert pct["p50"] == 50.0
        assert pct["p95"] == 95.0
        assert pct["p99"] == 99.0
        assert pct["max"] == 100.0

    def test_build_requests_is_seeded(self):
        kw = dict(
            num_requests=5,
            seed=42,
            vocab_size=VOCAB,
            prompt_tokens_min=2,
            prompt_tokens_max=10,
            max_new_tokens=4,
        )
        a, b = build_requests(**kw), build_requests(**kw)
        for ra, rb in zip(a, b):
            assert np.array_equal(ra.prompt_ids, rb.prompt_ids)
            assert ra.seed == rb.seed
        assert any(
            not np.array_equal(ra.prompt_ids, rb.prompt_ids)
            for ra, rb in zip(a, build_requests(**{**kw, "seed": 43}))
        )

    def test_loadgen_slo_block_and_registry(self, tiny_model):
        """Open-loop seeded run → the serving report block: percentiles,
        throughput, occupancy >= 2 in flight, and llmtrain_serve_* gauges
        in the registry (the Prometheus surface)."""
        model, params = tiny_model
        engine = _engine(model, params)
        registry = MetricsRegistry(None)
        scheduler = ContinuousBatchingScheduler(engine, registry=registry).start()
        try:
            requests = build_requests(
                num_requests=6,
                seed=9,
                vocab_size=VOCAB,
                prompt_tokens_min=2,
                prompt_tokens_max=12,
                max_new_tokens=5,
            )
            # High rate => arrivals overlap => a real in-flight batch.
            block = run_loadgen(
                scheduler, requests, rate_rps=200.0, seed=9, timeout_sec=120.0
            )
        finally:
            scheduler.close()
        assert block["requests"]["completed"] == 6
        assert block["requests"]["failed"] == 0
        assert block["slo"]["ttft_ms"]["p50"] is not None
        assert block["slo"]["ttft_ms"]["p99"] >= block["slo"]["ttft_ms"]["p50"]
        assert block["slo"]["per_token_ms"]["p50"] is not None
        assert block["throughput"]["new_tokens"] == 6 * 5
        assert block["throughput"]["tokens_per_sec"] > 0
        assert block["occupancy"]["peak"] >= 2
        assert block["compile"]["within_budget"]
        assert block["arrival"]["process"] == "poisson-open-loop"
        latest = registry.latest()
        assert "serve/ttft_ms_p50" in latest
        assert "serve/tokens_per_sec" in latest
        assert latest["serve/peak_batch_occupancy"][0] >= 2
        assert registry.counters()["serve/requests"] == 6

    @pytest.mark.slow
    def test_loadgen_soak_parity(self, tiny_model):
        """Longer seeded soak (make verify-serving): every completion
        bitwise-identical to sequential generate()."""
        model, params = tiny_model
        engine = _engine(model, params, max_batch_slots=4)
        scheduler = ContinuousBatchingScheduler(engine).start()
        try:
            requests = build_requests(
                num_requests=24,
                seed=123,
                vocab_size=VOCAB,
                prompt_tokens_min=2,
                prompt_tokens_max=16,
                max_new_tokens=8,
            )
            block = run_loadgen(
                scheduler, requests, rate_rps=100.0, seed=123, timeout_sec=300.0
            )
        finally:
            scheduler.close()
        assert block["requests"]["completed"] == 24
        assert block["occupancy"]["peak"] >= 2
        for req in requests:
            assert req.tokens == _reference(model, params, req)
