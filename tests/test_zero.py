"""ZeRO-style cross-replica sharded optimizer state (``trainer.zero``).

Contracts under test (docs/perf.md "Sharded optimizer state",
parallel/sharding.py:opt_state_shardings):

* every optimizer-state leaf with a dim divisible by the data-parallel
  product is partitioned across the combined ``data``/``fsdp``/``expert``
  axes, derived from the param-inherited spec; scalars and indivisible
  leaves stay replicated with a one-time named warning;
* loss trajectories are BITWISE-identical zero on/off at stage 1,
  including host offload (the explicit round-trip fallback on this
  backend — no ``pinned_host`` memory space on CPU);
* checkpoints hold FULL host arrays regardless of the live sharding:
  zero→non-zero and non-zero→zero resumes continue the exact trajectory,
  as does an elastic world-size change with sharded state (device-subset
  emulation as in tests/test_elastic.py — this container's jax cannot run
  real multi-process collectives);
* report.json ``memory.opt_state_bytes_per_device`` measures the ~N_dp×
  reduction instead of claiming it.

Heavy multi-fit cases are ``@pytest.mark.slow``; ``make verify-zero``
runs everything.
"""

from __future__ import annotations

import json
import logging
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config import MeshConfig, RunConfig
from llmtrain_tpu.distributed import build_mesh
from llmtrain_tpu.parallel.sharding import (
    host_memory_kind,
    opt_state_shardings,
    state_shardings,
)
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training import CheckpointManager, Trainer


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


@contextmanager
def _capture_llmtrain_warnings():
    """Attach a handler DIRECTLY to the llmtrain logger: earlier suites
    (in-process cli.main runs) can leave its propagate flag off, which
    blinds caplog's root-logger handler in full-suite order."""
    from llmtrain_tpu.utils.logging import get_logger

    messages: list[str] = []

    class _Collector(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    handler = _Collector(level=logging.WARNING)
    lg = get_logger()
    lg.addHandler(handler)
    try:
        yield messages
    finally:
        lg.removeHandler(handler)


@contextmanager
def _visible_devices(n):
    """Emulate a world size by restricting the devices the Trainer sees
    (same pattern as tests/test_elastic.py)."""
    all_cpu = jax.devices("cpu")
    assert len(all_cpu) >= n
    real = jax.devices
    jax.devices = lambda *a, **k: all_cpu[:n]
    try:
        yield
    finally:
        jax.devices = real


def _trees_equal(a, b) -> bool:
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    return ta == tb and all(np.array_equal(x, y) for x, y in zip(la, lb))


# --------------------------------------------------------------------------
# sharding derivation (pure, no fits)
# --------------------------------------------------------------------------


class TestOptStateShardings:
    def test_param_spec_extended_with_free_dp_axes(self):
        """An fsdp-annotated moment leaf gains the free ``data`` axis on
        its first divisible dim; the fsdp mapping is kept, not replaced."""
        mesh = build_mesh(MeshConfig(data=2, fsdp=2), jax.devices("cpu")[:4])
        state = {
            "mu": nn_meta.Partitioned(
                jax.ShapeDtypeStruct((8, 16), jnp.float32), names=("embed", None)
            )
        }
        sh = opt_state_shardings(mesh, state)
        assert sh["mu"].shard_shape((8, 16)) == (2, 16)  # fsdp(2) x data(2)
        axes = sh["mu"].spec[0]
        assert "fsdp" in axes and "data" in axes

    def test_plain_leaf_shards_over_dp_product(self):
        mesh = build_mesh(MeshConfig(data=4), jax.devices("cpu")[:4])
        state = {"nu": jax.ShapeDtypeStruct((8, 6), jnp.float32)}
        sh = opt_state_shardings(mesh, state)
        assert sh["nu"].shard_shape((8, 6)) == (2, 6)

    def test_scalar_and_indivisible_leaves_stay_replicated(self):
        mesh = build_mesh(MeshConfig(data=4), jax.devices("cpu")[:4])
        state = {
            "count": jax.ShapeDtypeStruct((), jnp.int32),
            "odd": jax.ShapeDtypeStruct((5, 3), jnp.float32),
        }
        with _capture_llmtrain_warnings() as messages:
            sh = opt_state_shardings(mesh, state)
        assert sh["count"].shard_shape(()) == ()
        assert sh["odd"].shard_shape((5, 3)) == (5, 3)
        # One-time warning NAMES the leaf that lost the memory win.
        assert any("ZeRO" in m and "odd" in m for m in messages)

    def test_second_dim_used_when_first_is_indivisible(self):
        mesh = build_mesh(MeshConfig(data=4), jax.devices("cpu")[:4])
        state = {"v": jax.ShapeDtypeStruct((6, 8), jnp.float32)}
        sh = opt_state_shardings(mesh, state)
        assert sh["v"].shard_shape((6, 8)) == (6, 2)

    def test_single_device_mesh_is_identity(self):
        mesh = build_mesh(MeshConfig(data=1), jax.devices("cpu")[:1])
        state = {"mu": jax.ShapeDtypeStruct((8, 8), jnp.float32)}
        sh = opt_state_shardings(mesh, state)
        assert sh["mu"].shard_shape((8, 8)) == (8, 8)

    def test_adafactor_style_placeholder_stays_silent(self):
        """(1,) placeholders are structural noise — replicated, NO warning."""
        mesh = build_mesh(MeshConfig(data=4), jax.devices("cpu")[:4])
        with _capture_llmtrain_warnings() as messages:
            sh = opt_state_shardings(
                mesh, {"ph": jax.ShapeDtypeStruct((1,), jnp.float32)}
            )
        assert sh["ph"].shard_shape((1,)) == (1,)
        assert not any("'ph'" in m for m in messages)

    def test_no_pinned_host_memory_on_cpu(self):
        mesh = build_mesh(MeshConfig(data=4), jax.devices("cpu")[:4])
        assert host_memory_kind(mesh) is None  # forces the round-trip path


class TestStateShardingsRepair:
    def test_indivisible_param_spec_repairs_to_replicated_with_warning(self):
        """A sharded leaf whose dim the mapped axis product does not divide
        used to die at jit time with an opaque pjit error; now it stores
        replicated and warns ONCE, naming the leaf."""
        mesh = build_mesh(MeshConfig(data=2, tensor=2), jax.devices("cpu")[:4])
        tree = {
            "odd_vocab": nn_meta.Partitioned(
                jax.ShapeDtypeStruct((5, 4), jnp.float32), names=("vocab", None)
            )
        }
        with _capture_llmtrain_warnings() as messages:
            sh = state_shardings(mesh, tree)
            first = sum(
                "odd_vocab" in m and "REPLICATED" in m for m in messages
            )
            state_shardings(mesh, tree)  # re-derivation stays silent
            second = sum(
                "odd_vocab" in m and "REPLICATED" in m for m in messages
            )
        assert sh["odd_vocab"].shard_shape((5, 4)) == (5, 4)
        assert first == 1 and second == 1

    def test_divisible_param_spec_is_untouched(self):
        mesh = build_mesh(MeshConfig(data=2, tensor=2), jax.devices("cpu")[:4])
        tree = {
            "vocab": nn_meta.Partitioned(
                jax.ShapeDtypeStruct((8, 4), jnp.float32), names=("vocab", None)
            )
        }
        sh = state_shardings(mesh, tree)
        assert sh["vocab"].shard_shape((8, 4)) == (4, 4)


# --------------------------------------------------------------------------
# trainer-level parity on an emulated 4-device mesh
# --------------------------------------------------------------------------


def _zero_cfg(root, *, zero=False, stage=1, host_offload=False, micro=1, data=4):
    return RunConfig.model_validate(
        {
            "run": {"name": "zero", "seed": 11},
            "model": {
                "name": "gpt",
                "block_size": 8,
                "vocab_size": 256,
                "dropout": 0.0,
                "d_model": 32,
                "n_heads": 2,
                "d_ff": 64,
                "n_layers": 1,
                "extra": {"tokenizer": "byte"},
            },
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": 6,
                "micro_batch_size": micro,
                "grad_accum_steps": 1,
                "lr": 3e-3,
                "warmup_steps": 0,
                "log_every_steps": 1,
                "eval_every_steps": 100,
                "save_every_steps": 3,
                "zero": {
                    "enabled": zero,
                    "stage": stage,
                    "host_offload": host_offload,
                },
            },
            "distributed": {"mesh": {"data": data}},
            "mlflow": {"enabled": False},
            "output": {"root_dir": str(root)},
        }
    )


def _fit(root, run_dir, **kw):
    run_dir.mkdir(parents=True, exist_ok=True)
    ndev = kw.pop("ndev", 4)
    resume_from = kw.pop("resume_from", None)
    with _visible_devices(ndev):
        result = Trainer(_zero_cfg(root, **kw), run_dir, NullTracker(), None).fit(
            resume_from=resume_from
        )
    report = json.loads((run_dir / "report.json").read_text())
    return result, report


@pytest.fixture(scope="module")
def parity_runs(tmp_path_factory):
    """One zero-off and one zero-on fit over the same data/seed — the
    shared reference pair for the parity + round-trip tests."""
    tmp = tmp_path_factory.mktemp("zero_parity")
    out = {}
    for name, zero in (("off", False), ("on", True)):
        result, report = _fit(tmp, tmp / name, zero=zero)
        out[name] = {"dir": tmp / name, "result": result, "report": report}
    out["root"] = tmp
    return out


class TestZeroParity:
    @pytest.mark.slow
    def test_loss_trajectory_bitwise_identical_and_memory_measured(
        self, parity_runs
    ):
        # @slow with the rest of the fit-based contracts: tier-1 sits at
        # ~830s reported of the 870s kill budget, so every Trainer fit
        # belongs in `make verify-zero` (the sharding-derivation units
        # above stay tier-1).
        off, on = parity_runs["off"], parity_runs["on"]
        # Bitwise: every logged step's loss, not just the final one
        # (floats survive the JSON round-trip exactly via repr).
        assert off["report"]["loss"]["trajectory"] == on["report"]["loss"]["trajectory"]
        assert off["result"].final_loss == on["result"].final_loss
        # The final checkpoints hold identical FULL host arrays: the
        # sharded state gathers on save, so manifests stay topology- and
        # zero-portable.
        p_off = CheckpointManager.load(off["dir"] / "checkpoints" / "step_000006.ckpt")
        p_on = CheckpointManager.load(on["dir"] / "checkpoints" / "step_000006.ckpt")
        assert _trees_equal(p_off["params"], p_on["params"])
        assert _trees_equal(p_off["opt_state"], p_on["opt_state"])
        # Measured memory win: replicated keeps a full copy per device;
        # zero drops it ~4x on the 4-device mesh (scalar counts stay
        # replicated, hence the small remainder).
        mem_off = off["report"]["memory"]
        mem_on = on["report"]["memory"]
        assert mem_off["opt_state_bytes_per_device"] == mem_off["opt_state_bytes"]
        assert mem_on["opt_state_bytes"] == mem_off["opt_state_bytes"]
        ratio = mem_off["opt_state_bytes_per_device"] / mem_on["opt_state_bytes_per_device"]
        assert ratio > 3.5
        # report.md renders the accounting (observability satellite).
        md = (on["dir"] / "report.md").read_text()
        assert "optimizer state:" in md and "per device" in md

    @pytest.mark.slow
    def test_host_offload_roundtrip_bitwise_and_fully_host_resident(
        self, parity_runs, tmp_path
    ):
        result, report = _fit(
            parity_runs["root"], tmp_path / "offload", zero=True, host_offload=True
        )
        off = parity_runs["off"]
        assert report["loss"]["trajectory"] == off["report"]["loss"]["trajectory"]
        assert result.final_loss == off["result"].final_loss
        mem = report["memory"]
        assert mem["opt_state_bytes_host"] == mem["opt_state_bytes"]
        assert mem["opt_state_bytes_per_device"] == 0

    @pytest.mark.slow
    def test_stage2_reduce_scatter_tracks_replicated_closely(
        self, parity_runs, tmp_path
    ):
        """Stage 2 reassociates the global-norm sum (shard partials first):
        the documented contract is ~float-noise, not bitwise."""
        result, report = _fit(parity_runs["root"], tmp_path / "s2", zero=True, stage=2)
        off = parity_runs["off"]
        got = np.asarray([v for _, v in report["loss"]["trajectory"]])
        want = np.asarray([v for _, v in off["report"]["loss"]["trajectory"]])
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)
        mem = report["memory"]
        assert (
            mem["opt_state_bytes_per_device"]
            < mem["opt_state_bytes"] / 3.5
        )


# --------------------------------------------------------------------------
# checkpoint round-trips and elastic resume with sharded state
# --------------------------------------------------------------------------


class TestZeroCheckpointRoundTrip:
    @pytest.mark.slow
    def test_zero_to_nonzero_and_back_bitwise(self, parity_runs, tmp_path):
        """A zero-on checkpoint resumes with zero off (and vice versa) and
        lands bitwise on the uninterrupted runs — the payload is full host
        arrays, the live sharding is purely a placement decision."""
        off, on = parity_runs["off"], parity_runs["on"]
        root = parity_runs["root"]
        # zero-on save at step 3 -> resumed WITHOUT zero.
        res_a, _ = _fit(
            root,
            tmp_path / "on_to_off",
            zero=False,
            resume_from=str(on["dir"] / "checkpoints" / "step_000003.ckpt"),
        )
        assert res_a.resumed_from_step == 3
        assert res_a.final_loss == off["result"].final_loss
        final_a = CheckpointManager.load(
            tmp_path / "on_to_off" / "checkpoints" / "step_000006.ckpt"
        )
        final_off = CheckpointManager.load(
            off["dir"] / "checkpoints" / "step_000006.ckpt"
        )
        assert _trees_equal(final_a["params"], final_off["params"])
        assert _trees_equal(final_a["opt_state"], final_off["opt_state"])
        # zero-off save at step 3 -> resumed WITH zero (incl. offload).
        res_b, report_b = _fit(
            root,
            tmp_path / "off_to_on",
            zero=True,
            host_offload=True,
            resume_from=str(off["dir"] / "checkpoints" / "step_000003.ckpt"),
        )
        assert res_b.resumed_from_step == 3
        assert res_b.final_loss == off["result"].final_loss
        assert report_b["memory"]["opt_state_bytes_per_device"] == 0


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Topology-independent dataset (same rationale as tests/test_elastic.py:
    local_text sizes itself from file contents, not the batch topology)."""
    tmp = tmp_path_factory.mktemp("zero_corpus")
    f = tmp / "corpus.txt"
    f.write_text("sharded optimizer state pays for bigger models. " * 200)
    return tmp


def _elastic_zero_cfg(corpus_dir, root, *, micro, data, zero=True):
    cfg = _zero_cfg(root, zero=zero, micro=micro, data=data)
    return cfg.model_copy(
        update={
            "data": cfg.data.model_copy(
                update={
                    "name": "local_text",
                    "cache_dir": str(corpus_dir / "cache"),
                    "extra": {
                        "globs": [str(corpus_dir / "corpus.txt")],
                        "val_fraction": 0.1,
                    },
                }
            )
        }
    )


class TestZeroElasticResume:
    @pytest.mark.slow
    def test_ws4_to_ws2_and_back_with_sharded_state(self, corpus, tmp_path, caplog):
        """Elastic dp resize with ZeRO on both sides: the step-3 manifest
        saved on a data=4 mesh resumes on data=2 (micro scaled inversely,
        global micro-batch preserved) and continues the ws2 reference
        trajectory bitwise — and the reverse direction too. The restored
        full-host state lands as 2-way (resp. 4-way) shards through
        reshard_state's jit identity."""
        r4 = tmp_path / "ws4"
        r4.mkdir()
        with _visible_devices(4):
            ref4 = Trainer(
                _elastic_zero_cfg(corpus, tmp_path, micro=1, data=4),
                r4,
                NullTracker(),
                None,
            ).fit()
        r2 = tmp_path / "ws2"
        r2.mkdir()
        with _visible_devices(2):
            ref2 = Trainer(
                _elastic_zero_cfg(corpus, tmp_path, micro=2, data=2),
                r2,
                NullTracker(),
                None,
            ).fit()
            with caplog.at_level(logging.WARNING, logger="llmtrain"):
                down = Trainer(
                    _elastic_zero_cfg(corpus, tmp_path, micro=2, data=2),
                    None,
                    NullTracker(),
                    None,
                ).fit(resume_from=str(r4 / "checkpoints" / "step_000003.ckpt"))
        assert down.resumed_from_step == 3
        assert down.final_loss == ref2.final_loss
        assert ref2.final_loss == ref4.final_loss
        assert any("elastic resume" in r.message for r in caplog.records)
        with _visible_devices(4):
            up = Trainer(
                _elastic_zero_cfg(corpus, tmp_path, micro=1, data=4),
                None,
                NullTracker(),
                None,
            ).fit(resume_from=str(r2 / "checkpoints" / "step_000003.ckpt"))
        assert up.resumed_from_step == 3
        assert up.final_loss == ref4.final_loss
