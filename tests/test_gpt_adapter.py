"""GPT adapter tests (parity with reference tests/test_gpt_adapter.py):
loss vs a hand-rolled reference computation, tokenizer-derived vocab sizing,
batch validation errors."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.models.gpt import GPTAdapter

CFG = {
    "run": {"name": "t"},
    "model": {
        "name": "gpt",
        "block_size": 8,
        "d_model": 32,
        "n_layers": 1,
        "n_heads": 4,
        "d_ff": 64,
        "dropout": 0.0,
        "vocab_size": 50,
    },
    "data": {"name": "dummy_text"},
    "trainer": {"max_steps": 5, "warmup_steps": 0},
}


def _build():
    cfg = RunConfig.model_validate(CFG)
    adapter = GPTAdapter()
    model = adapter.build_model(cfg)
    params = adapter.init_params(model, cfg, jax.random.key(0))
    return cfg, adapter, model, params


def _batch(B=2, T=8, vocab=50, with_mask=True, seed=0):
    rng = np.random.default_rng(seed)
    input_ids = rng.integers(0, vocab, (B, T)).astype(np.int32)
    labels = rng.integers(0, vocab, (B, T)).astype(np.int32)
    batch = {"input_ids": jnp.asarray(input_ids), "labels": jnp.asarray(labels)}
    if with_mask:
        mask = np.ones((B, T), dtype=np.int32)
        mask[-1, T // 2 :] = 0
        batch["attention_mask"] = jnp.asarray(mask)
    return batch


def test_loss_matches_handrolled_cross_entropy():
    _, adapter, model, params = _build()
    batch = _batch()
    loss, metrics = adapter.compute_loss(model, params, batch)

    logits = np.asarray(
        model.apply(
            {"params": params},
            batch["input_ids"],
            attention_mask=batch["attention_mask"],
            deterministic=True,
        ),
        dtype=np.float64,
    )
    # Hand-rolled masked CE.
    shifted = logits - logits.max(axis=-1, keepdims=True)
    log_probs = shifted - np.log(np.exp(shifted).sum(axis=-1, keepdims=True))
    labels = np.asarray(batch["labels"])
    per_token = -np.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    mask = np.asarray(batch["attention_mask"], dtype=np.float64)
    expected = (per_token * mask).sum() / mask.sum()

    assert float(loss) == pytest.approx(expected, rel=1e-5)
    assert float(metrics["loss"]) == pytest.approx(float(loss))


def test_loss_without_mask_is_plain_mean():
    _, adapter, model, params = _build()
    batch = _batch(with_mask=False)
    loss, _ = adapter.compute_loss(model, params, batch)
    assert np.isfinite(float(loss))


def test_vocab_size_from_tokenizer(monkeypatch):
    class _FakeTok:
        n_vocab = 61

    adapter = GPTAdapter()
    monkeypatch.setattr(adapter, "build_tokenizer", lambda cfg: _FakeTok())
    cfg_dict = {**CFG, "model": {**CFG["model"], "vocab_size": None}}
    cfg = RunConfig.model_validate(cfg_dict)
    model = adapter.build_model(cfg)
    assert model.vocab_size == 61


def test_bad_tokenizer_vocab_raises(monkeypatch):
    class _BadTok:
        n_vocab = 0

    adapter = GPTAdapter()
    monkeypatch.setattr(adapter, "build_tokenizer", lambda cfg: _BadTok())
    cfg_dict = {**CFG, "model": {**CFG["model"], "vocab_size": None}}
    cfg = RunConfig.model_validate(cfg_dict)
    with pytest.raises(ValueError, match="n_vocab"):
        adapter.build_model(cfg)


def test_shape_validation():
    _, adapter, model, params = _build()
    bad = {
        "input_ids": jnp.zeros((2, 8), jnp.int32),
        "labels": jnp.zeros((2, 7), jnp.int32),
    }
    with pytest.raises(ValueError, match="same shape"):
        adapter.compute_loss(model, params, bad)

    bad2 = {
        "input_ids": jnp.zeros((8,), jnp.int32),
        "labels": jnp.zeros((8,), jnp.int32),
    }
    with pytest.raises(ValueError, match="2D"):
        adapter.compute_loss(model, params, bad2)

    bad3 = {
        "input_ids": jnp.zeros((2, 1), jnp.int32),
        "labels": jnp.zeros((2, 1), jnp.int32),
    }
    with pytest.raises(ValueError, match="length >= 2"):
        adapter.compute_loss(model, params, bad3)

    bad4 = {
        "input_ids": jnp.zeros((2, 8), jnp.float32),
        "labels": jnp.zeros((2, 8), jnp.int32),
    }
    with pytest.raises(ValueError, match="integer"):
        adapter.compute_loss(model, params, bad4)
