"""CLI integration tests (parity with reference tests/test_cli.py):
subcommands as subprocesses asserting exit codes, stdout JSON schema, and
artifacts on disk; resume via run dir and explicit ckpt path."""

import json
import os
import subprocess
import sys

import pytest
import yaml

CFG = {
    "schema_version": 1,
    "run": {"name": "cli-test", "seed": 5, "device": "cpu", "deterministic": True},
    "model": {
        "name": "dummy_gpt",
        "block_size": 8,
        "d_model": 48,
        "n_layers": 1,
        "n_heads": 2,
        "d_ff": 96,
        "dropout": 0.0,
        "vocab_size": 32,
    },
    "data": {"name": "dummy_text"},
    "trainer": {
        "max_steps": 6,
        "micro_batch_size": 2,
        "grad_accum_steps": 1,
        "lr": 0.003,
        "warmup_steps": 0,
        "log_every_steps": 3,
        "eval_every_steps": 3,
        "save_every_steps": 3,
    },
    "mlflow": {"enabled": False},
    "logging": {"level": "INFO", "json_output": True, "log_to_file": True},
    "output": {"root_dir": "runs"},
    # These tests pin CLI behavior; the end-of-fit cost-attribution lower
    # has its own e2e (test_profiling.py) and would add ~0.8s of cold
    # trace per train subprocess here.
    "telemetry": {"perf_attribution": False},
}


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _run(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "llmtrain_tpu", *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env=_env(),
        timeout=420,
    )


@pytest.fixture()
def workdir(tmp_path):
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(CFG))
    return tmp_path


class TestValidate:
    def test_valid(self, workdir):
        proc = _run(["validate", "--config", "config.yaml"], workdir)
        assert proc.returncode == 0
        assert "succeeded" in proc.stdout

    def test_invalid_exit_2_with_json_stderr(self, workdir):
        (workdir / "bad.yaml").write_text(yaml.safe_dump({**CFG, "bogus": 1}))
        proc = _run(["validate", "--config", "bad.yaml"], workdir)
        assert proc.returncode == 2
        err = json.loads(proc.stderr.strip().splitlines()[-1])
        assert "error" in err and err["errors"]

    def test_missing_file_exit_2(self, workdir):
        proc = _run(["validate", "--config", "nope.yaml"], workdir)
        assert proc.returncode == 2


class TestPrintConfig:
    def test_yaml_defaults_materialized(self, workdir):
        proc = _run(["print-config", "--config", "config.yaml"], workdir)
        assert proc.returncode == 0
        resolved = yaml.safe_load(proc.stdout)
        assert resolved["trainer"]["weight_decay"] == 0.1
        assert resolved["distributed"]["mesh"]["data"] == -1

    def test_json(self, workdir):
        proc = _run(["print-config", "--config", "config.yaml", "--json"], workdir)
        assert proc.returncode == 0
        assert json.loads(proc.stdout)["run"]["name"] == "cli-test"


class TestTrain:
    def test_full_train_json_summary_and_artifacts(self, workdir):
        proc = _run(
            ["train", "--config", "config.yaml", "--json", "--run-id", "run1"], workdir
        )
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        tr = summary["train_result"]
        assert tr["final_step"] == 6
        assert tr["final_loss"] > 0 and tr["first_step_loss"] > 0
        assert tr["parameter_count"] > 0
        assert summary["run_id"] == "run1"

        run_dir = workdir / "runs" / "run1"
        assert (run_dir / "config.yaml").is_file()
        assert (run_dir / "meta.json").is_file()
        assert (run_dir / "logs" / "train.log").is_file()
        ckpts = sorted(p.name for p in (run_dir / "checkpoints").glob("step_*.ckpt"))
        assert ckpts == ["step_000003.ckpt", "step_000006.ckpt"]
        # Each checkpoint ships with its sha-256 integrity sidecar.
        sidecars = sorted(p.name for p in (run_dir / "checkpoints").glob("*.sha256"))
        assert sidecars == [n + ".sha256" for n in ckpts]
        # --json keeps stdout pure JSON; logs went to stderr/file
        assert proc.stdout.strip().startswith("{")

    def test_dry_run(self, workdir):
        proc = _run(["train", "--config", "config.yaml", "--dry-run", "--json"], workdir)
        assert proc.returncode == 0, proc.stderr
        summary = json.loads(proc.stdout)
        assert summary["dry_run"] is True
        assert summary["dry_run_resolution"]["steps_executed"] == 5
        assert summary["dry_run_resolution"]["model_adapter"] == "dummy_gpt"

    def test_resume_by_run_dir(self, workdir):
        first = _run(["train", "--config", "config.yaml", "--json", "--run-id", "runA"], workdir)
        assert first.returncode == 0, first.stderr
        second = _run(
            [
                "train",
                "--config",
                "config.yaml",
                "--json",
                "--run-id",
                "runB",
                "--resume",
                str(workdir / "runs" / "runA" / "checkpoints" / "step_000003.ckpt"),
            ],
            workdir,
        )
        assert second.returncode == 0, second.stderr
        tr = json.loads(second.stdout)["train_result"]
        assert tr["resumed_from_step"] == 3

    def test_auto_resume_fresh_then_continue(self, workdir):
        short = {**CFG, "trainer": {**CFG["trainer"], "max_steps": 3}}
        (workdir / "short.yaml").write_text(yaml.safe_dump(short))
        first = _run(
            ["train", "--config", "short.yaml", "--json", "--run-id", "runAR", "--auto-resume"],
            workdir,
        )
        assert first.returncode == 0, first.stderr
        tr1 = json.loads(first.stdout)["train_result"]
        assert tr1["resumed_from_step"] is None and tr1["final_step"] == 3

        # Simulated preemption restart with a longer schedule: same run id,
        # dir already exists, training continues from the checkpoint.
        second = _run(
            ["train", "--config", "config.yaml", "--json", "--run-id", "runAR", "--auto-resume"],
            workdir,
        )
        assert second.returncode == 0, second.stderr
        tr2 = json.loads(second.stdout)["train_result"]
        assert tr2["resumed_from_step"] == 3
        assert tr2["final_step"] == 6

    def test_auto_resume_requires_run_id(self, workdir):
        proc = _run(["train", "--config", "config.yaml", "--auto-resume"], workdir)
        assert proc.returncode == 2
        assert "stable run id" in proc.stderr

    def test_auto_resume_excludes_resume(self, workdir):
        proc = _run(
            ["train", "--config", "config.yaml", "--auto-resume", "--resume", "x"],
            workdir,
        )
        assert proc.returncode == 2  # argparse mutual exclusion

    def test_unknown_adapter_exit_2(self, workdir):
        bad = {**CFG, "model": {**CFG["model"], "name": "nonexistent"}}
        (workdir / "bad.yaml").write_text(yaml.safe_dump(bad))
        proc = _run(["train", "--config", "bad.yaml"], workdir)
        assert proc.returncode == 2
        assert "nonexistent" in proc.stderr

    def test_train_failure_exit_1(self, workdir):
        bad = {**CFG, "trainer": {**CFG["trainer"], "max_steps": 6}}
        bad["data"] = {"name": "hf_text"}  # no dataset_name -> setup raises
        (workdir / "bad.yaml").write_text(yaml.safe_dump(bad))
        proc = _run(["train", "--config", "bad.yaml", "--json"], workdir)
        assert proc.returncode == 1
        err = json.loads(proc.stderr.strip().splitlines()[-1])
        assert "training failed" in err["error"]


class TestAverageCheckpoints:
    def test_soup_is_the_uniform_average_and_resumable(self, workdir):
        """average-checkpoints writes the exact param mean of the inputs
        as a standard resumable step-0 checkpoint."""
        import numpy as np

        first = _run(["train", "--config", "config.yaml", "--json",
                      "--run-id", "runAV"], workdir)
        assert first.returncode == 0, first.stderr
        ckpt_dir = workdir / "runs" / "runAV" / "checkpoints"
        files = sorted(ckpt_dir.glob("step_*.ckpt"))
        assert len(files) >= 2

        proc = _run(
            ["average-checkpoints", "--config", "config.yaml", "--inputs",
             str(ckpt_dir), "--last-k", "2", "--output", "soup", "--json"],
            workdir,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert len(out["inputs"]) == 2

        from flax import serialization

        def params_of(path):
            payload = serialization.msgpack_restore(path.read_bytes())
            return payload["params"]

        import jax

        a, b = params_of(files[-2]), params_of(files[-1])
        soup = params_of(workdir / "soup" / "step_000000.ckpt")
        want = jax.tree.map(lambda x, y: (np.asarray(x, np.float64) + y) / 2, a, b)
        for got, exp in zip(jax.tree.leaves(soup), jax.tree.leaves(want)):
            np.testing.assert_allclose(
                np.asarray(got, np.float64), exp, atol=1e-6
            )

        # The soup resumes/evals like any checkpoint.
        ev = _run(["eval", "--config", "config.yaml", "--from", "soup",
                   "--json"], workdir)
        assert ev.returncode == 0, ev.stderr
        assert np.isfinite(json.loads(ev.stdout)["metrics"]["val/loss"])

    def test_needs_two_inputs(self, workdir):
        proc = _run(
            ["average-checkpoints", "--config", "config.yaml", "--inputs",
             "onlyone", "--output", "soup2"],
            workdir,
        )
        assert proc.returncode == 2
        assert "at least 2" in proc.stderr


class TestGenerate:
    def test_generate_from_trained_run(self, workdir):
        first = _run(["train", "--config", "config.yaml", "--json", "--run-id", "runG"], workdir)
        assert first.returncode == 0, first.stderr
        proc = _run(
            [
                "generate",
                "--config",
                "config.yaml",
                "--from",
                "runG",
                "--prompt-ids",
                "1,2,3",
                "--max-new-tokens",
                "4",
                "--temperature",
                "0",
                "--json",
            ],
            workdir,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["step"] == 6
        assert out["prompt_ids"] == [1, 2, 3]
        assert len(out["completion_ids"]) == 4  # newly generated only
        assert out["output_ids"] == out["prompt_ids"] + out["completion_ids"]
        assert all(0 <= t < CFG["model"]["vocab_size"] for t in out["output_ids"])
        # dummy adapter has no tokenizer -> no decoded text
        assert out["text"] is None

    def test_generate_quantized_int8(self, workdir):
        """--quantize int8 decodes on QuantizedArray weights end to end
        (ops/quant.py): same output contract, valid token range."""
        first = _run(["train", "--config", "config.yaml", "--json",
                      "--run-id", "runQ"], workdir)
        assert first.returncode == 0, first.stderr
        proc = _run(
            ["generate", "--config", "config.yaml", "--from", "runQ",
             "--prompt-ids", "1,2,3", "--max-new-tokens", "4",
             "--temperature", "0", "--quantize", "int8", "--json"],
            workdir,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert len(out["completion_ids"]) == 4
        assert all(0 <= t < CFG["model"]["vocab_size"] for t in out["output_ids"])

    def test_generate_greedy_is_deterministic(self, workdir):
        first = _run(["train", "--config", "config.yaml", "--json", "--run-id", "runH"], workdir)
        assert first.returncode == 0, first.stderr
        args = [
            "generate",
            "--config",
            "config.yaml",
            "--from",
            str(workdir / "runs" / "runH" / "checkpoints"),
            "--prompt-ids",
            "5,6",
            "--max-new-tokens",
            "3",
            "--temperature",
            "0",
            "--json",
        ]
        a, b = _run(args, workdir), _run(args, workdir)
        assert a.returncode == 0 and b.returncode == 0, a.stderr + b.stderr
        assert json.loads(a.stdout)["completion_ids"] == json.loads(b.stdout)["completion_ids"]

    def test_decode_param_dtype_cast_and_optout(self, workdir):
        """bf16-compute models decode from bf16 weights by default (half the
        weight bandwidth, tools/diag_decode.py attribution); --decode-param-
        dtype param keeps the checkpoint's f32 master params."""
        cfg = {
            **CFG,
            "model": {
                "name": "gpt",
                "block_size": 8,
                "d_model": 32,
                "n_layers": 1,
                "n_heads": 2,
                "d_ff": 64,
                "dropout": 0.0,
                "vocab_size": 64,
                "dtype": "bfloat16",
                "param_dtype": "float32",
                "extra": {"tokenizer": "byte"},
            },
        }
        (workdir / "bf16.yaml").write_text(yaml.safe_dump(cfg))
        first = _run(
            ["train", "--config", "bf16.yaml", "--json", "--run-id", "runDD"],
            workdir,
        )
        assert first.returncode == 0, first.stderr
        base = [
            "generate", "--config", "bf16.yaml", "--from", "runDD",
            "--prompt-ids", "1,2", "--max-new-tokens", "3",
            "--temperature", "0", "--json",
        ]
        cast = _run(base, workdir)
        assert cast.returncode == 0, cast.stderr
        assert "cast floating params to bfloat16" in cast.stderr
        kept = _run([*base, "--decode-param-dtype", "param"], workdir)
        assert kept.returncode == 0, kept.stderr
        assert "cast floating params" not in kept.stderr
        # Both modes produce a full-length completion from the same ckpt.
        for proc in (cast, kept):
            assert len(json.loads(proc.stdout)["completion_ids"]) == 3

    @pytest.mark.slow  # budget: tier-1 siblings test_generate_greedy_is_deterministic + test_speculative greedy exactness
    def test_speculative_generate_matches_plain_greedy(self, workdir):
        """--draft-config/--draft-from: greedy speculative output through
        the CLI is bit-identical to the plain greedy path."""
        tgt = {
            **CFG,
            "model": {
                "name": "gpt", "block_size": 32, "d_model": 32,
                "n_layers": 2, "n_heads": 2, "d_ff": 64, "dropout": 0.0,
                "vocab_size": 32,
            },
        }
        drf = {**tgt, "model": {**tgt["model"], "n_layers": 1, "d_model": 16,
                                "d_ff": 32}}
        (workdir / "tgt.yaml").write_text(yaml.safe_dump(tgt))
        (workdir / "drf.yaml").write_text(yaml.safe_dump(drf))
        for cfg_name, rid in (("tgt.yaml", "runT"), ("drf.yaml", "runD")):
            proc = _run(["train", "--config", cfg_name, "--json",
                         "--run-id", rid], workdir)
            assert proc.returncode == 0, proc.stderr
        base = ["generate", "--config", "tgt.yaml", "--from", "runT",
                "--prompt-ids", "1,2,3", "--max-new-tokens", "8",
                "--temperature", "0", "--json"]
        plain = _run(base, workdir)
        assert plain.returncode == 0, plain.stderr
        spec = _run([*base, "--draft-config", "drf.yaml", "--draft-from",
                     "runD", "--gamma", "3"], workdir)
        assert spec.returncode == 0, spec.stderr
        assert (
            json.loads(spec.stdout)["completion_ids"]
            == json.loads(plain.stdout)["completion_ids"]
        )

    def test_generate_logprobs(self, workdir):
        first = _run(["train", "--config", "config.yaml", "--json",
                      "--run-id", "runLP"], workdir)
        assert first.returncode == 0, first.stderr
        proc = _run(
            ["generate", "--config", "config.yaml", "--from", "runLP",
             "--prompt-ids", "1,2", "--max-new-tokens", "4",
             "--temperature", "0", "--logprobs", "--json"],
            workdir,
        )
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert len(out["logprobs"]) == 4
        assert all(lp <= 0.0 for lp in out["logprobs"])

    def test_logprobs_rejected_with_speculative(self, workdir):
        proc = _run(
            ["generate", "--config", "config.yaml", "--from", "x",
             "--prompt-ids", "1", "--logprobs", "--draft-config",
             "config.yaml", "--draft-from", "y"],
            workdir,
        )
        assert proc.returncode == 2
        assert "logprobs" in proc.stderr

    @pytest.mark.slow  # ~14s: CLI speculative parity stays tier-1 via
    # test_speculative_generate_matches_plain_greedy; this adds only the
    # prompts-file/length-group dimension on top of the same path.
    def test_speculative_prompts_file_matches_plain(self, workdir):
        """The per-row speculative loop over a prompts file (different
        prompt lengths → separate length groups) matches the plain
        batched path's completions exactly."""
        tgt = {
            **CFG,
            "model": {
                "name": "gpt", "block_size": 32, "d_model": 32,
                "n_layers": 2, "n_heads": 2, "d_ff": 64, "dropout": 0.0,
                "vocab_size": 257, "extra": {"tokenizer": "byte"},
            },
        }
        drf = {**tgt, "model": {**tgt["model"], "n_layers": 1}}
        (workdir / "tgt.yaml").write_text(yaml.safe_dump(tgt))
        (workdir / "drf.yaml").write_text(yaml.safe_dump(drf))
        for cfg_name, rid in (("tgt.yaml", "runPT"), ("drf.yaml", "runPD")):
            proc = _run(["train", "--config", cfg_name, "--json",
                         "--run-id", rid], workdir)
            assert proc.returncode == 0, proc.stderr
        (workdir / "prompts.txt").write_text("hello\nworld wide\n")
        base = ["generate", "--config", "tgt.yaml", "--from", "runPT",
                "--prompts-file", "prompts.txt", "--max-new-tokens", "5",
                "--temperature", "0", "--json"]
        plain = _run(base, workdir)
        assert plain.returncode == 0, plain.stderr
        spec = _run([*base, "--draft-config", "drf.yaml", "--draft-from",
                     "runPD", "--gamma", "2"], workdir)
        assert spec.returncode == 0, spec.stderr
        p_res = json.loads(plain.stdout)["results"]
        s_res = json.loads(spec.stdout)["results"]
        assert [r["completion_ids"] for r in p_res] == [
            r["completion_ids"] for r in s_res
        ]

    def test_speculative_flags_must_pair(self, workdir):
        proc = _run(
            ["generate", "--config", "config.yaml", "--from", "nope",
             "--prompt-ids", "1", "--draft-config", "config.yaml"],
            workdir,
        )
        assert proc.returncode == 2
        assert "together" in proc.stderr

    def test_generate_eos_token_stops_early(self, workdir):
        """--eos-token-id is wired through to generate(): once the EOS token
        is produced, the rest of the completion is EOS-filled (ADVICE r1)."""
        first = _run(["train", "--config", "config.yaml", "--json", "--run-id", "runE"], workdir)
        assert first.returncode == 0, first.stderr
        base = [
            "generate",
            "--config",
            "config.yaml",
            "--from",
            "runE",
            "--prompt-ids",
            "1,2,3",
            "--max-new-tokens",
            "5",
            "--temperature",
            "0",
            "--json",
        ]
        plain = _run(base, workdir)
        assert plain.returncode == 0, plain.stderr
        eos = json.loads(plain.stdout)["completion_ids"][0]
        stopped = _run(base + ["--eos-token-id", str(eos)], workdir)
        assert stopped.returncode == 0, stopped.stderr
        completion = json.loads(stopped.stdout)["completion_ids"]
        # Greedy decode reproduces the same first token, which is now EOS;
        # every subsequent slot must be EOS-filled.
        assert completion[0] == eos
        assert all(t == eos for t in completion)

    def test_generate_missing_checkpoint_exit_1(self, workdir):
        proc = _run(
            [
                "generate",
                "--config",
                "config.yaml",
                "--from",
                "no-such-run",
                "--prompt-ids",
                "1",
            ],
            workdir,
        )
        assert proc.returncode == 1
        assert "generation failed" in proc.stderr

    def test_generate_prompt_without_tokenizer_exit_1(self, workdir):
        first = _run(["train", "--config", "config.yaml", "--json", "--run-id", "runI"], workdir)
        assert first.returncode == 0, first.stderr
        proc = _run(
            ["generate", "--config", "config.yaml", "--from", "runI", "--prompt", "hi"],
            workdir,
        )
        assert proc.returncode == 1
        assert "prompt-ids" in proc.stderr


class TestPresets:
    def test_all_presets_validate(self, workdir):
        import pathlib

        presets = pathlib.Path(__file__).resolve().parent.parent / "configs" / "presets"
        assert presets.is_dir()
        paths = [str(p) for p in sorted(presets.glob("*.yaml"))]
        assert paths
        # One subprocess for ALL presets: each `validate` still goes
        # through the real CLI entrypoint (argparse, exit codes), but the
        # interpreter + jax import cost is paid once, not per preset —
        # at ~0.75s a spawn, per-preset subprocesses were >20s of tier-1.
        driver = (
            "import sys\n"
            "from llmtrain_tpu.cli import main\n"
            "bad = [p for p in sys.argv[1:]\n"
            "       if main(['validate', '--config', p]) != 0]\n"
            "print('INVALID PRESETS:', bad)\n"
            "sys.exit(1 if bad else 0)\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", driver, *paths],
            capture_output=True,
            text=True,
            cwd=workdir,
            env=_env(),
            timeout=420,
        )
        assert proc.returncode == 0, f"{proc.stdout}\n{proc.stderr}"
