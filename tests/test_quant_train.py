"""Quantized training path (ops/quant.py training section, docs/perf.md
"Quantized training") + the bench scenario-matrix gate rules.

Tier-1 keeps to pure units — per-channel scale/STE-vjp behavior, the
quant_dot_general modes against plain ``lax.dot_general``, QuantDense's
drop-in contract, knob validation + the fp8 capability fallback, the
chunked-CE auto-select rule, and tools/perf_gate.py's matrix comparison
core. Everything that runs train steps or compiles a full program (the
int8-vs-f32 loss-parity fit, the non-finite-guard fit, the checkpoint/
elastic-resume round-trip, the attribution pin) is ``@pytest.mark.slow``
under ``make verify-quant``.
"""

from __future__ import annotations

import importlib.util
import logging
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.models.gpt import GPTAdapter
from llmtrain_tpu.ops import quant
from llmtrain_tpu.ops.quant import (
    MATMUL_PRECISIONS,
    QuantDense,
    fake_quant,
    fp8_supported,
    quant_dot_general,
    quantize_array,
    resolve_matmul_precision,
)
from llmtrain_tpu.registry import initialize_registries

REPO = Path(__file__).resolve().parents[1]

# docs/perf.md "Parity band": the documented N-step loss-trajectory rtols.
PARITY_RTOL = {"int8": 0.05, "int8_act": 0.05, "fp8": 0.10}

_DN = (((1,), (0,)), ((), ()))  # plain (M,K)x(K,N) contraction


@pytest.fixture(scope="module", autouse=True)
def _registries():
    initialize_registries()


def _gpt_cfg(extra: dict, *, vocab: int = 256, seq: int = 16, root=None, **trainer_kw):
    doc = {
        "run": {"name": "quant-test", "seed": 7, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": seq,
            "d_model": 32,
            "n_layers": 2,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": vocab,
            "extra": extra,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "micro_batch_size": 4,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
            **trainer_kw,
        },
        "mlflow": {"enabled": False},
    }
    if root is not None:
        doc["output"] = {"root_dir": str(root)}
    return RunConfig.model_validate(doc)


# --------------------------------------------------------------------------
# per-channel scales + straight-through fake_quant
# --------------------------------------------------------------------------


class TestScalesAndSTE:
    def test_per_channel_scales_and_zero_channel_guard(self):
        w = np.random.default_rng(0).normal(size=(8, 6)).astype(np.float32)
        w[:, 2] = 0.0  # all-zero output channel
        qa = quantize_array(jnp.asarray(w), reduce_axes=(0,))
        scale = np.asarray(qa.scale)
        assert scale.shape == (1, 6)  # keepdims: one scale per output unit
        # amax/127 per channel; the zero channel gets the 1.0 guard so the
        # round-trip is exact and gradients stay finite.
        expect = np.abs(w).max(axis=0) / 127.0
        np.testing.assert_allclose(scale[0, [0, 1, 3, 4, 5]], expect[[0, 1, 3, 4, 5]], rtol=1e-6)
        assert scale[0, 2] == 1.0
        deq = np.asarray(qa.dequantize())
        np.testing.assert_array_equal(deq[:, 2], 0.0)
        # symmetric int8: error bounded by half a step per channel
        assert np.all(np.abs(deq - w) <= scale / 2 + 1e-7)

    def test_fake_quant_straight_through_gradient(self):
        w = jnp.asarray(np.random.default_rng(1).normal(size=(5, 4)).astype(np.float32))
        grads = jax.grad(lambda x: jnp.sum(fake_quant(x, (0,))))(w)
        # STE: backward is the exact identity onto the f32 master weights.
        np.testing.assert_array_equal(np.asarray(grads), np.ones_like(np.asarray(w)))


# --------------------------------------------------------------------------
# quant_dot_general modes
# --------------------------------------------------------------------------


class TestQuantDotGeneral:
    def setup_method(self):
        rng = np.random.default_rng(2)
        self.lhs = jnp.asarray(rng.normal(size=(6, 16)).astype(np.float32))
        self.rhs = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
        self.ref = lax.dot_general(self.lhs, self.rhs, _DN)

    def test_f32_mode_is_stock_path(self):
        # None -> flax uses its default lax.dot_general: bit-identical
        # builds for everyone who never sets the knob.
        assert quant_dot_general("f32") is None

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="int4"):
            quant_dot_general("int4")

    @pytest.mark.parametrize("mode", ["int8", "int8_act"])
    def test_int8_modes_close_with_finite_grads(self, mode):
        dg = quant_dot_general(mode)
        out = dg(self.lhs, self.rhs, _DN)
        rel = float(jnp.max(jnp.abs(out - self.ref)) / jnp.max(jnp.abs(self.ref)))
        assert rel < 0.05, f"{mode} dot drifted {rel:.4f} from f32"
        gl, gr = jax.grad(lambda a, b: jnp.sum(dg(a, b, _DN) ** 2), argnums=(0, 1))(
            self.lhs, self.rhs
        )
        assert bool(jnp.all(jnp.isfinite(gl))) and bool(jnp.all(jnp.isfinite(gr)))

    @pytest.mark.skipif(not fp8_supported(), reason="backend has no fp8 dot")
    def test_fp8_forward_close_backward_exact_f32(self):
        dg = quant_dot_general("fp8")
        out = dg(self.lhs, self.rhs, _DN)
        rel = float(jnp.max(jnp.abs(out - self.ref)) / jnp.max(jnp.abs(self.ref)))
        assert rel < 0.10
        # The backward replays an exact f32 dot_general VJP on the saved
        # operands — gradients must MATCH the plain dot's, not just be
        # finite (an fp8 transpose would be neither).
        loss_q = lambda a, b: jnp.sum(dg(a, b, _DN) * 0.5)  # noqa: E731
        loss_f = lambda a, b: jnp.sum(lax.dot_general(a, b, _DN) * 0.5)  # noqa: E731
        gq = jax.grad(loss_q, argnums=(0, 1))(self.lhs, self.rhs)
        gf = jax.grad(loss_f, argnums=(0, 1))(self.lhs, self.rhs)
        for q, f in zip(gq, gf):
            np.testing.assert_array_equal(np.asarray(q), np.asarray(f))

    def test_jit_matches_eager(self):
        dg = quant_dot_general("int8")
        eager = dg(self.lhs, self.rhs, _DN)
        jitted = jax.jit(lambda a, b: dg(a, b, _DN))(self.lhs, self.rhs)
        np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), rtol=1e-6)


# --------------------------------------------------------------------------
# QuantDense drop-in contract
# --------------------------------------------------------------------------


class TestQuantDense:
    def test_same_param_tree_and_close_outputs(self):
        from flax import linen as nn

        x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 16)).astype(np.float32))
        dense = nn.Dense(8)
        qdense = QuantDense(8, matmul_precision="int8")
        pd = dense.init(jax.random.key(0), x)
        pq = qdense.init(jax.random.key(0), x)
        # Checkpoint compatibility both ways: identical tree AND identical
        # f32 master values (init never sees the quantizer).
        assert jax.tree.structure(pd) == jax.tree.structure(pq)
        for a, b in zip(jax.tree.leaves(pd), jax.tree.leaves(pq)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out_d = dense.apply(pd, x)
        out_q = qdense.apply(pd, x)  # Dense params applied through QuantDense
        rel = float(jnp.max(jnp.abs(out_d - out_q)) / jnp.max(jnp.abs(out_d)))
        assert 0.0 < rel < 0.05  # quantized (so not bitwise) but close

    def test_f32_mode_bitwise_equals_dense(self):
        from flax import linen as nn

        x = jnp.asarray(np.random.default_rng(4).normal(size=(4, 16)).astype(np.float32))
        dense = nn.Dense(8)
        params = dense.init(jax.random.key(0), x)
        out_f32 = QuantDense(8, matmul_precision="f32").apply(params, x)
        np.testing.assert_array_equal(np.asarray(dense.apply(params, x)), np.asarray(out_f32))


# --------------------------------------------------------------------------
# knob validation + fp8 capability fallback
# --------------------------------------------------------------------------


class TestKnobValidation:
    def test_resolve_rejects_unknown_mode(self):
        with pytest.raises(ValueError, match="int4"):
            resolve_matmul_precision("int4")

    def test_adapter_rejects_unknown_mode(self):
        cfg = _gpt_cfg({"matmul_precision": "bf8"})
        with pytest.raises(ValueError, match="bf8"):
            GPTAdapter().build_model(cfg)

    @pytest.mark.parametrize("mode", MATMUL_PRECISIONS)
    def test_all_documented_modes_build(self, mode):
        model = GPTAdapter().build_model(_gpt_cfg({"matmul_precision": mode}))
        assert model.matmul_precision in MATMUL_PRECISIONS

    def test_fp8_falls_back_to_f32_with_one_warning(self, monkeypatch, caplog):
        monkeypatch.setattr(quant, "fp8_supported", lambda: False)
        monkeypatch.setattr(quant, "_FALLBACK_WARNED", set())
        with caplog.at_level(logging.WARNING, logger="llmtrain_tpu.ops.quant"):
            assert resolve_matmul_precision("fp8") == "f32"
            assert resolve_matmul_precision("fp8") == "f32"
        warnings = [r for r in caplog.records if "fp8" in r.getMessage()]
        assert len(warnings) == 1  # once per process, not per matmul


# --------------------------------------------------------------------------
# chunked-CE auto-select (model.extra.ce_auto_vocab)
# --------------------------------------------------------------------------


class TestChunkedCEAutoSelect:
    def test_large_vocab_auto_selects_chunked(self):
        model = GPTAdapter().build_model(_gpt_cfg({}, vocab=40000))
        assert model.loss_impl == "chunked_ce"

    def test_small_vocab_stays_dense(self):
        model = GPTAdapter().build_model(_gpt_cfg({}, vocab=256))
        assert model.loss_impl == "dense"

    def test_explicit_dense_wins_at_large_vocab(self):
        model = GPTAdapter().build_model(_gpt_cfg({"loss_impl": "dense"}, vocab=40000))
        assert model.loss_impl == "dense"

    def test_ce_auto_vocab_override(self):
        model = GPTAdapter().build_model(_gpt_cfg({"ce_auto_vocab": 128}, vocab=256))
        assert model.loss_impl == "chunked_ce"


# --------------------------------------------------------------------------
# perf_gate matrix comparison core (tools/perf_gate.py)
# --------------------------------------------------------------------------


def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate_quant", REPO / "tools" / "perf_gate.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round(mat: dict, skipped: list | None = None) -> list[dict]:
    return [
        {
            "metric": "tokens_per_sec_per_chip",
            "value": 100.0,
            "detail": {"model": "gpt", "attention": "dense", "batch": 4},
            "matrix": mat,
            "skipped": skipped or [],
        }
    ]


def _mline(tps: float, flops: float = 5.0e8, **kw) -> dict:
    return {"tokens_per_sec": tps, "attribution": {"flops": flops}, **kw}


class TestPerfGateMatrix:
    KEY = "dense|short|dense_ce|f32"

    def test_genuine_regression_gates(self):
        gate = _load_perf_gate()
        verdict = gate.compare_matrix(
            _round({self.KEY: _mline(1000.0)}), _round({self.KEY: _mline(400.0)})
        )
        assert verdict["regressions"]

    def test_new_key_never_gates(self):
        gate = _load_perf_gate()
        verdict = gate.compare_matrix(
            _round({self.KEY: _mline(1000.0)}),
            _round({self.KEY: _mline(1000.0), "dense|short|dense_ce|int8": _mline(1.0)}),
        )
        assert not verdict["regressions"]
        assert any("new scenario" in n for n in verdict["notes"])

    def test_removed_key_warns_unless_budget_skipped(self):
        gate = _load_perf_gate()
        old = _round({self.KEY: _mline(1000.0)})
        verdict = gate.compare_matrix(old, _round({}))
        assert not verdict["regressions"]
        assert any("WARNING scenario removed" in n for n in verdict["notes"])
        verdict = gate.compare_matrix(
            old, _round({}, skipped=[{"scenario": self.KEY, "reason": "budget"}])
        )
        assert not any("WARNING" in n for n in verdict["notes"])
        assert any("skipped for budget" in n for n in verdict["notes"])

    def test_degraded_parity_line_skipped_not_gated(self):
        gate = _load_perf_gate()
        bad = _mline(
            400.0,
            degraded=True,
            fallback="loss parity vs f32 failed: max rel diff 0.2 > rtol 0.05",
            parity={"rtol": 0.05, "max_rel_diff": 0.2, "ok": False},
        )
        verdict = gate.compare_matrix(
            _round({self.KEY: _mline(1000.0)}), _round({self.KEY: bad})
        )
        assert not verdict["regressions"] and verdict["skipped"]

    def test_flops_drift_skips(self):
        gate = _load_perf_gate()
        verdict = gate.compare_matrix(
            _round({self.KEY: _mline(1000.0, flops=1.0e9)}),
            _round({self.KEY: _mline(400.0, flops=2.0e9)}),
        )
        assert not verdict["regressions"] and verdict["skipped"]

    def test_matrix_lines_last_json_wins(self):
        gate = _load_perf_gate()
        early, late = _round({self.KEY: _mline(1.0)}), _round({self.KEY: _mline(2.0)})
        lines = gate.matrix_lines(early + late)
        assert lines[self.KEY]["tokens_per_sec"] == 2.0

    def test_self_test_passes(self):
        gate = _load_perf_gate()
        assert gate._self_test() == 0


# --------------------------------------------------------------------------
# fits: loss parity, guard, checkpoint/elastic resume (@slow)
# --------------------------------------------------------------------------


def _fit_losses(extra: dict, steps: int = 5, *, nonfinite_guard: bool = False):
    """N train steps on the tiny GPT straight through make_train_step;
    returns (per-step losses, final params, final metrics)."""
    from llmtrain_tpu.training.optimizer import build_optimizer
    from llmtrain_tpu.training.train_step import create_train_state, make_train_step

    cfg = _gpt_cfg(extra)
    adapter = GPTAdapter()
    model = adapter.build_model(cfg)
    tx = build_optimizer(cfg.trainer)
    rng = jax.random.key(0)
    params = adapter.init_params(model, cfg, rng)
    state = create_train_state(params, tx)
    step_fn = jax.jit(
        make_train_step(
            adapter, model, tx, grad_accum_steps=1, use_dropout=False,
            nonfinite_guard=nonfinite_guard,
        )
    )
    tokens = np.random.default_rng(0).integers(0, 256, size=(1, 4, 16), dtype=np.int32)
    batch = {
        "input_ids": jnp.asarray(tokens),
        "labels": jnp.asarray(tokens),
        "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
    }
    losses = []
    metrics = {}
    for _ in range(steps):
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses, state.params, metrics


@pytest.mark.slow
class TestQuantFits:
    @pytest.mark.parametrize("mode", ["int8", "fp8"])
    def test_loss_parity_vs_f32_within_band(self, mode):
        """The bench matrix's parity gate, as a unit: N quantized steps
        track the f32 trajectory within the documented rtol."""
        if mode == "fp8" and not fp8_supported():
            pytest.skip("backend has no fp8 dot; clean f32 fallback covered elsewhere")
        ref, _, _ = _fit_losses({"matmul_precision": "f32"})
        got, _, _ = _fit_losses({"matmul_precision": mode})
        max_rel = max(abs(q - f) / max(abs(f), 1e-6) for q, f in zip(got, ref))
        assert max_rel < PARITY_RTOL[mode], f"{mode} drifted {max_rel:.4f}"
        # and the f32 knob itself is bitwise the no-knob baseline
        base, _, _ = _fit_losses({})
        assert ref == base

    def test_grads_finite_under_nonfinite_guard(self):
        losses, params, metrics = _fit_losses(
            {"matmul_precision": "int8"}, nonfinite_guard=True
        )
        assert all(np.isfinite(losses))
        # guard never tripped: quantized grads are finite, no step skipped
        assert int(jax.device_get(metrics["nonfinite_count"])) == 0
        assert all(bool(jnp.all(jnp.isfinite(p))) for p in jax.tree.leaves(params))

    def test_checkpoint_elastic_resume_roundtrip_int8(self, tmp_path):
        """A checkpoint written under int8 training resumes bitwise — with
        the same knob AND with the knob flipped (f32 master weights mean
        matmul_precision is resume-mutable, like loss_impl)."""
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training import Trainer

        def fit(run_dir, extra, resume_from=None):
            run_dir.mkdir(parents=True, exist_ok=True)
            cfg = _gpt_cfg(
                extra,
                root=tmp_path,
                max_steps=6,
                log_every_steps=1,
                eval_every_steps=100,
                save_every_steps=3,
            )
            return Trainer(cfg, run_dir, NullTracker(), None).fit(resume_from=resume_from)

        full = fit(tmp_path / "full", {"matmul_precision": "int8"})
        ckpt = tmp_path / "full" / "checkpoints" / "step_000003.ckpt"
        assert ckpt.exists()
        resumed = fit(
            tmp_path / "resume_int8", {"matmul_precision": "int8"}, resume_from=str(ckpt)
        )
        assert resumed.resumed_from_step == 3
        assert resumed.final_loss == full.final_loss  # bitwise trajectory
        # knob change across resume: int8 checkpoint trains on at f32
        flipped = fit(
            tmp_path / "resume_f32", {"matmul_precision": "f32"}, resume_from=str(ckpt)
        )
        assert flipped.resumed_from_step == 3
        assert np.isfinite(flipped.final_loss)

    def test_attribution_pin_logits_absent_under_auto_chunked(self):
        """Satellite pin for the auto-select: under auto-selected
        chunked_ce no dot materializes the [B,T,V] logits — the dense
        run's aggregate ``dot`` bytes include the full logits tensor, the
        chunked run's stay below it (attribution-based, via the same
        aot_profile the `llmtrain profile` CLI uses)."""
        from llmtrain_tpu.telemetry import profiling
        from llmtrain_tpu.training.optimizer import build_optimizer
        from llmtrain_tpu.training.train_step import create_train_state, make_train_step

        B, T, V = 4, 64, 16384

        def dot_bytes(extra):
            cfg = _gpt_cfg(extra, vocab=V, seq=T)
            adapter = GPTAdapter()
            model = adapter.build_model(cfg)
            tx = build_optimizer(cfg.trainer)
            params = adapter.init_params(model, cfg, jax.random.key(0))
            state = create_train_state(params, tx)
            step_fn = jax.jit(
                make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
            )
            tokens = np.zeros((1, B, T), np.int32)
            batch = {
                "input_ids": jnp.asarray(tokens),
                "labels": jnp.asarray(tokens),
                "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
            }
            prof = profiling.aot_profile(
                step_fn, (state, batch, jax.random.key(0)),
                name="pin", peaks=profiling.resolve_peaks(),
            )
            assert prof is not None
            rows = {r["op"]: r for r in prof["top_ops"]}
            return model.loss_impl, rows.get("dot", {"bytes_accessed": 0.0})["bytes_accessed"]

        logits_bytes = B * T * V * 4
        impl_dense, dense_bytes = dot_bytes({"loss_impl": "dense"})
        impl_auto, chunked_bytes = dot_bytes({"ce_auto_vocab": 1024})
        assert impl_dense == "dense" and impl_auto == "chunked_ce"
        assert dense_bytes >= logits_bytes, "dense CE must materialize the logits dot"
        assert chunked_bytes < logits_bytes, "chunked CE leaked a full-vocab logits dot"
