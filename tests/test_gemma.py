"""Gemma-family tests (models/gemma.py).

Beyond-reference model family. Gemma is the llama stack with GeGLU,
(1 + scale) RMSNorm, and sqrt(d)-scaled input embeddings, so these
tests cover exactly those deltas plus HF-torch-Gemma numerical parity
and the HF state-dict round-trip (mirroring tests/test_qwen2.py's
strategy for the qkv-bias delta).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.linen import meta as nn_meta

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.registry import initialize_registries
from llmtrain_tpu.registry.models import get_model_adapter
from llmtrain_tpu.tracking import NullTracker
from llmtrain_tpu.training.trainer import Trainer

V, T, D, H, F = 64, 16, 32, 4, 88


@pytest.fixture(autouse=True)
def _registries():
    initialize_registries()


def _cfg(_max_steps=25, _model_overrides=None, **model_extra):
    model = {
        "name": "gemma",
        "block_size": T,
        "d_model": D,
        "n_layers": 2,
        "n_heads": H,
        "d_ff": F,
        "dropout": 0.0,
        "vocab_size": V,
        "extra": model_extra,
    }
    model.update(_model_overrides or {})
    return RunConfig.model_validate(
        {
            "run": {"name": "gemma-t", "seed": 0, "device": "cpu"},
            "model": model,
            "data": {"name": "dummy_text"},
            "trainer": {
                "max_steps": _max_steps,
                "micro_batch_size": 2,
                "grad_accum_steps": 1,
                "lr": 5e-3,
                "warmup_steps": 0,
                "log_every_steps": 10,
                "eval_every_steps": 100,
                "save_every_steps": 100,
            },
            "mlflow": {"enabled": False},
        }
    )


def _build(_model_overrides=None, **model_extra):
    cfg = _cfg(_model_overrides=_model_overrides, **model_extra)
    adapter = get_model_adapter("gemma")()
    model = adapter.build_model(cfg)
    params = nn_meta.unbox(
        model.init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32), deterministic=True
        )["params"]
    )
    return cfg, adapter, model, params


class TestArchitecture:
    def test_gemma_knobs_set(self):
        _, _, model, _ = _build()
        assert model.mlp_act == "gelu_tanh"
        assert model.norm_offset is True
        assert model.embed_scale is True
        assert model.tie_embeddings is True  # family default
        _, _, untied, _ = _build(_model_overrides={"tie_embeddings": False})
        assert untied.tie_embeddings is False

    def test_norm_deltas_init_to_zero(self):
        """(1 + scale) parameterization: stored scales are zero deltas."""
        _, _, _, params = _build()
        assert float(jnp.abs(params["norm_f"]["scale"]).max()) == 0.0
        assert float(
            jnp.abs(params["block_0"]["attn_norm"]["scale"]).max()
        ) == 0.0

    def test_embeddings_scaled_at_input_only(self):
        """sqrt(d) enters the forward exactly once, at the input: a
        zero-block gemma-configured Llama equals the embedding rows
        scaled, rms-normed (identity-at-init offset norm), and read
        against the UNSCALED tied head."""
        from llmtrain_tpu.models.llama import Llama

        model = Llama(
            vocab_size=V, block_size=T, d_model=D, n_layers=0, n_heads=H,
            d_ff=F, dropout=0.0, tie_embeddings=True,
            mlp_act="gelu_tanh", norm_offset=True, embed_scale=True,
        )
        params = nn_meta.unbox(
            model.init(jax.random.key(0), jnp.zeros((1, 2), jnp.int32))["params"]
        )
        ids = jnp.asarray([[3, 9]], jnp.int32)
        logits = model.apply({"params": params}, ids, deterministic=True)
        emb = params["token_embedding"]["embedding"]
        x = np.asarray(emb)[np.asarray(ids)[0]] * (D**0.5)
        x = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6)
        want = x @ np.asarray(emb).T  # tied head reads UNSCALED embeddings
        np.testing.assert_allclose(np.asarray(logits)[0], want, atol=1e-4)

    def test_llama_unaffected(self):
        """The gemma knobs must not leak into the llama family."""
        from llmtrain_tpu.models.llama import Llama

        m = Llama(
            vocab_size=V, block_size=T, d_model=D, n_layers=1, n_heads=H,
            d_ff=F, dropout=0.0,
        )
        assert m.mlp_act == "silu" and not m.norm_offset and not m.embed_scale
        p = nn_meta.unbox(
            m.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
        )
        assert float(jnp.abs(p["norm_f"]["scale"]).max()) == 1.0  # ones init

    def test_loss_decreases_under_trainer(self):
        trainer = Trainer(_cfg(), None, NullTracker(), None)
        res = trainer.fit()
        assert res.final_loss < res.first_step_loss

    def test_bad_mlp_act_rejected(self):
        from llmtrain_tpu.models.llama import Llama

        m = Llama(
            vocab_size=V, block_size=T, d_model=D, n_layers=1, n_heads=H,
            d_ff=F, dropout=0.0, mlp_act="tanh",
        )
        with pytest.raises(ValueError, match="mlp_act"):
            m.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))


class TestGemmaSharded:
    def test_train_step_on_fsdp_tp_mesh(self):
        """One Trainer step under {data:2, fsdp:2, tensor:2} — the gemma
        knobs (offset norms, scaled embed, GeGLU) must shard through the
        shared logical-axis rules without pjit errors."""
        cfg = _cfg(_max_steps=2, n_kv_heads=2)
        cfg = RunConfig.model_validate(
            {
                **cfg.model_dump(),
                "distributed": {
                    "enabled": False,
                    "mesh": {"data": 2, "fsdp": 2, "tensor": 2},
                },
            }
        )
        res = Trainer(cfg, None, NullTracker(), None).fit()
        assert np.isfinite(res.final_loss)


class TestHFParity:
    """Numerics pinned against transformers' torch Gemma (fwd logits)."""

    @pytest.fixture(scope="class")
    def pair(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        initialize_registries()
        hf_cfg = transformers.GemmaConfig(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            head_dim=D // H,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            rope_theta=10000.0,
            hidden_activation="gelu_pytorch_tanh",
            tie_word_embeddings=True,
        )
        torch.manual_seed(0)
        hf = transformers.GemmaForCausalLM(hf_cfg).eval()

        cfg = _cfg(n_kv_heads=2, rope_theta=10000.0)
        adapter = get_model_adapter("gemma")()
        ours = adapter.build_model(cfg)
        p = nn_meta.unbox(
            ours.init(
                jax.random.key(0), jnp.zeros((1, 4), jnp.int32),
                deterministic=True,
            )["params"]
        )

        from llmtrain_tpu.interop import llama_params_from_hf_state_dict

        sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
        new = llama_params_from_hf_state_dict(sd, p)
        assert jax.tree.map(jnp.shape, p) == jax.tree.map(jnp.shape, new)
        return hf, ours, new

    def test_logits_match(self, pair):
        torch = pytest.importorskip("torch")
        hf, ours, params = pair
        ids = np.asarray([[1, 5, 9, 2, 40, 3, 0, 63]], np.int32)
        with torch.no_grad():
            want = hf(torch.from_numpy(ids).long()).logits.numpy()
        got = np.asarray(
            ours.apply({"params": params}, jnp.asarray(ids), deterministic=True)
        )
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_generate_greedy_runs(self, pair):
        """KV-cache decode carries the scaled-embed path end to end."""
        from llmtrain_tpu.generation import generate

        _, ours, params = pair
        out = generate(
            ours,
            params,
            np.array([[1, 2, 3]], np.int32),
            max_new_tokens=4,
            temperature=0.0,
        )
        assert np.asarray(out).shape == (1, 7)


class TestHFRoundtrip:
    def test_export_loads_into_hf_gemma(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        from llmtrain_tpu.interop import llama_params_to_hf_state_dict

        _, _, _, params = _build(n_kv_heads=2)
        sd = {
            k: torch.from_numpy(v)
            for k, v in llama_params_to_hf_state_dict(params).items()
        }
        hf_cfg = transformers.GemmaConfig(
            vocab_size=V,
            hidden_size=D,
            intermediate_size=F,
            num_hidden_layers=2,
            num_attention_heads=H,
            num_key_value_heads=2,
            head_dim=D // H,
            max_position_embeddings=T,
            rms_norm_eps=1e-6,
            hidden_activation="gelu_pytorch_tanh",
            tie_word_embeddings=True,
        )
        hf = transformers.GemmaForCausalLM(hf_cfg)
        result = hf.load_state_dict(sd, strict=False)
        # strict=False only because the tied lm_head may dedupe — nothing
        # else may be missing, and no exported tensor may go unconsumed.
        assert result.unexpected_keys == []
        assert set(result.missing_keys) <= {"lm_head.weight"}
        # The loaded embedding matches ours bit-for-bat.
        np.testing.assert_allclose(
            hf.model.embed_tokens.weight.detach().numpy(),
            np.asarray(params["token_embedding"]["embedding"], np.float32),
            atol=0,
        )
