"""Contracts the round driver depends on: bench.py and __graft_entry__.py.

bench.py must ALWAYS exit 0 and print one JSON line with the agreed keys
(round 1 was lost to a crash here); __graft_entry__ must expose
``entry()`` (jittable flagship forward) and ``dryrun_multichip(n)``.
These are the only invocations nothing else in the suite exercises.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _cpu_env(**extra: str) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


class TestBenchContract:
    def test_emits_one_json_line_and_exits_zero(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True,
            text=True,
            timeout=600,
            # Keep the internal watchdog's budget well inside the pytest
            # timeout so a hung child resolves through bench's fallback
            # (the contract under test) rather than TimeoutExpired here.
            # Small batch/steps: the contract is the JSON line and exit 0,
            # not the throughput — the default L2/d1280 CPU shape at full
            # batch can exceed the watchdog on a loaded 1-core host.
            env=_cpu_env(
                LLMTRAIN_BENCH_CPU_TIMEOUT="240",
                LLMTRAIN_BENCH_BATCH="4",
                LLMTRAIN_BENCH_STEPS="2",
            ),
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        json_lines = [
            ln for ln in proc.stdout.strip().splitlines() if ln.startswith("{")
        ]
        assert len(json_lines) == 1, proc.stdout
        payload = json.loads(json_lines[0])
        assert payload["metric"] == "tokens_per_sec_per_chip"
        assert payload["unit"] == "tokens/s"
        assert payload["value"] > 0
        assert payload["vs_baseline"] > 0
        detail = payload["detail"]
        for key in ("backend", "mfu", "attention", "loss_impl", "batch", "final_loss"):
            assert key in detail, key

    def test_require_tpu_child_refuses_cpu_without_json(self):
        """A watchdog-spawned 'TPU' child that lands on CPU must exit
        nonzero with NO JSON line — otherwise a dead tunnel's in-process
        CPU fallback would print a line the watchdog mislabels as
        on-chip (evidence mode contamination)."""
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True,
            text=True,
            timeout=300,
            env=_cpu_env(LLMTRAIN_BENCH_CHILD="1", LLMTRAIN_BENCH_REQUIRE_TPU="1"),
            cwd=REPO,
        )
        assert proc.returncode == 3
        assert "REQUIRE_TPU" in proc.stderr
        assert not [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]

    def test_invalid_ce_knob_fails_loudly(self):
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True,
            text=True,
            timeout=600,
            env=_cpu_env(LLMTRAIN_BENCH_CE="typo", LLMTRAIN_BENCH_CHILD="1"),
            cwd=REPO,
        )
        assert proc.returncode != 0
        assert "LLMTRAIN_BENCH_CE" in proc.stderr


@pytest.mark.slow
class TestGraftEntry:
    def test_entry_compiles_single_device(self):
        """The driver compile-checks entry() single-chip; do the same on CPU."""
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                (
                    "import jax; jax.config.update('jax_platforms', 'cpu');\n"
                    "import __graft_entry__ as g\n"
                    "fn, args = g.entry()\n"
                    "out = jax.jit(fn)(*args)\n"
                    "print('entry ok', out.shape)"
                ),
            ],
            capture_output=True,
            text=True,
            timeout=600,
            env=_cpu_env(),
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "entry ok (8, 512, 50257)" in proc.stdout

    def test_dryrun_multichip_two_devices(self):
        """All three dryrun legs (dp/fsdp/tp/sp mesh, pipeline, MoE) run on
        a 2-virtual-device mesh — the cheapest even device count."""
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import __graft_entry__ as g; g.dryrun_multichip(2)",
            ],
            capture_output=True,
            text=True,
            timeout=900,
            env=_cpu_env(XLA_FLAGS="--xla_force_host_platform_device_count=2"),
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        for leg in ("dryrun_multichip ok", "dryrun_pipeline ok", "dryrun_moe ok"):
            assert leg in proc.stdout, proc.stdout
