"""Multi-tenant fleet supervisor tests (docs/robustness.md "Fleet: many
tenants, shared capacity").

Tier-1 keeps to pure units — the scheduling policy and the tenant state
machine are deliberately pure functions/tables, the escalation-ladder
test's child process never imports jax — so the additions cost
milliseconds against the suite's kill budget. Everything that runs a
real Trainer fit (the preemption-storm acceptance drill, the
twice-evicted resume-count fairness pin, the elastic-resize exercise,
the CLI round-trip) is ``@pytest.mark.slow`` under ``make verify-fleet``.
"""

from __future__ import annotations

import json
import re
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest
import yaml

from llmtrain_tpu.config import RunConfig
from llmtrain_tpu.fleet.policy import (
    TenantDemand,
    candidate_world_sizes,
    plan_allocations,
    priority_order,
    within_bounds,
)
from llmtrain_tpu.fleet.tenant import (
    BACKOFF,
    COMPLETED,
    PREEMPTING,
    QUEUED,
    RUNNING,
    SUSPENDED,
    InvalidTransitionError,
    TenantStateMachine,
)

_FLEET_PRESET = Path(__file__).resolve().parents[1] / "configs" / "presets" / (
    "gpt_fleet_smoke.yaml"
)


# --------------------------------------------------------------------------
# scheduling policy (pure, table-driven)
# --------------------------------------------------------------------------


class TestSchedulingPolicy:
    def test_candidate_sizes_are_divisors_within_bounds(self):
        assert candidate_world_sizes(8, 1, 4) == (1, 2, 4)
        assert candidate_world_sizes(6, 2, 6) == (2, 3, 6)
        assert candidate_world_sizes(2, 1, 2) == (1, 2)

    def test_infeasible_window_is_a_config_error(self):
        with pytest.raises(ValueError, match="divides the"):
            candidate_world_sizes(4, 3, 3)

    @pytest.mark.parametrize(
        "pool,demands,expected,suspended",
        [
            # Everyone fits at minimum; no slack to grow.
            (
                3,
                [("a", 1, (1, 2)), ("b", 0, (1,)), ("c", 0, (1,))],
                {"a": 1, "b": 1, "c": 1},
                (),
            ),
            # Slack grows the highest-priority tenant first.
            (
                4,
                [("a", 1, (1, 2)), ("b", 0, (1, 2)), ("c", 0, (1,))],
                {"a": 2, "b": 1, "c": 1},
                (),
            ),
            # Round-robin growth: spare devices spread by priority, one
            # feasibility step per turn.
            (
                6,
                [("a", 1, (1, 2, 4)), ("b", 0, (1, 2))],
                {"a": 4, "b": 2},
                (),
            ),
            # Shrink-before-suspend: the pool no longer fits every
            # minimum; the LOWEST priority tenant suspends, nobody
            # crashes, nobody exceeds a quota.
            (
                2,
                [("a", 2, (1, 2)), ("b", 1, (1,)), ("c", 0, (1,))],
                {"a": 1, "b": 1, "c": 0},
                ("c",),
            ),
            # Priority ties break by name — deterministic, not dict-order.
            (
                1,
                [("zeta", 0, (1,)), ("alpha", 0, (1,))],
                {"alpha": 1, "zeta": 0},
                ("zeta",),
            ),
            # Capacity zero suspends the whole fleet (drain), no errors.
            (
                0,
                [("a", 1, (1,)), ("b", 0, (1,))],
                {"a": 0, "b": 0},
                ("a", "b"),
            ),
            # Feasibility gaps are respected: with sizes (1, 4) and one
            # spare device the tenant stays at 1 — 2 and 3 would break
            # the elastic divisor contract.
            (
                3,
                [("a", 1, (1, 4)), ("b", 0, (1,))],
                {"a": 1, "b": 1},
                (),
            ),
        ],
    )
    def test_allocation_table(self, pool, demands, expected, suspended):
        plan = plan_allocations(
            pool,
            [TenantDemand(n, p, sizes) for n, p, sizes in demands],
        )
        assert plan.allocations == expected
        assert plan.suspended == suspended
        assert sum(plan.allocations.values()) <= pool

    def test_non_runnable_tenants_hold_no_devices(self):
        plan = plan_allocations(
            2,
            [
                TenantDemand("done", 5, (1, 2), runnable=False),
                TenantDemand("live", 0, (1, 2)),
            ],
        )
        assert plan.allocations == {"done": 0, "live": 2}

    def test_priority_order_is_deterministic(self):
        demands = [TenantDemand(n, 0, (1,)) for n in ("b", "a", "c")]
        assert [d.name for d in priority_order(demands)] == ["a", "b", "c"]

    def test_within_bounds(self):
        d = TenantDemand("a", 0, (1, 2, 4))
        assert within_bounds(0, d) and within_bounds(2, d)
        assert not within_bounds(3, d) and not within_bounds(8, d)


# --------------------------------------------------------------------------
# tenant state machine
# --------------------------------------------------------------------------


class TestTenantStateMachine:
    def test_happy_path_with_eviction_cycle(self):
        sm = TenantStateMachine("t")
        for to in (RUNNING, PREEMPTING, BACKOFF, RUNNING, PREEMPTING,
                   SUSPENDED, RUNNING, COMPLETED):
            sm.transition(to, "test")
        assert sm.state == COMPLETED and sm.terminal
        assert [s for s, _ in sm.history][0] == QUEUED

    @pytest.mark.parametrize(
        "path,bad",
        [
            ((), PREEMPTING),            # queued cannot preempt
            ((), COMPLETED),             # queued cannot complete
            ((RUNNING, COMPLETED), RUNNING),   # terminal is terminal
            ((RUNNING, PREEMPTING), RUNNING),  # must exit first
            ((RUNNING, BACKOFF), PREEMPTING),  # nothing to preempt
        ],
    )
    def test_illegal_transitions_raise(self, path, bad):
        sm = TenantStateMachine("t")
        for to in path:
            sm.transition(to, "setup")
        with pytest.raises(InvalidTransitionError):
            sm.transition(bad, "illegal")

    def test_unknown_state_raises(self):
        with pytest.raises(InvalidTransitionError):
            TenantStateMachine("t").transition("zombie")


# --------------------------------------------------------------------------
# supervisor units (no training subprocesses)
# --------------------------------------------------------------------------


def _fleet_cfg(**fleet_overrides):
    raw = yaml.safe_load(_FLEET_PRESET.read_text())
    raw.setdefault("fleet", {}).update(fleet_overrides)
    return RunConfig.model_validate(raw), raw


def _make_supervisor(tmp_path, **fleet_overrides):
    from llmtrain_tpu.fleet.supervisor import FleetSupervisor

    cfg, raw = _fleet_cfg(**fleet_overrides)
    return FleetSupervisor(cfg, raw, work_dir=tmp_path / "fleet", seed=0)


class TestSupervisorUnits:
    def test_child_env_replaces_forced_device_count(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "XLA_FLAGS",
            "--xla_force_host_platform_device_count=8 --xla_foo=1",
        )
        sup = _make_supervisor(tmp_path)
        env = sup._child_env(2)
        assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
        assert "device_count=8" not in env["XLA_FLAGS"]
        assert "--xla_foo=1" in env["XLA_FLAGS"]  # unrelated flags survive
        assert env["JAX_PLATFORMS"] == "cpu"

    def test_tenant_base_config_pins_cadence_and_overrides(self, tmp_path):
        sup = _make_supervisor(tmp_path)
        base_b = sup.tenants["tenant-b"].base_config
        assert base_b["trainer"]["lr"] == 0.001  # tenant override applied
        assert base_b["model"]["extra"]["lora"]["rank"] == 4
        assert base_b["model"]["extra"]["tokenizer"] == "byte"  # base kept
        assert base_b["mlflow"]["enabled"] is False
        assert base_b["resilience"]["watchdog"]["enabled"] is True
        assert base_b["trainer"]["save_every_steps"] % base_b["trainer"][
            "log_every_steps"
        ] == 0
        assert base_b["logging"]["log_to_file"] is True
        assert "fleet" not in base_b  # tenants do not recurse

    def test_production_derive_keeps_cadence_eval_and_tracker(self, tmp_path):
        """Drill semantics (pinned cadence, eval pushed to the end,
        trackers off) apply only under drill=True or explicit cadence
        overrides — a plain production fleet run must respect each
        tenant's own config (telemetry.prometheus stays off either way:
        the FLEET owns the /metrics port)."""
        from llmtrain_tpu.fleet.supervisor import FleetSupervisor

        raw = yaml.safe_load(_FLEET_PRESET.read_text())
        raw["trainer"]["max_steps"] = 120
        raw["trainer"]["save_every_steps"] = 100
        raw["trainer"]["eval_every_steps"] = 10
        raw["mlflow"] = {"enabled": True}
        cfg = RunConfig.model_validate(raw)
        prod = FleetSupervisor(cfg, raw, work_dir=tmp_path / "prod", seed=0)
        base = prod.tenants["tenant-a"].base_config
        assert base["trainer"]["save_every_steps"] == 100
        assert base["trainer"]["eval_every_steps"] == 10
        assert base["mlflow"]["enabled"] is True
        assert base["telemetry"]["prometheus"] is False
        drill = FleetSupervisor(
            cfg, raw, work_dir=tmp_path / "drill", seed=0, drill=True
        )
        dbase = drill.tenants["tenant-a"].base_config
        assert dbase["trainer"]["save_every_steps"] == 40  # clamped to steps//3
        assert dbase["trainer"]["eval_every_steps"] == 120  # pushed to the end
        assert dbase["mlflow"]["enabled"] is False

    def test_segment_config_scales_micro_batch_inversely(self, tmp_path):
        sup = _make_supervisor(tmp_path)
        t = sup.tenants["tenant-a"]
        path = sup._write_segment_cfg(t, 0, 2, {"kill_at_step": 5})
        seg = yaml.safe_load(path.read_text())
        assert seg["trainer"]["micro_batch_size"] * 2 == t.global_micro
        assert seg["resilience"]["faults"] == {"kill_at_step": 5}

    def test_launch_outside_bounds_is_an_invariant_error(self, tmp_path):
        from llmtrain_tpu.fleet.supervisor import FleetInvariantError

        sup = _make_supervisor(tmp_path)
        with pytest.raises(FleetInvariantError, match="bounds"):
            sup._launch(sup.tenants["tenant-b"], 3)

    def test_escalation_ladder_sigkills_a_term_ignoring_tenant(self, tmp_path):
        """Rung 2 for real: the 'tenant' traps SIGTERM and refuses to die;
        past the grace deadline the supervisor SIGKILLs it. The child is a
        bare python -c (no jax) so this stays tier-1 cheap."""
        sup = _make_supervisor(tmp_path, preempt_grace_sec=0.3)
        t = sup.tenants["tenant-a"]
        t.proc = subprocess.Popen(
            [
                sys.executable,
                "-c",
                "import signal, time; "
                "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
                "print('up', flush=True); time.sleep(60)",
            ],
            stdout=subprocess.PIPE,
        )
        assert t.proc.stdout.readline().strip() == b"up"  # handler installed
        t.sm.transition(RUNNING, "test")
        sup._preempt(t, reason="test evict")
        assert t.sm.state == PREEMPTING
        deadline = time.monotonic() + 10.0
        while t.proc.poll() is None and time.monotonic() < deadline:
            sup._escalate_overdue(time.monotonic())
            time.sleep(0.05)
        assert t.proc.poll() == -signal.SIGKILL
        assert t.counts["escalations"] == 1

    def test_backoff_delays_are_seeded_and_bounded(self, tmp_path):
        sup_a = _make_supervisor(tmp_path / "a")
        sup_b = _make_supervisor(tmp_path / "b")
        da = [sup_a._backoff_delay(sup_a.tenants["tenant-a"]) for _ in range(4)]
        db = [sup_b._backoff_delay(sup_b.tenants["tenant-a"]) for _ in range(4)]
        assert da == db  # same seed -> same full-jitter schedule
        assert all(0.0 <= d <= sup_a._fleet.respawn_backoff_max_sec for d in da)
        # Different tenants draw different (decorrelated) streams.
        assert da != [
            sup_a._backoff_delay(sup_a.tenants["tenant-b"]) for _ in range(4)
        ]

    def test_render_fleet_report_md(self, tmp_path):
        from llmtrain_tpu.fleet.supervisor import render_fleet_report_md

        md = render_fleet_report_md(
            {
                "pool_devices": 2,
                "capacity_changes": 2,
                "wall_time_sec": 1.0,
                "seed": 0,
                "totals": {
                    "completed": 1,
                    "failed": 0,
                    "evictions": 3,
                    "escalations": 1,
                    "respawns": 3,
                    "resizes": 1,
                    "suspensions": 1,
                },
                "tenants": {
                    "a": {
                        "state": "completed",
                        "priority": 1,
                        "min_devices": 1,
                        "max_devices": 2,
                        "segments": 4,
                        "evictions": {"total": 3},
                        "respawns": 3,
                        "resume_count": 2,
                        "final_step": 12,
                        "final_loss": 3.25,
                    }
                },
            }
        )
        assert "| a | completed |" in md and "| 3 | 3 | 2 | 12 | 3.25 |" in md

    def test_render_metrics_federates_tenant_textfiles(self, tmp_path):
        """One scrape of the supervisor covers the fleet: each tenant's
        metrics.prom snapshot is re-emitted with a tenant label, counters
        additionally roll up into an unlabeled fleet-wide sum, and the
        fleet's own gauges still lead the exposition."""
        sup = _make_supervisor(tmp_path)
        for name, loss, commits in (("tenant-a", 2.5, 3), ("tenant-b", 1.5, 4)):
            prom = sup.tenants[name].run_dir / "telemetry" / "metrics.prom"
            prom.parent.mkdir(parents=True, exist_ok=True)
            prom.write_text(
                "# TYPE llmtrain_train_loss gauge\n"
                f"llmtrain_train_loss {loss}\n"
                "# TYPE llmtrain_ckpt_commits_total counter\n"
                f"llmtrain_ckpt_commits_total {commits}\n",
                encoding="utf-8",
            )
        text = sup._render_metrics()
        # Fleet's own identity gauge is untouched by federation.
        assert 'mode="fleet"' in text
        # Per-tenant series carry the tenant label.
        assert 'llmtrain_train_loss{tenant="tenant-a"} 2.5' in text
        assert 'llmtrain_train_loss{tenant="tenant-b"} 1.5' in text
        assert 'llmtrain_ckpt_commits_total{tenant="tenant-a"} 3' in text
        # Counters also sum into one unlabeled fleet-wide series.
        assert re.search(
            r"^llmtrain_ckpt_commits_total 7(\.0)?$", text, re.MULTILINE
        )
        # A missing textfile (tenant never started) is skipped, not fatal.
        (sup.tenants["tenant-a"].run_dir / "telemetry" / "metrics.prom").unlink()
        assert 'tenant="tenant-b"' in sup._render_metrics()


# --------------------------------------------------------------------------
# preempt_at_step fault + partial-interval comparison rule
# --------------------------------------------------------------------------


class TestPreemptFault:
    def test_preempt_at_step_delivers_real_sigterm_once(self):
        from llmtrain_tpu.config.schemas import FaultInjectionConfig
        from llmtrain_tpu.resilience.faults import FaultPlan

        hits: list[int] = []
        old = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            plan = FaultPlan(FaultInjectionConfig(preempt_at_step=3))
            fired: list[tuple[str, int]] = []
            plan.observer = lambda kind, step: fired.append((kind, step))
            plan.maybe_sigterm(2)
            assert hits == []  # exact step only, never >=
            plan.maybe_sigterm(3)
            assert hits == [signal.SIGTERM]
            plan.maybe_sigterm(3)
            plan.maybe_sigterm(4)
            assert hits == [signal.SIGTERM]  # one-shot
            assert fired == [("preempt", 3)]  # telemetry names the knob
        finally:
            signal.signal(signal.SIGTERM, old)

    def test_preempt_and_sigterm_are_mutually_exclusive(self):
        from llmtrain_tpu.config.schemas import FaultInjectionConfig

        with pytest.raises(Exception, match="exactly one"):
            FaultInjectionConfig(preempt_at_step=3, sigterm_at_step=4)


class TestPartialIntervalRule:
    @pytest.mark.parametrize(
        "resumed,log_every,expected",
        [
            (None, 3, None),   # fresh run: every interval is full
            (6, 3, None),      # aligned resume: every interval is full
            (7, 3, 9),         # mid-interval: only the next boundary skips
            (8, 3, 9),
            (4, 5, 5),
            (0, 3, None),
        ],
    )
    def test_partial_interval_step(self, resumed, log_every, expected):
        from llmtrain_tpu.fleet.chaos import partial_interval_step

        assert partial_interval_step(resumed, log_every) == expected


# --------------------------------------------------------------------------
# shared drill harness (the chaos.py extraction satellite)
# --------------------------------------------------------------------------


class TestSharedHarness:
    def test_chaos_module_reexports_the_shared_helpers(self):
        """`llmtrain chaos` keeps its contract: the historical private
        names still resolve (tests and docs pin them) and now come from
        the shared harness the fleet drill imports."""
        from llmtrain_tpu.resilience import chaos, harness

        assert chaos._trees_bitwise_equal is harness.trees_bitwise_equal
        assert chaos._newest_committed_step is harness.newest_committed_step
        assert chaos._segment_resumed_step is harness.segment_resumed_step
        assert issubclass(chaos.ChaosInvariantError, harness.DrillInvariantError)

    def test_derive_segment_config_merges_overrides_deep(self):
        from llmtrain_tpu.resilience.harness import derive_segment_config

        derived = derive_segment_config(
            {
                "trainer": {"lr": 0.1, "max_steps": 99},
                "model": {"extra": {"tokenizer": "byte"}},
                "mlflow": {"enabled": True},
            },
            root_dir="/tmp/x",
            max_steps=10,
            save_every=5,
            log_every=5,
            faults={"kill_at_step": 7},
            overrides={"trainer": {"lr": 0.2}, "model": {"extra": {"lora": {"rank": 2}}}},
        )
        assert derived["trainer"]["lr"] == 0.2
        assert derived["trainer"]["max_steps"] == 10  # cadence pin wins
        assert derived["model"]["extra"] == {
            "tokenizer": "byte",
            "lora": {"rank": 2},
        }
        assert derived["mlflow"]["enabled"] is False
        assert derived["resilience"]["faults"] == {"kill_at_step": 7}

    @pytest.mark.parametrize(
        "save,log,expected", [(6, 3, 3), (6, 4, 6), (5, 5, 5), (4, 8, 4)]
    )
    def test_aligned_log_every(self, save, log, expected):
        from llmtrain_tpu.resilience.harness import aligned_log_every

        assert aligned_log_every(save, log) == expected


# --------------------------------------------------------------------------
# the drills (slow: real train subprocesses; `make verify-fleet`)
# --------------------------------------------------------------------------


def _three_tenant_storm_cfg(tmp_path: Path) -> Path:
    """The acceptance shape: >= 3 tenants on a shared pool, all FIXED world
    size so every tenant is held to bitwise parity (docs/robustness.md —
    resizing reorders float reductions and is exercised separately)."""
    raw = yaml.safe_load(_FLEET_PRESET.read_text())
    raw["fleet"] = {
        "pool_devices": 3,
        "preempt_grace_sec": 20.0,
        "tenants": [
            {"name": "alpha", "priority": 2, "min_devices": 1, "max_devices": 1},
            {
                "name": "bravo",
                "priority": 1,
                "min_devices": 1,
                "max_devices": 1,
                "overrides": {"trainer": {"lr": 0.001}},
            },
            {
                "name": "charlie",
                "priority": 0,
                "min_devices": 1,
                "max_devices": 1,
                "overrides": {
                    "model": {"extra": {"lora": {"rank": 4, "alpha": 8}}}
                },
            },
        ],
    }
    path = tmp_path / "storm3.yaml"
    path.write_text(yaml.safe_dump(raw, sort_keys=False), encoding="utf-8")
    return path


@pytest.mark.slow
class TestFleetStormDrill:
    def test_three_tenant_storm_is_bitwise_recoverable(self, tmp_path):
        """THE acceptance drill: seeded capacity drop + random evictions +
        one mid-checkpoint kill across 3 tenants; every tenant's loss
        trajectory and final param/opt tree must come out bitwise-equal to
        its uninterrupted reference, resume/eviction counts land in
        fleet_report.json, and no tenant ever ran outside its
        [min_devices, quota] bounds (run_fleet_storm raises
        FleetInvariantError on any violation)."""
        from llmtrain_tpu.fleet.chaos import run_fleet_storm

        result = run_fleet_storm(
            _three_tenant_storm_cfg(tmp_path),
            seed=1,
            work_dir=tmp_path / "storm",
            timeout_sec=600.0,
        )
        assert result["bitwise_match"] is True
        assert len(result["tenants"]) == 3
        assert result["total_evictions"] >= 3
        assert result["capacity_changes"] >= 2  # drop AND restore happened
        assert result["total_suspensions"] >= 1  # the drop bit somebody
        assert result["mid_checkpoint_kill_tenant"]
        for name, r in result["tenants"].items():
            assert r["parity"] == "bitwise", name
            assert r["evictions"]["total"] >= 1, name
            assert r["resume_count"] >= 1, name
            assert r["trajectory_points_compared"] >= 1, name
        report = json.loads(
            Path(result["fleet_report_json"]).read_text()
        )
        for name, v in report["tenants"].items():
            assert v["state"] == "completed"
            # Bounds invariant over the whole allocation history.
            assert all(a == 1 for a in v["allocations"]), (name, v["allocations"])

    def test_twice_evicted_tenant_accumulates_resume_count(self, tmp_path):
        """The resume-count fairness pin: the supervisor's respawns reuse
        the tenant's --auto-resume run dir, so a twice-evicted tenant
        reports resilience.resume_count == 2 in its OWN report.json (each
        graceful eviction's preemption save persists the incremented
        counter for the next segment to inherit)."""
        from llmtrain_tpu.fleet.supervisor import FleetSupervisor

        raw = yaml.safe_load(_FLEET_PRESET.read_text())
        raw["fleet"] = {
            "pool_devices": 1,
            "preempt_grace_sec": 20.0,
            "tenants": [
                {"name": "solo", "priority": 0, "min_devices": 1, "max_devices": 1}
            ],
        }
        cfg = RunConfig.model_validate(raw)
        sup = FleetSupervisor(
            cfg,
            raw,
            work_dir=tmp_path / "fair",
            seed=3,
            extra_tenant_overrides={
                "trainer": {"extra": {"step_delay_sec": 0.2}}
            },
        )
        state = {"evicted": 0, "gate": 0}

        def controller(s: FleetSupervisor) -> None:
            t = s.tenants["solo"]
            if (
                state["evicted"] < 2
                and t.sm.state == "running"
                and t.segments
                and time.monotonic() - t.segments[-1]["started_at"] >= 2.5
                and s.newest_commit("solo") > state["gate"]
                and s.request_eviction("solo", "graceful")
            ):
                state["evicted"] += 1
                state["gate"] = s.newest_commit("solo")

        report = sup.run(timeout_sec=300.0, on_tick=controller)
        view = report["tenants"]["solo"]
        assert view["state"] == "completed"
        assert state["evicted"] == 2
        assert view["evictions"]["graceful"] == 2
        assert view["resume_count"] == 2
        run_report = json.loads(
            (sup.work_dir / "runs" / "solo" / "report.json").read_text()
        )
        assert run_report["resilience"]["resume_count"] == 2

    def test_capacity_growth_triggers_elastic_resize(self, tmp_path):
        """Grow/shrink through topology-change resume: a short-lived
        neighbor completes, the freed device grows tenant-a 1 -> 2 via
        preempt + respawn, and the resumed run carries the SAME trajectory
        through the elastic re-shard (supervisor invariants stay on; the
        parity bar for resized tenants is the elastic contract's, not
        bitwise — docs/robustness.md)."""
        from llmtrain_tpu.fleet.supervisor import FleetSupervisor

        raw = yaml.safe_load(_FLEET_PRESET.read_text())
        raw["trainer"]["max_steps"] = 18
        raw["fleet"] = {
            "pool_devices": 2,
            "preempt_grace_sec": 20.0,
            "tenants": [
                {"name": "grower", "priority": 1, "min_devices": 1,
                 "max_devices": 2},
                {
                    "name": "shortlived",
                    "priority": 0,
                    "min_devices": 1,
                    "max_devices": 1,
                    "overrides": {"trainer": {"max_steps": 6}},
                },
            ],
        }
        cfg = RunConfig.model_validate(raw)
        sup = FleetSupervisor(
            cfg,
            raw,
            work_dir=tmp_path / "resize",
            seed=5,
            extra_tenant_overrides={
                "trainer": {"extra": {"step_delay_sec": 0.25}}
            },
        )
        report = sup.run(timeout_sec=300.0)
        grower = report["tenants"]["grower"]
        assert grower["state"] == "completed"
        assert grower["resizes"] >= 1
        assert 2 in grower["allocations"]  # actually ran on the grown slice
        assert grower["final_step"] == 18
        assert report["tenants"]["shortlived"]["state"] == "completed"

    def test_fleet_cli_round_trip(self, tmp_path):
        """`llmtrain fleet` end to end over the shipped preset: exit 0,
        every tenant completed, fleet_report.json + .md + the Prometheus
        textfile written."""
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "llmtrain_tpu",
                "fleet",
                "--config",
                str(_FLEET_PRESET),
                "--work-dir",
                str(tmp_path / "cli"),
                "--max-steps",
                "6",
                "--json",
            ],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["totals"]["completed"] == 2
        work = tmp_path / "cli"
        assert (work / "fleet_report.json").is_file()
        assert (work / "fleet_report.md").is_file()
        prom = (work / "fleet_metrics.prom").read_text()
        assert "llmtrain_fleet_pool_devices" in prom
        assert "llmtrain_fleet_tenants_completed" in prom

    def test_cli_rejects_fleetless_config(self):
        from llmtrain_tpu import cli
        from llmtrain_tpu.resilience.exit_codes import EXIT_CONFIG_ERROR

        rc = cli.main(
            [
                "fleet",
                "--config",
                str(_FLEET_PRESET.parent / "gpt_smoke.yaml"),
            ]
        )
        assert rc == EXIT_CONFIG_ERROR
