"""Fused lm-head + CE Pallas kernel (ops/fused_ce.py) and the fused
residual-add + LayerNorm kernel (ops/fused_norm.py), docs/perf.md
"Fused lm-head + CE".

Tier-1 keeps to pure units and interpret-mode kernels — forward/grad
parity vs the dense reference and chunked_ce's custom_vjp (tied/untied,
z_loss on/off, shapes not multiples of the blocks, padded tokens), the
fused-norm parity vs nn.LayerNorm with an identical param tree, the
loss_impl/fused_norm resolution rules, and the planner's logits-buffer
accounting. Everything that runs full fits (5-step loss parity, the
checkpoint resume with loss_impl flipped across the boundary, the
attribution pin) is ``@pytest.mark.slow`` under ``make verify-fusedce``.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmtrain_tpu.config.schemas import RunConfig
from llmtrain_tpu.models.gpt import GPTAdapter
from llmtrain_tpu.ops import fused_ce as fused_ce_mod
from llmtrain_tpu.ops import fused_norm as fused_norm_mod
from llmtrain_tpu.ops.chunked_ce import chunked_ce_components, chunked_ce_per_token
from llmtrain_tpu.ops.fused_ce import (
    LOSS_IMPLS,
    fused_ce_components,
    fused_ce_per_token,
    resolve_loss_impl,
)
from llmtrain_tpu.ops.fused_norm import (
    fused_add_layer_norm,
    fused_layer_norm,
    resolve_fused_norm,
)
from llmtrain_tpu.registry import initialize_registries

# Interpret-mode blocks chosen to NOT divide the test shapes below, so
# every padding path (token rows and vocab columns) is exercised.
BT, BV = 16, 64
# Adapter-level wiring tests use coarser blocks: the interpreter pays
# python-loop overhead per grid step, and the padding paths are already
# covered by the kernel tests above at (BT, BV).
WBT, WBV = 64, 128


@pytest.fixture(scope="module", autouse=True)
def _registries():
    initialize_registries()


def _gpt_cfg(extra: dict, *, vocab: int = 256, seq: int = 16, tie: bool = True,
             root=None, **trainer_kw):
    doc = {
        "run": {"name": "fusedce-test", "seed": 7, "device": "cpu"},
        "model": {
            "name": "gpt",
            "block_size": seq,
            "d_model": 32,
            "n_layers": 2,
            "n_heads": 2,
            "d_ff": 64,
            "dropout": 0.0,
            "vocab_size": vocab,
            "tie_embeddings": tie,
            "extra": extra,
        },
        "data": {"name": "dummy_text"},
        "trainer": {
            "micro_batch_size": 4,
            "grad_accum_steps": 1,
            "lr": 3e-3,
            "warmup_steps": 0,
            **trainer_kw,
        },
        "mlflow": {"enabled": False},
    }
    if root is not None:
        doc["output"] = {"root_dir": str(root)}
    return RunConfig.model_validate(doc)


def _dense_ce_ref(h, w, labels, z_loss=0.0):
    logits = jnp.einsum("btd,vd->btv", h, w)
    lse = jax.nn.logsumexp(logits, axis=-1)
    label_logit = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    per = lse - label_logit
    if z_loss:
        per = per + z_loss * jnp.square(lse)
    return per


def _rand_problem(b=2, t=13, d=32, v=117, seed=0):
    """Shapes deliberately NOT multiples of (BT, BV): B*T=26 pads to 32
    token rows (2 blocks), V=117 pads to 128 vocab rows (2 blocks)."""
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    h = jax.random.normal(ks[0], (b, t, d), jnp.float32)
    w = jax.random.normal(ks[1], (v, d), jnp.float32) * 0.2
    labels = jax.random.randint(ks[2], (b, t), 0, v)
    return h, w, labels


# --------------------------------------------------------------------------
# kernel parity (interpret mode): forward + custom_vjp grads
# --------------------------------------------------------------------------


class TestFusedCEKernel:
    @pytest.mark.parametrize("z_loss", [0.0, 1e-3])
    def test_forward_matches_dense_and_chunked(self, z_loss):
        h, w, labels = _rand_problem()
        fused = fused_ce_per_token(h, w, labels, BT, BV, None, z_loss, True)
        dense = _dense_ce_ref(h, w, labels, z_loss)
        chunked = chunked_ce_per_token(h, w, labels, BV, None, z_loss)
        np.testing.assert_allclose(fused, dense, atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(fused, chunked, atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("z_loss", [0.0, 1e-3])
    def test_grads_match_chunked_vjp(self, z_loss):
        h, w, labels = _rand_problem(seed=1)
        # Non-uniform cotangent: a mean-loss-only check would hide
        # per-token cotangent bugs (every g identical).
        g = jax.random.normal(jax.random.PRNGKey(9), labels.shape)

        def fused_loss(h, w):
            return jnp.sum(fused_ce_per_token(h, w, labels, BT, BV, None, z_loss, True) * g)

        def chunked_loss(h, w):
            return jnp.sum(chunked_ce_per_token(h, w, labels, BV, None, z_loss) * g)

        dh_f, dw_f = jax.grad(fused_loss, argnums=(0, 1))(h, w)
        dh_c, dw_c = jax.grad(chunked_loss, argnums=(0, 1))(h, w)
        np.testing.assert_allclose(dh_f, dh_c, atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(dw_f, dw_c, atol=1e-5, rtol=1e-4)

    def test_block_sizes_larger_than_problem(self):
        # One grid cell total: blocks exceeding N and V must still pad
        # and mask correctly.
        h, w, labels = _rand_problem(seed=2)
        fused = fused_ce_per_token(h, w, labels, 512, 512, None, 0.0, True)
        np.testing.assert_allclose(
            fused, _dense_ce_ref(h, w, labels), atol=1e-5, rtol=1e-5
        )

    def test_components_mask_semantics_match_chunked(self):
        # Padded tokens (mask 0) drop out; packed segment ids > 1 count
        # as boolean 1, not as loss weights.
        h, w, labels = _rand_problem(seed=3)
        mask = jnp.array([[1] * 9 + [0] * 4, [2] * 6 + [1] * 3 + [0] * 4])
        ls_f, n_f = fused_ce_components(
            h, w, labels, mask, block_t=BT, block_v=BV, z_loss=1e-3, interpret=True
        )
        ls_c, n_c = chunked_ce_components(
            h, w, labels, mask, chunk=BV, z_loss=1e-3
        )
        np.testing.assert_allclose(ls_f, ls_c, atol=1e-4, rtol=1e-5)
        np.testing.assert_array_equal(n_f, n_c)
        assert n_f.tolist() == [9.0, 9.0]

    def test_masked_grads_zero_for_padded_tokens(self):
        h, w, labels = _rand_problem(seed=4)
        mask = jnp.concatenate(
            [jnp.ones((2, 7), jnp.int32), jnp.zeros((2, 6), jnp.int32)], axis=1
        )

        def loss(h):
            ls, n = fused_ce_components(
                h, w, labels, mask, block_t=BT, block_v=BV, interpret=True
            )
            return jnp.sum(ls) / jnp.sum(n)

        dh = jax.grad(loss)(h)
        assert bool(jnp.all(dh[:, 7:] == 0.0)), "padded tokens leaked gradient"
        assert bool(jnp.any(dh[:, :7] != 0.0))


# --------------------------------------------------------------------------
# fused residual-add + LayerNorm kernel
# --------------------------------------------------------------------------


class TestFusedNormKernel:
    def _ref_ln(self, x, scale, bias, eps=1e-6):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + eps) * scale + bias

    def _operands(self, seed=0, d=48):
        ks = jax.random.split(jax.random.PRNGKey(seed), 4)
        x = jax.random.normal(ks[0], (2, 13, d))
        r = jax.random.normal(ks[1], (2, 13, d))
        scale = 1.0 + 0.1 * jax.random.normal(ks[2], (d,))
        bias = 0.1 * jax.random.normal(ks[3], (d,))
        return x, r, scale, bias

    def test_plain_norm_matches_reference(self):
        x, _, scale, bias = self._operands()
        y = fused_layer_norm(x, scale, bias, 1e-6, BT, True)
        np.testing.assert_allclose(
            y, self._ref_ln(x, scale, bias), atol=1e-5, rtol=1e-5
        )

    def test_plain_norm_grads(self):
        x, _, scale, bias = self._operands(seed=1)
        g = jax.random.normal(jax.random.PRNGKey(8), x.shape)

        def fused(x, s, b):
            return jnp.sum(fused_layer_norm(x, s, b, 1e-6, BT, True) * g)

        def ref(x, s, b):
            return jnp.sum(self._ref_ln(x, s, b) * g)

        got = jax.grad(fused, argnums=(0, 1, 2))(x, scale, bias)
        want = jax.grad(ref, argnums=(0, 1, 2))(x, scale, bias)
        for a, b_ in zip(got, want):
            np.testing.assert_allclose(a, b_, atol=2e-5, rtol=1e-4)

    def test_add_norm_returns_sum_and_matches_reference(self):
        x, r, scale, bias = self._operands(seed=2)
        y, s = fused_add_layer_norm(x, r, scale, bias, 1e-6, BT, True)
        np.testing.assert_allclose(s, x + r, atol=0, rtol=0)
        np.testing.assert_allclose(
            y, self._ref_ln(x + r, scale, bias), atol=1e-5, rtol=1e-5
        )

    def test_add_norm_grads_through_both_outputs(self):
        # Both outputs carry cotangents in the real block wiring: the
        # normed copy feeds the MLP, the sum continues the residual stream.
        x, r, scale, bias = self._operands(seed=3)
        gy = jax.random.normal(jax.random.PRNGKey(5), x.shape)
        gs = jax.random.normal(jax.random.PRNGKey(6), x.shape)

        def fused(x, r, s, b):
            y, summed = fused_add_layer_norm(x, r, s, b, 1e-6, BT, True)
            return jnp.sum(y * gy) + jnp.sum(summed * gs)

        def ref(x, r, s, b):
            return jnp.sum(self._ref_ln(x + r, s, b) * gy) + jnp.sum((x + r) * gs)

        got = jax.grad(fused, argnums=(0, 1, 2, 3))(x, r, scale, bias)
        want = jax.grad(ref, argnums=(0, 1, 2, 3))(x, r, scale, bias)
        for a, b_ in zip(got, want):
            np.testing.assert_allclose(a, b_, atol=2e-5, rtol=1e-4)


# --------------------------------------------------------------------------
# model wiring (adapter loss dispatch, fused_norm blocks, decode clones)
# -- the full parity fits are @slow: tier-1 keeps to pure units +
# interpret kernels (make verify-fusedce runs everything)
# --------------------------------------------------------------------------


class TestModelWiring:
    def _batch(self, vocab=256, seq=16):
        ks = jax.random.split(jax.random.PRNGKey(0), 2)
        ids = jax.random.randint(ks[0], (4, seq), 0, vocab)
        labels = jax.random.randint(ks[1], (4, seq), 0, vocab)
        return {
            "input_ids": ids,
            "labels": labels,
            "attention_mask": jnp.ones((4, seq), jnp.int32),
        }

    @pytest.mark.slow
    @pytest.mark.parametrize("tie", [True, False])
    def test_loss_components_parity_across_impls(self, tie):
        adapter = GPTAdapter()
        batch = self._batch()
        results = {}
        params = None
        for impl in LOSS_IMPLS:
            extra = {
                "loss_impl": impl,
                "fused_ce_block_t": WBT,
                "fused_ce_block_v": WBV,
                "pallas_interpret": True,
            }
            model = adapter.build_model(_gpt_cfg(extra, tie=tie))
            assert model.loss_impl == impl
            if params is None:
                params = model.init(jax.random.PRNGKey(0), batch["input_ids"])["params"]
            (_, ls), grads = jax.value_and_grad(
                lambda p: (
                    lambda c: (jnp.sum(c[0]), c[0])
                )(adapter.compute_loss_components(model, p, batch)),
                has_aux=True,
            )(params)
            results[impl] = (np.asarray(ls), jax.tree.leaves(jax.tree.map(np.asarray, grads)))
        for impl in ("chunked_ce", "fused_ce"):
            np.testing.assert_allclose(
                results[impl][0], results["dense"][0], atol=1e-4, rtol=1e-5
            )
            for a, b in zip(results[impl][1], results["dense"][1]):
                np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3)

    @pytest.mark.slow
    def test_fused_norm_param_tree_and_parity(self):
        adapter = GPTAdapter()
        batch = self._batch()
        plain = adapter.build_model(_gpt_cfg({}))
        fused = adapter.build_model(_gpt_cfg({"fused_norm": True, "pallas_interpret": True}))
        assert fused.fused_norm is True
        params = plain.init(jax.random.PRNGKey(0), batch["input_ids"])["params"]
        fused_params = fused.init(jax.random.PRNGKey(0), batch["input_ids"])["params"]
        # Checkpoint compatibility: identical tree (ln_1/ln_2 scale+bias).
        assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(
            fused_params
        )
        out_p = plain.apply({"params": params}, batch["input_ids"])
        out_f = fused.apply({"params": params}, batch["input_ids"])
        np.testing.assert_allclose(out_f, out_p, atol=1e-4, rtol=1e-4)
        g_p = jax.grad(
            lambda p: jnp.sum(plain.apply({"params": p}, batch["input_ids"]) ** 2)
        )(params)
        g_f = jax.grad(
            lambda p: jnp.sum(fused.apply({"params": p}, batch["input_ids"]) ** 2)
        )(params)
        for a, b in zip(jax.tree.leaves(g_p), jax.tree.leaves(g_f)):
            np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)

    def test_decode_clones_clear_fused_norm(self):
        model = GPTAdapter().build_model(
            _gpt_cfg({"fused_norm": True, "pallas_interpret": True})
        )
        assert model.for_decoding(8).fused_norm is False
        assert (
            model.for_paged_decoding(num_blocks=2, block_tokens=4).fused_norm is False
        )

    @pytest.mark.slow
    def test_moe_adapter_routes_fused_ce_through_hidden(self):
        from llmtrain_tpu.registry import get_model_adapter

        adapter = get_model_adapter("gpt_moe")()
        cfg = _gpt_cfg(
            {
                "loss_impl": "fused_ce",
                "pallas_interpret": True,
                "fused_ce_block_t": WBT,
                "fused_ce_block_v": WBV,
                "n_experts": 2,
            }
        )
        model = adapter.build_model(cfg)
        assert model.loss_impl == "fused_ce"
        batch = self._batch()
        params = adapter.init_params(model, cfg, jax.random.PRNGKey(0))
        ls, n = adapter.compute_loss_components(model, params, batch)
        assert np.all(np.isfinite(np.asarray(ls)))


# --------------------------------------------------------------------------
# config validation + capability fallbacks (resolution rules)
# --------------------------------------------------------------------------


class TestConfigResolution:
    def test_unknown_loss_impl_raises(self):
        with pytest.raises(ValueError, match="loss_impl 'typo' unknown"):
            GPTAdapter().build_model(_gpt_cfg({"loss_impl": "typo"}))

    def test_fused_ce_without_pallas_falls_back_warn_once(self, caplog):
        # CPU backend, no interpret: the fp8_supported() contract — degrade
        # to chunked_ce, warn ONCE per process.
        fused_ce_mod._FALLBACK_WARNED.discard("fused_ce")
        with caplog.at_level(logging.WARNING, logger="llmtrain_tpu.ops.fused_ce"):
            m1 = GPTAdapter().build_model(_gpt_cfg({"loss_impl": "fused_ce"}))
            m2 = GPTAdapter().build_model(_gpt_cfg({"loss_impl": "fused_ce"}))
        assert m1.loss_impl == "chunked_ce" and m2.loss_impl == "chunked_ce"
        warnings = [r for r in caplog.records if "falling back to chunked_ce" in r.message]
        assert len(warnings) == 1, "fallback must warn exactly once per process"

    def test_fused_norm_without_pallas_falls_back_warn_once(self, caplog):
        fused_norm_mod._FALLBACK_WARNED.discard("fused_norm")
        with caplog.at_level(logging.WARNING, logger="llmtrain_tpu.ops.fused_norm"):
            m1 = GPTAdapter().build_model(_gpt_cfg({"fused_norm": True}))
            m2 = GPTAdapter().build_model(_gpt_cfg({"fused_norm": True}))
        assert m1.fused_norm is False and m2.fused_norm is False
        warnings = [r for r in caplog.records if "unfused LayerNorm path" in r.message]
        assert len(warnings) == 1

    def test_interpret_knob_forces_fused_paths_on_cpu(self):
        m = GPTAdapter().build_model(
            _gpt_cfg({"loss_impl": "fused_ce", "fused_norm": True, "pallas_interpret": True})
        )
        assert m.loss_impl == "fused_ce" and m.fused_norm is True

    def test_auto_select_prefers_fused_only_with_pallas(self):
        # vocab >= ce_auto_vocab, loss_impl unset: chunked on a plain CPU
        # backend, fused when the interpret path is forced on.
        assert resolve_loss_impl(None, vocab_size=256, ce_auto_vocab=128) == "chunked_ce"
        assert (
            resolve_loss_impl(None, vocab_size=256, ce_auto_vocab=128, interpret=True)
            == "fused_ce"
        )
        assert resolve_loss_impl(None, vocab_size=64, ce_auto_vocab=128) == "dense"
        m = GPTAdapter().build_model(_gpt_cfg({"ce_auto_vocab": 128}))
        assert m.loss_impl == "chunked_ce"

    def test_resolve_fused_norm_passthrough(self):
        assert resolve_fused_norm(False) is False
        assert resolve_fused_norm(True, interpret=True) is True

    @pytest.mark.parametrize("key", ["fused_ce_block_t", "fused_ce_block_v"])
    def test_block_knobs_must_be_positive(self, key):
        with pytest.raises(ValueError, match=key):
            GPTAdapter().build_model(_gpt_cfg({key: 0}))

    def test_pipeline_adapter_rejects_fused_ce(self):
        from llmtrain_tpu.registry import get_model_adapter

        adapter = get_model_adapter("gpt_pipeline")()
        cfg = _gpt_cfg({"loss_impl": "fused_ce"})
        cfg = cfg.model_copy(
            update={"model": cfg.model.model_copy(update={"name": "gpt_pipeline"})}
        )
        with pytest.raises(ValueError, match="not supported with.*pipeline"):
            adapter.build_model(cfg)

    def test_llama_adapter_rejects_fused_norm(self):
        from llmtrain_tpu.registry import get_model_adapter

        adapter = get_model_adapter("llama")()
        cfg = _gpt_cfg({"fused_norm": True, "pallas_interpret": True})
        cfg = cfg.model_copy(
            update={"model": cfg.model.model_copy(update={"name": "llama"})}
        )
        with pytest.raises(ValueError, match="RMSNorm"):
            adapter.build_model(cfg)

    def test_llama_adapter_accepts_fused_ce(self):
        from llmtrain_tpu.registry import get_model_adapter

        adapter = get_model_adapter("llama")()
        cfg = _gpt_cfg(
            {
                "loss_impl": "fused_ce",
                "pallas_interpret": True,
                "fused_ce_block_t": WBT,
                "fused_ce_block_v": WBV,
            }
        )
        cfg = cfg.model_copy(
            update={"model": cfg.model.model_copy(update={"name": "llama"})}
        )
        model = adapter.build_model(cfg)
        assert model.loss_impl == "fused_ce"
        # The compute path itself is shared with the GPT adapter
        # (chunked_components_from_hidden); a loss evaluation here would
        # only re-pay the interpret cost, so tier-1 stops at the build.


# --------------------------------------------------------------------------
# fits + attribution pin (@slow, make verify-fusedce)
# --------------------------------------------------------------------------


def _fit_losses(extra: dict, steps: int = 5, vocab: int = 256):
    from llmtrain_tpu.training.optimizer import build_optimizer
    from llmtrain_tpu.training.train_step import create_train_state, make_train_step

    cfg = _gpt_cfg(extra, vocab=vocab)
    adapter = GPTAdapter()
    model = adapter.build_model(cfg)
    tx = build_optimizer(cfg.trainer)
    params = adapter.init_params(model, cfg, jax.random.key(0))
    state = create_train_state(params, tx)
    step_fn = jax.jit(
        make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
    )
    tokens = np.random.default_rng(0).integers(0, vocab, size=(1, 4, 16), dtype=np.int32)
    batch = {
        "input_ids": jnp.asarray(tokens),
        "labels": jnp.asarray(tokens),
        "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
    }
    rng = jax.random.key(0)
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state, batch, rng)
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


@pytest.mark.slow
class TestFusedFits:
    # Same band as the bench matrix's CE parity gate (_CE_PARITY_RTOL in
    # bench.py, docs/perf.md): identical math, fp reduction-order noise
    # amplified over the 5-step trajectory.
    CE_RTOL = 5e-4

    def test_fit_loss_parity_vs_dense(self):
        ref = _fit_losses({"loss_impl": "dense"})
        got = _fit_losses(
            {
                "loss_impl": "fused_ce",
                "pallas_interpret": True,
                "fused_ce_block_t": WBT,
                "fused_ce_block_v": WBV,
            }
        )
        max_rel = max(abs(q - f) / max(abs(f), 1e-6) for q, f in zip(got, ref))
        assert max_rel < self.CE_RTOL, f"fused_ce drifted {max_rel:.6f}"

    def test_checkpoint_resume_flips_loss_impl(self, tmp_path):
        """loss_impl is resume-mutable: a dense checkpoint trains on under
        fused_ce (and back) — the param tree is impl-independent."""
        from llmtrain_tpu.tracking import NullTracker
        from llmtrain_tpu.training import Trainer

        def fit(run_dir, extra, resume_from=None):
            run_dir.mkdir(parents=True, exist_ok=True)
            cfg = _gpt_cfg(
                extra,
                root=tmp_path,
                max_steps=6,
                log_every_steps=1,
                eval_every_steps=100,
                save_every_steps=3,
            )
            return Trainer(cfg, run_dir, NullTracker(), None).fit(
                resume_from=resume_from
            )

        fused_extra = {
            "loss_impl": "fused_ce",
            "pallas_interpret": True,
            "fused_ce_block_t": WBT,
            "fused_ce_block_v": WBV,
        }
        full = fit(tmp_path / "full", fused_extra)
        ckpt = tmp_path / "full" / "checkpoints" / "step_000003.ckpt"
        assert ckpt.exists()
        resumed = fit(tmp_path / "resume_fused", fused_extra, resume_from=str(ckpt))
        assert resumed.resumed_from_step == 3
        np.testing.assert_allclose(
            resumed.final_loss, full.final_loss, rtol=self.CE_RTOL
        )
        flipped = fit(
            tmp_path / "resume_dense", {"loss_impl": "dense"}, resume_from=str(ckpt)
        )
        assert flipped.resumed_from_step == 3
        # Same math across the boundary, so the flipped trajectory stays
        # inside the CE parity band of the unflipped one.
        np.testing.assert_allclose(
            flipped.final_loss, full.final_loss, rtol=self.CE_RTOL
        )

    def test_attribution_pin_no_logits_dot_under_fused(self):
        """Satellite pin: under fused_ce the aggregate ``dot``-class op
        bytes stay BELOW the [B,T,V] logits size (the tile dots live in
        the kernel's grid loop, counted once) — while dense CE provably
        materializes the full logits dot. Mirror of the chunked-CE pin in
        test_quant_train.py."""
        from llmtrain_tpu.telemetry import profiling
        from llmtrain_tpu.training.optimizer import build_optimizer
        from llmtrain_tpu.training.train_step import create_train_state, make_train_step

        B, T, V = 4, 64, 16384

        def dot_bytes(extra):
            cfg = _gpt_cfg(extra, vocab=V, seq=T)
            adapter = GPTAdapter()
            model = adapter.build_model(cfg)
            tx = build_optimizer(cfg.trainer)
            params = adapter.init_params(model, cfg, jax.random.key(0))
            state = create_train_state(params, tx)
            step_fn = jax.jit(
                make_train_step(adapter, model, tx, grad_accum_steps=1, use_dropout=False)
            )
            tokens = np.zeros((1, B, T), np.int32)
            batch = {
                "input_ids": jnp.asarray(tokens),
                "labels": jnp.asarray(tokens),
                "attention_mask": jnp.ones_like(jnp.asarray(tokens)),
            }
            prof = profiling.aot_profile(
                step_fn,
                (state, batch, jax.random.key(0)),
                name="fused_pin",
                peaks=profiling.resolve_peaks(),
            )
            assert prof is not None
            rows = {r["op"]: r for r in prof["top_ops"]}
            return model.loss_impl, rows.get("dot", {"bytes_accessed": 0.0})[
                "bytes_accessed"
            ]

        logits_bytes = B * T * V * 4
        impl_dense, dense_bytes = dot_bytes({"loss_impl": "dense"})
        impl_fused, fused_bytes = dot_bytes(
            {"loss_impl": "fused_ce", "pallas_interpret": True}
        )
        assert impl_dense == "dense" and impl_fused == "fused_ce"
        assert dense_bytes >= logits_bytes, "dense CE must materialize the logits dot"
        assert fused_bytes < logits_bytes, "fused CE leaked a full-vocab logits dot"
